"""Headline benchmark: ResNet-50 synthetic training throughput per chip.

Mirrors the reference's measurement vehicle
(``examples/pytorch_synthetic_benchmark.py:107-120``: img/sec mean over
timed iterations of a synthetic-data training loop).  Baseline for
``vs_baseline`` is the reference's published per-GPU throughput:
1656.82 images/sec on 16 Pascal GPUs => 103.55 img/sec/GPU
(``docs/benchmarks.rst:31-43``, BASELINE.md).

Also reports (in the same JSON object, under ``extra``):
  - ``mfu``: model-FLOPs utilization = achieved training FLOPs/s per
    chip over the chip's peak bf16 FLOPs/s (XLA cost analysis where
    available, analytic ResNet-50 estimate otherwise).
  - ``allreduce_gbs``: eager-path ``hvd.allreduce`` algorithmic
    bandwidth (GB/s) swept over payload sizes 1KB..256MB — the
    framework-overhead oracle that autotune tunes against (reference:
    ``docs/benchmarks.rst:31-43``).  Two legs: the legacy numpy
    round-trip (host -> device -> psum -> device -> host each call) and
    ``allreduce_gbs_device``, the device-resident path (jax.Array in /
    jax.Array out, one host sync at the end) — the honest measure of
    the eager plane once data lives on device.
  - ``allreduce_gbs_ring`` / ``allreduce_gbs_int8``: exact vs
    block-scaled int8 loopback-TCP worker ring.
  - ``allreduce_gbs_ring_pipelined``: the pipelined ring transfer
    engine (native wire dtypes + segment overlap + socket striping)
    swept over segment size and stripe count at 1/4/16/64 MB against
    the seed-era serial f64-wire ring (docs/benchmarks.md).
  - ``groups`` (``python bench.py --groups`` standalone): process-group
    overlap — two disjoint groups' allreduces serialized vs
    concurrently in flight on both the TCP ring plane and the public
    ``group=`` API, plus the DP x TP grid-vs-mesh transformer step
    cell (docs/groups.md).

Structure: running ``python bench.py`` starts a supervisor that retries
the actual measurement in a fresh subprocess (``--worker``), because a
transiently-held TPU poisons the jax backend cache for the whole
process.  Prints ONE JSON line at the end.
"""

import json
import os
import socket
import subprocess
import sys
import time

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16.0

# Peak bf16 matmul FLOPs/s by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,  # v6e
    "v6e": 918e12,
}

# Analytic fallback: ResNet-50 fwd ~4.09 GFLOPs/image @224x224; training
# (fwd + bwd) ~3x fwd.
_RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9


def _peak_flops_per_chip(device):
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return None


def _bench_resnet(devices, per_device_batch=None):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel._compat import shard_map
    import horovod_tpu as hvd
    from horovod_tpu.models import ResNet50
    from horovod_tpu.parallel import make_mesh

    n = len(devices)
    mesh = make_mesh({"hvd": n}, devices=devices)

    if per_device_batch is None:
        per_device_batch = int(os.environ.get("BENCH_BATCH", 64))
    batch = per_device_batch * n
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)

    rng = jax.random.PRNGKey(0)
    x_host = np.random.RandomState(0).randn(
        batch, 224, 224, 3).astype(np.float32)
    y_host = np.random.RandomState(1).randint(0, 1000, (batch,))

    variables = jax.jit(lambda r, x: model.init(r, x, train=True))(
        rng, jnp.zeros((1, 224, 224, 3), jnp.float32))
    params, batch_stats = variables["params"], variables["batch_stats"]

    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                   named_axes=("hvd",))
    opt_state = opt.init(params)

    def per_shard_step(params, batch_stats, opt_state, x, y):
        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(y, 1000)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))
            return loss, updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_stats = jax.tree.map(
            lambda s: jax.lax.pmean(s, "hvd"), new_stats)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_stats, new_opt_state, \
            jax.lax.pmean(loss, "hvd")

    step = jax.jit(shard_map(
        per_shard_step, mesh=mesh,
        in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P(), P()),
    ), donate_argnums=(0, 1, 2))

    sharded = NamedSharding(mesh, P("hvd"))
    x = jax.device_put(x_host, sharded)
    y = jax.device_put(y_host, sharded)

    # XLA's own FLOP count for the compiled step, if the backend
    # exposes it; analytic estimate otherwise.
    flops_per_step = None
    cost_info = None
    try:
        cost = step.lower(params, batch_stats, opt_state, x, y) \
            .compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops_per_step = float(cost.get("flops", 0.0)) or None
        cost_info = {k: float(v) for k, v in cost.items()
                     if k in ("flops", "bytes accessed",
                              "optimal_seconds", "transcendentals")}
    except Exception:
        pass
    if not flops_per_step:
        flops_per_step = _RESNET50_TRAIN_FLOPS_PER_IMG * batch
    _bench_resnet.last_cost_analysis = cost_info

    # device_get of the loss is the synchronization point: it cannot
    # complete before the step's program has finished on-device.
    # (block_until_ready alone can return early on relayed backends.)
    for _ in range(int(os.environ.get("BENCH_WARMUP", 3))):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y)
    float(jax.device_get(loss))

    iters = int(os.environ.get("BENCH_ITERS", 20))
    start = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y)
    float(jax.device_get(loss))
    elapsed = time.perf_counter() - start

    img_sec = batch * iters / elapsed
    img_sec_per_device = img_sec / n

    mfu = None
    peak = _peak_flops_per_chip(devices[0])
    if peak:
        achieved = flops_per_step * iters / elapsed / n
        mfu = achieved / peak
    return img_sec_per_device, mfu


def _bench_transformer(devices):
    """Transformer-LM headline: tokens/sec/chip + MFU for a fixed small
    LM (bf16, seq 2048) — the vehicle that exercises all three Pallas
    kernels (flash attention, fused LayerNorm, fused softmax-xent).
    Reference vehicle: ``examples/tensorflow2_synthetic_benchmark.py``
    (same timed-synthetic-loop methodology, LM config instead of
    ResNet).  Same ``device_get`` synchronization discipline as the
    ResNet bench."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel._compat import shard_map
    import horovod_tpu as hvd
    from horovod_tpu.models import Transformer, TransformerConfig, lm_loss
    from horovod_tpu.parallel import make_mesh

    n = len(devices)
    mesh = make_mesh({"hvd": n}, devices=devices)

    seq_len = int(os.environ.get("BENCH_LM_SEQ", 2048))
    per_device_batch = int(os.environ.get("BENCH_LM_BATCH", 8))
    d_model = int(os.environ.get("BENCH_LM_DMODEL", 1024))
    n_layers = int(os.environ.get("BENCH_LM_LAYERS", 8))
    vocab = int(os.environ.get("BENCH_LM_VOCAB", 32768))
    batch = per_device_batch * n

    cfg = TransformerConfig(
        vocab_size=vocab, n_layers=n_layers, d_model=d_model,
        n_heads=d_model // 128, d_ff=4 * d_model, max_len=seq_len,
        dtype=jnp.bfloat16,
        # BENCH_LM_REMAT=1 + a bigger BENCH_LM_BATCH: the MFU lever when
        # activations bound the per-chip batch
        remat=bool(int(os.environ.get("BENCH_LM_REMAT", "0"))))
    model = Transformer(cfg)
    tokens = np.random.RandomState(0).randint(
        0, vocab, (batch, seq_len))

    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, seq_len), jnp.int32))
    params = params["params"]
    opt = hvd.DistributedOptimizer(optax.adamw(1e-4), named_axes=("hvd",))
    opt_state = opt.init(params)

    def per_shard(params, opt_state, tokens):
        def loss_fn(p):
            return lm_loss(model.apply({"params": p}, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            jax.lax.pmean(loss, "hvd")

    step = jax.jit(shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P(), P("hvd")),
        out_specs=(P(), P(), P())), donate_argnums=(0, 1))

    td = jax.device_put(tokens, NamedSharding(mesh, P("hvd")))

    flops_per_step = None
    try:
        cost = step.lower(params, opt_state, td).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops_per_step = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    if not flops_per_step:
        # analytic: 6 * params * tokens per train step
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree_util.tree_leaves(params))
        flops_per_step = 6.0 * n_params * batch * seq_len

    for _ in range(int(os.environ.get("BENCH_WARMUP", 3))):
        params, opt_state, loss = step(params, opt_state, td)
    float(jax.device_get(loss))

    iters = int(os.environ.get("BENCH_LM_ITERS", 10))
    start = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, td)
    float(jax.device_get(loss))
    elapsed = time.perf_counter() - start

    tokens_sec_per_device = batch * seq_len * iters / elapsed / n
    mfu = None
    peak = _peak_flops_per_chip(devices[0])
    if peak:
        mfu = flops_per_step * iters / elapsed / n / peak
    return {
        "tokens_sec_per_chip": round(tokens_sec_per_device, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "config": {"d_model": d_model, "n_layers": n_layers,
                   "seq_len": seq_len, "vocab": vocab,
                   "batch_per_chip": per_device_batch, "dtype": "bf16"},
    }


def _bench_allreduce_bandwidth():
    """Eager hvd.allreduce algorithmic bandwidth over a size sweep."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    sizes = [1 << 10, 1 << 14, 1 << 18, 1 << 22, 1 << 24, 1 << 26,
             1 << 28]  # 1KB .. 256MB
    if os.environ.get("BENCH_CPU_FALLBACK"):
        sizes = sizes[:4]  # the one-core fallback skips the big sweep

    def sweep(rank=0):
        out = {}
        out_device = {}
        out_latency = {}
        for nbytes in sizes:
            n_elem = nbytes // 4
            x = np.ones((n_elem,), np.float32)
            # warmup; np.asarray forces the full eager round trip.
            warm = hvd.allreduce(x, name=f"bw_{nbytes}")
            np.asarray(warm)
            label = (f"{nbytes // (1 << 20)}MB" if nbytes >= (1 << 20)
                     else f"{nbytes // (1 << 10)}KB")
            if nbytes <= (1 << 16):
                # Resolution fix: at 1KB a fixed 10 iterations lands
                # under the 3-decimal rounding floor and reports 0.000
                # GB/s.  Calibrate the repeat count to a >=50ms timing
                # window, take the median of 5 windows, and report the
                # per-op latency in us alongside — the number that
                # actually characterizes this regime.
                t0 = time.perf_counter()
                np.asarray(hvd.allreduce(x, name=f"bw_{nbytes}"))
                once = time.perf_counter() - t0
                iters = min(2000, max(20, int(0.05 / max(once, 1e-7))))
                windows = []
                for _ in range(5):
                    start = time.perf_counter()
                    for _ in range(iters):
                        np.asarray(hvd.allreduce(x, name=f"bw_{nbytes}"))
                    windows.append(time.perf_counter() - start)
                elapsed = sorted(windows)[len(windows) // 2]
                out[label] = round(nbytes * iters / elapsed / 1e9, 4)
                out_latency[label] = round(elapsed / iters * 1e6, 1)
            else:
                iters = 10 if nbytes <= (1 << 22) else 3
                start = time.perf_counter()
                for _ in range(iters):
                    np.asarray(hvd.allreduce(x, name=f"bw_{nbytes}"))
                elapsed = time.perf_counter() - start
                out[label] = round(nbytes * iters / elapsed / 1e9, 3)

            # device-resident leg: the input is the warmup's on-device
            # result (jax.Array in -> jax.Array out, zero host copies);
            # Average keeps the chained values stable.  ONE host sync at
            # the end — the chain's data dependency means the final
            # np.asarray cannot complete before every step's device work
            # does (block_until_ready lies on the relayed backend).
            y = warm
            start = time.perf_counter()
            for i in range(iters):
                y = hvd.allreduce(y, name=f"bwdev_{nbytes}",
                                  op=hvd.Average)
            # 4-byte sync: the chain's data dependency forces every
            # step to finish, without charging a full D2H transfer to
            # the "zero host copies" leg
            float(y[0])
            elapsed = time.perf_counter() - start
            # 4 decimals: the calibrated small cells live well below
            # the 3-decimal floor that produced the 0.000 readings
            out_device[label] = round(nbytes * iters / elapsed / 1e9, 4)
        return out, out_device, out_latency

    if hvd.local_size() > 1:
        # multi-device (e.g. the CPU fallback): every logical rank needs
        # its own thread context; rank 0's timings are reported
        return basics.run_parallel(sweep)[0]
    return sweep()


def _bench_ring_allreduce_bandwidth(p=4):
    """Quantized TCP-ring sweep (ISSUE 1 acceptance: on payloads >= 4MB
    the int8 ring must move >= 2x the effective GB/s of the
    uncompressed ring on the same host — bytes-on-wire shrink ~4x, 2x
    end-to-end leaves room for quantize overhead).

    Same-host worker ring over real loopback TCP: ``p`` threads, one
    PeerService mailbox + RingPlane per rank, exactly the transport the
    multi-process tcp mode uses.  Effective GB/s = payload bytes x iters
    / wall time (algorithmic bandwidth, same convention as the eager
    sweep)."""
    import threading

    import numpy as np

    from horovod_tpu.ops.tcp_dataplane import PeerService, RingPlane
    from horovod_tpu.run.service import network

    key = b"0" * 32
    services = [PeerService(key) for _ in range(p)]

    def resolver(rank):
        return network.MuxClient([("127.0.0.1", services[rank].port)],
                                 key, timeout=60)

    planes = [RingPlane(r, services[r], resolver) for r in range(p)]
    ring_seq = [0]

    def run_all(data, compression):
        errs = []

        def run(r):
            try:
                planes[r].allreduce(
                    ring_seq[0], data[r], list(range(p)),
                    op_average=False, world_size=p, timeout=300,
                    compression=compression)
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=run, args=(r,))
                   for r in range(p)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    sizes = [1 << 20, 1 << 22, 1 << 24]
    if os.environ.get("BENCH_CPU_FALLBACK"):
        sizes = sizes[:2]
    out = {"ring": {}, "int8": {}, "speedup": {}}
    try:
        for nbytes in sizes:
            n_elem = nbytes // 4
            rng = np.random.RandomState(0)
            data = [rng.randn(n_elem).astype(np.float32)
                    for _ in range(p)]
            label = (f"{nbytes // (1 << 20)}MB" if nbytes >= (1 << 20)
                     else f"{nbytes // (1 << 10)}KB")
            for comp, bucket in (("none", "ring"), ("int8", "int8")):
                ring_seq[0] += 1
                run_all(data, comp)  # warmup (connection setup)
                iters = 3
                start = time.perf_counter()
                for _ in range(iters):
                    ring_seq[0] += 1
                    run_all(data, comp)
                elapsed = time.perf_counter() - start
                out[bucket][label] = round(
                    nbytes * iters / elapsed / 1e9, 3)
            out["speedup"][label] = round(
                out["int8"][label] / out["ring"][label], 2)
    finally:
        for plane in planes:
            plane.close()
        for svc in services:
            svc.shutdown()
    return out


def _ring_harness(p, segment_bytes, stripes, reconnect_budget=None):
    """In-process worker ring over real loopback TCP (the exact
    transport of multi-process tcp mode): one PeerService mailbox +
    RingPlane per rank, control MuxClients + bulk StripeClients.
    ``reconnect_budget`` arms the self-healing session layer explicitly
    (None = the env default, i.e. off) — the reconnect leg passes it as
    a ctor kwarg so the measurement never mutates process env."""
    from horovod_tpu.ops.tcp_dataplane import PeerService, RingPlane
    from horovod_tpu.run.service import network

    key = b"0" * 32
    services = [PeerService(key) for _ in range(p)]

    def resolver(rank):
        return network.MuxClient([("127.0.0.1", services[rank].port)],
                                 key, timeout=60,
                                 reconnect_budget=reconnect_budget)

    def resolve_bulk(rank):
        return network.StripeClient(
            [("127.0.0.1", services[rank].port)], key, timeout=60,
            reconnect_budget=reconnect_budget)

    planes = [RingPlane(r, services[r], resolver, resolve_bulk,
                        segment_bytes=segment_bytes, stripes=stripes)
              for r in range(p)]
    return services, planes


def _ring_run_all(planes, fn):
    import threading

    errs = []

    def run(r):
        try:
            fn(r)
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    threads = [threading.Thread(target=run, args=(r,))
               for r in range(len(planes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]


def _tcp_local_groups(p, local_size):
    """HVD_HIER_LOCAL_SIZE-style group plan over loopback planes:
    consecutive ``local_size`` chunks of the sorted rank list (the same
    rule the tcp coordinator's ``_plan_groups`` applies)."""
    return [list(range(lo, min(lo + local_size, p)))
            for lo in range(0, p, local_size)]


def _bench_tcp_scaling(ranks=(1, 2, 4, 8), payload_bytes=1 << 14,
                       local_size=2, compute_ms=3.0, step_iters=20,
                       step_windows=3, latency_bytes=1 << 14,
                       latency_iters=30):
    """TCP-plane schedule scaling probe (ISSUE 12): the 1/2/4/8-rank
    efficiency curve of a synthetic train step — a fixed device-compute
    stage plus one gradient-bucket allreduce over the real loopback
    transport — for the flat ring vs the two-level hierarchical
    schedule (groups = HVD_HIER_LOCAL_SIZE-style chunks of
    ``local_size``), plus a 16KB 8-rank latency cell (flat ring vs
    recursive halving/doubling, medians over timing windows).

    The compute stage is a GIL-free fixed-latency sleep, modeling
    accelerator-resident work: on a real TPU host XLA owns the chips
    and the host CPU runs only the data plane, so p ranks' compute
    phases overlap regardless of host core count (a host-side BLAS
    kernel would instead serialize on this boxes' core budget and
    measure the hardware, not the schedule).

    Efficiency = step(1) / step(p): per-rank work is constant (weak
    scaling), so everything lost below 1.0 is collective overhead, and
    the schedule with the shorter serialized-round critical path keeps
    the curve flatter — the flat ring pays 2(p-1) rounds and p·2(p-1)
    mailbox messages; the two-level plan pays (g-1) + 2(G-1) + 2
    rounds and roughly a third of the messages at p=8."""
    import numpy as np

    def run_steps(planes, p, schedule, groups, data, seq, iters,
                  compute=True):
        def fn(r):
            part = list(range(p))
            kw = dict(op_average=False, world_size=p, timeout=120)
            for i in range(iters):
                if compute:
                    time.sleep(compute_ms / 1e3)
                rid = seq[0] + i
                if schedule == "hierarchical":
                    planes[r].allreduce_hierarchical(
                        rid, data[r], part, groups, **kw)
                elif schedule == "rhd":
                    planes[r].allreduce_rhd(rid, data[r], part, **kw)
                else:
                    planes[r].allreduce(rid, data[r], part, **kw)

        start = time.perf_counter()
        _ring_run_all(planes, fn)
        elapsed = time.perf_counter() - start
        seq[0] += iters
        return elapsed / iters

    def median_steps(planes, p, schedule, groups, data, seq,
                     windows, iters, compute=True):
        run_steps(planes, p, schedule, groups, data, seq, 4,
                  compute=compute)  # warmup: connections + codepaths
        ws = [run_steps(planes, p, schedule, groups, data, seq, iters,
                        compute=compute) for _ in range(windows)]
        return sorted(ws)[len(ws) // 2]

    out = {"step_ms": {"flat_ring": {}, "hierarchical": {}},
           "efficiency": {"flat_ring": {}, "hierarchical": {}},
           "latency_us_16KB_8ranks": {},
           "payload_bytes": payload_bytes, "local_size": local_size,
           "compute_ms": compute_ms}
    base_ms = None
    for p in ranks:
        services, planes = _ring_harness(p, 1 << 20, 2)
        seq = [1]
        rng = np.random.RandomState(1)
        data = [rng.rand(payload_bytes // 4).astype(np.float32)
                for _ in range(p)]
        groups = _tcp_local_groups(p, local_size)
        try:
            flat_s = median_steps(planes, p, "flat_ring", None, data,
                                  seq, step_windows, step_iters)
            hier_s = median_steps(planes, p, "hierarchical", groups,
                                  data, seq, step_windows, step_iters)
            if base_ms is None:
                # p=1: both schedules degenerate to the same no-wire
                # reduction; flat_ring's number is the common base
                base_ms = flat_s * 1e3
            out["step_ms"]["flat_ring"][str(p)] = round(flat_s * 1e3, 3)
            out["step_ms"]["hierarchical"][str(p)] = round(
                hier_s * 1e3, 3)
            out["efficiency"]["flat_ring"][str(p)] = round(
                base_ms / (flat_s * 1e3), 3)
            out["efficiency"]["hierarchical"][str(p)] = round(
                base_ms / (hier_s * 1e3), 3)
            if p == 8:
                # latency cell: pure allreduce (no compute stage),
                # median of 3 windows of back-to-back ops
                lat = [rng.rand(latency_bytes // 4).astype(np.float32)
                       for _ in range(p)]
                for sched in ("flat_ring", "rhd"):
                    med = median_steps(planes, p, sched, None, lat,
                                       seq, 3, latency_iters,
                                       compute=False)
                    out["latency_us_16KB_8ranks"][sched] = round(
                        med * 1e6, 1)
        finally:
            for plane in planes:
                plane.close()
            for svc in services:
                svc.shutdown()
    return out


def _bench_group_overlap(p=8, group_size=4, payload_bytes=1 << 14,
                         compute_ms=20.0, iters=4, windows=3):
    """Process-group overlap probe (ISSUE 14, docs/groups.md): two
    disjoint groups' allreduces over the real loopback transport,
    serialized (group A's whole run completes before group B starts)
    vs concurrently in flight.  Each step is a GIL-free compute stage
    plus one group-ring allreduce — the model is a TP group on one
    half of the job and a DP bucket on the other half of the same
    step.  A data plane with any cross-group serialization point (a
    shared ring lock, coordinator head-of-line blocking, a global ring
    namespace) pins concurrent time to serial time; independent
    per-group planes push ``overlap_speedup`` toward 2x.

    The compute stage is a GIL-free fixed-latency sleep standing in
    for accelerator-resident work (same rationale as
    ``_bench_tcp_scaling``), and the payload is small so the step is
    compute-dominated: on a loaded/1-core CI host the host-CPU cost of
    the reduction itself cannot overlap, and making it dominant would
    measure this box's core count instead of whether the transport
    serializes the two groups."""
    import threading

    import numpy as np

    groups = [list(range(group_size)), list(range(group_size, p))]

    def run_ranks(ranks, fn):
        errs = []

        def run(r):
            try:
                fn(r)
            except Exception as exc:  # noqa: BLE001 — reraised below
                errs.append(exc)

        threads = [threading.Thread(target=run, args=(r,))
                   for r in ranks]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    services, planes = _ring_harness(p, 1 << 20, 2)
    seq = [1]
    rng = np.random.RandomState(2)
    data = [rng.rand(payload_bytes // 4).astype(np.float32)
            for _ in range(p)]

    def steps(gi, r, base_rid):
        grp = groups[gi]
        # disjoint rid namespaces per group, as the controller's
        # group-scoped ring-id allocator guarantees on the real path
        for i in range(iters):
            time.sleep(compute_ms / 1e3)
            planes[r].allreduce(base_rid + gi * 1_000_000 + i, data[r],
                                grp, op_average=False,
                                world_size=len(grp), timeout=120)

    def serial_run():
        base = seq[0]
        seq[0] += iters
        start = time.perf_counter()
        for gi, grp in enumerate(groups):
            run_ranks(grp, lambda r, gi=gi: steps(gi, r, base))
        return time.perf_counter() - start

    def concurrent_run():
        base = seq[0]
        seq[0] += iters
        start = time.perf_counter()
        run_ranks(range(p), lambda r: steps(
            0 if r in groups[0] else 1, r, base))
        return time.perf_counter() - start

    try:
        serial_run()      # warmup: connection setup + codepaths
        concurrent_run()
        serial_s = sorted(serial_run() for _ in range(windows))[
            windows // 2]
        conc_s = sorted(concurrent_run() for _ in range(windows))[
            windows // 2]
    finally:
        for plane in planes:
            plane.close()
        for svc in services:
            svc.shutdown()
    return {"serial_ms": round(serial_s * 1e3, 3),
            "concurrent_ms": round(conc_s * 1e3, 3),
            "overlap_speedup": round(serial_s / conc_s, 3),
            "groups": [len(g) for g in groups],
            "payload_bytes": payload_bytes, "compute_ms": compute_ms,
            "iters": iters}


def _bench_ring_pipelined_bandwidth(p=4):
    """Pipelined exact-ring sweep (ISSUE 3): effective GB/s of the
    native-dtype segmented/striped ring vs the seed-era serial
    f64-on-the-wire ring, across payload sizes and (segment, stripe)
    settings.  Effective GB/s = payload bytes x iters / wall time
    (algorithmic bandwidth, same convention as the eager sweep)."""
    import numpy as np

    sizes = [1 << 20, 1 << 22, 1 << 24, 1 << 26]
    combos = [("seg256KB_s2", 1 << 18, 2), ("seg1MB_s1", 1 << 20, 1),
              ("seg1MB_s2", 1 << 20, 2), ("seg1MB_s4", 1 << 20, 4),
              ("seg4MB_s2", 1 << 22, 2)]
    if os.environ.get("BENCH_CPU_FALLBACK"):
        sizes = sizes[:2]
        combos = combos[1:4]
    services, planes = _ring_harness(p, 1 << 20, max(c[2] for c in combos))
    ring_seq = [0]

    def measure(data, run_one, iters=3):
        ring_seq[0] += 1
        _ring_run_all(planes, lambda r: run_one(r, ring_seq[0]))  # warmup
        start = time.perf_counter()
        for _ in range(iters):
            ring_seq[0] += 1
            _ring_run_all(planes, lambda r: run_one(r, ring_seq[0]))
        return data[0].nbytes * iters / (time.perf_counter() - start) / 1e9

    out = {}
    try:
        for nbytes in sizes:
            rng = np.random.RandomState(0)
            data = [rng.randn(nbytes // 4).astype(np.float32)
                    for _ in range(p)]
            label = f"{nbytes // (1 << 20)}MB"
            row = {"seed": round(measure(data, lambda r, rid:
                   planes[r].allreduce_seed(
                       rid, data[r], list(range(p)), op_average=False,
                       world_size=p, timeout=300)), 3)}
            for name, seg, stripes in combos:
                for plane in planes:
                    plane.stripes = stripes
                row[name] = round(measure(data, lambda r, rid:
                    planes[r].allreduce(
                        rid, data[r], list(range(p)), op_average=False,
                        world_size=p, timeout=300,
                        segment_bytes=seg)), 3)
            best = max(v for k, v in row.items() if k != "seed")
            row["speedup_vs_seed"] = round(best / row["seed"], 2)
            out[label] = row
    finally:
        for plane in planes:
            plane.close()
        for svc in services:
            svc.shutdown()
    return out


def _bench_reconnect(heal_trials=5, p=2, nbytes=1 << 23, iters=5,
                     windows=3):
    """Self-healing transport leg (ISSUE 17, docs/fault_tolerance.md
    "connection blips vs dead peers"): two cells, one dict, all
    in-process loopback (no fault spec — the injector is process-global
    and would cut EVERY rank's links; the bench severs one client's
    socket directly, which is exactly what an injected RST does to it).

    - ``heal_ms``: wall time for a bulk StripeClient to notice a dead
      socket mid-stream, reconnect, resume its session and replay the
      unacked window — measured as the duration of the first
      ``post_bulk`` after the socket is shut down under it.  Median
      and max over ``heal_trials`` severs.
    - ``session_on/off_gbs``: pipelined-ring allreduce GB/s with the
      session layer armed (explicit ``reconnect_budget=`` ctor kwarg)
      vs off (budget None -> legacy byte-identical wire).  The
      steady-state seq/ack overhead must stay <= 2%
      (tests/test_bench_gate.py gates the ratio)."""
    import numpy as np

    from horovod_tpu.ops.tcp_dataplane import ChunkMsg, PeerService
    from horovod_tpu.run.service import network

    key = b"0" * 32

    # --- cell 1: heal latency of a severed bulk stripe
    svc = PeerService(key)
    client = network.StripeClient([("127.0.0.1", svc.port)], key,
                                  timeout=60, reconnect_budget=30.0)
    payload = b"\x5a" * (1 << 16)
    heals_ms = []
    healed_before = network.session_stats()["reconnects_healed"]
    try:
        for i in range(4):   # establish the session + a window
            client.post_bulk(ChunkMsg((0, i), 0, None), payload)
        for t in range(heal_trials):
            with client._lock:
                sock = client._sock
            sock.shutdown(socket.SHUT_RDWR)
            t0 = time.perf_counter()
            client.post_bulk(ChunkMsg((1, t), 0, None), payload)
            heals_ms.append((time.perf_counter() - t0) * 1e3)
    finally:
        client.close()
        svc.shutdown()
    healed = network.session_stats()["reconnects_healed"] - healed_before

    # --- cell 2: steady-state session overhead on the pipelined ring
    def ring_gbs(budget):
        services, planes = _ring_harness(p, 1 << 20, 2,
                                         reconnect_budget=budget)
        rng = np.random.RandomState(0)
        data = [rng.randn(nbytes // 4).astype(np.float32)
                for _ in range(p)]
        seq = [0]

        def one():
            seq[0] += 1
            rid = seq[0]
            _ring_run_all(planes, lambda r: planes[r].allreduce(
                rid, data[r], list(range(p)), op_average=False,
                world_size=p, timeout=300, segment_bytes=1 << 20))

        try:
            one()   # warmup (connections + session handshakes)
            samples = []
            for _ in range(windows):
                start = time.perf_counter()
                for _ in range(iters):
                    one()
                samples.append(
                    nbytes * iters / (time.perf_counter() - start) / 1e9)
            return sorted(samples)[len(samples) // 2]
        finally:
            for plane in planes:
                plane.close()
            for s in services:
                s.shutdown()

    off = ring_gbs(None)
    on = ring_gbs(30.0)
    return {
        "heal_ms_median": round(sorted(heals_ms)[len(heals_ms) // 2], 3),
        "heal_ms_max": round(max(heals_ms), 3),
        "heal_trials": heal_trials,
        "reconnects_healed": healed,
        "session_off_gbs": round(off, 3),
        "session_on_gbs": round(on, 3),
        "session_overhead_pct": round((1.0 - on / off) * 100.0, 2),
        "payload_bytes": nbytes, "ranks": p,
    }


def reconnect_worker():
    """Subprocess entry for the reconnect leg: pure loopback sockets +
    threads (no JAX backend), isolated because the session-layer heal
    counters and the fault injector are process-global state."""
    print(json.dumps(_bench_reconnect()))


def _run_reconnect(timeout=600):
    """Run the self-healing transport leg in a subprocess; returns the
    dict, or None when it failed."""
    line, _, _ = _run_worker_once(flag="--reconnect-worker",
                                  extra_env={"JAX_PLATFORMS": "cpu"},
                                  timeout=timeout)
    return None if line is None else json.loads(line)


def _bench_optimizer_state_bytes():
    """Per-rank optimizer-state footprint, replicated vs ZeRO-sharded
    (docs/sharding.md): adam state bytes for a flat parameter vector at
    world sizes 1/2/4/8.  The sharded figure is the LARGEST rank's
    (np.array_split gives the first ranks one extra element) and must
    scale ~1/N — the whole point of the sharded update."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.sharding.zero import zero_shard_layout

    n_params = int(os.environ.get("BENCH_ZERO_PARAMS", 1 << 20))
    params = jnp.zeros((n_params,), jnp.float32)
    opt = optax.adam(1e-3)

    def nbytes(state):
        return int(sum(np.asarray(l).nbytes
                       for l in jax.tree.leaves(state)))

    replicated = nbytes(opt.init(params))
    out = {"n_params": n_params, "replicated_bytes": replicated,
           "zero_max_rank_bytes": {}, "zero_ratio": {}}
    for world in (1, 2, 4, 8):
        per_rank = []
        for rank in range(world):
            _, off, cnt = zero_shard_layout(n_params, world, rank)
            per_rank.append(nbytes(opt.init(params[off:off + cnt])))
        out["zero_max_rank_bytes"][str(world)] = max(per_rank)
        out["zero_ratio"][str(world)] = round(
            max(per_rank) / replicated, 4)
    return out


def _bench_sharded_step():
    """ZeRO vs replicated eager step throughput on the current topology
    (docs/sharding.md): both legs run the SAME machinery
    (ZeroDistributedOptimizer; min_size forces the replicated fallback
    for the baseline), so the ratio isolates reduce-scatter + shard
    update + allgather vs allreduce + full update."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    n_params = int(os.environ.get("BENCH_ZERO_STEP_PARAMS", 1 << 18))
    steps = int(os.environ.get("BENCH_ZERO_STEPS", 10))

    def leg(min_size):
        def run(rank=0):
            params = jnp.zeros((n_params,), jnp.float32)
            opt = hvd.ZeroDistributedOptimizer(optax.adam(1e-3),
                                               min_size=min_size)
            state = opt.init(params)
            grad = jnp.ones((n_params,), jnp.float32)
            upd, state = opt.update(grad, state, params)  # warmup
            p = optax.apply_updates(params, upd)
            float(np.asarray(p[0]))
            start = time.perf_counter()
            s = state
            for _ in range(steps):
                upd, s = opt.update(grad, s, p)
                p = optax.apply_updates(p, upd)
            float(np.asarray(p[0]))
            return time.perf_counter() - start

        if hvd.local_size() > 1:
            return basics.run_parallel(run)[0]
        return run()

    replicated_s = leg(min_size=n_params + 1)   # forces fallback
    sharded_s = leg(min_size=1)
    return {
        "n_params": n_params, "steps": steps,
        "replicated_steps_per_s": round(steps / replicated_s, 2),
        "sharded_steps_per_s": round(steps / sharded_s, 2),
        "sharded_vs_replicated": round(replicated_s / sharded_s, 3),
    }


def sharding_worker():
    """Sharding legs (docs/sharding.md), CPU-mesh by default like the
    scaling harness; runs unchanged on real chips.  Prints one JSON
    object (not the driver headline line)."""
    import jax

    if not os.environ.get("BENCH_SHARDING_REAL"):
        jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd

    hvd.init()
    out = {
        "optimizer_state_bytes": _bench_optimizer_state_bytes(),
        "sharded_step": _bench_sharded_step(),
        "n_ranks": hvd.size(),
        "platform": jax.devices()[0].platform,
    }
    hvd.shutdown()
    print(json.dumps(out))


def _run_sharding(timeout=600):
    """Run the sharding legs in a CPU-forced subprocess; returns the
    parsed dict or None."""
    line, _, _ = _run_worker_once(
        flag="--sharding-worker",
        extra_env={"XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                                 " --xla_force_host_platform_device_count=4"
                                 ).strip()},
        timeout=timeout)
    if line is None:
        return None
    return json.loads(line)


def checkpoint_bench():
    """Durable-checkpoint overhead leg (docs/checkpoint.md): time N
    elastic commits bare vs with the background writer attached at
    interval 1 (the worst case), plus the resume (read + digest-verify
    + reassemble) latency.  Single process on the CPU mesh — the writer
    thread and the file formats are platform-independent."""
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from horovod_tpu.checkpoint import CheckpointManager
    from horovod_tpu.elastic import State

    n_params = int(os.environ.get("BENCH_CKPT_PARAMS", 1 << 20))
    steps = int(os.environ.get("BENCH_CKPT_STEPS", 20))

    def run(manager):
        params = np.zeros((n_params,), np.float32)
        opt = {"m": np.zeros((n_params,), np.float32),
               "count": np.zeros((), np.int32)}
        state = State(params=params, optimizer_state=opt)
        if manager is not None:
            state.attach_checkpoint(manager)
        start = time.perf_counter()
        for _ in range(steps):
            state.params = state.params + 1.0
            state.step += 1
            state.commit()
        elapsed = time.perf_counter() - start
        if manager is not None:
            manager.wait()
        return elapsed, state

    bare_s, _ = run(None)
    with tempfile.TemporaryDirectory() as d:
        manager = CheckpointManager(d, interval_steps=1, keep=2)
        ckpt_s, state = run(manager)
        manager.wait()
        fresh = State(params=np.zeros((n_params,), np.float32),
                      optimizer_state={"m": np.zeros((n_params,),
                                                     np.float32),
                                       "count": np.zeros((), np.int32)})
        t0 = time.perf_counter()
        resumed = manager.restore_latest(fresh)
        resume_s = time.perf_counter() - t0
        manager.close()
    out = {
        "n_params": n_params, "steps": steps,
        "commit_steps_per_s": round(steps / bare_s, 2),
        "ckpt_steps_per_s": round(steps / ckpt_s, 2),
        "ckpt_overhead": round(ckpt_s / bare_s, 3),
        "resume_s": round(resume_s, 4),
        "resumed_step": None if resumed is None else resumed[0],
    }
    print(json.dumps(out))
    return 0 if resumed is not None and fresh.step == state.step else 1


def worker():
    # watchdog: a held/unreachable TPU can make backend init BLOCK
    # (not fail); bail out so the supervisor's retry loop stays snappy
    import threading

    ready = threading.Event()

    def watchdog():
        if not ready.wait(timeout=240):
            sys.stderr.write("bench worker: backend init hung >240s\n")
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()

    import jax

    if os.environ.get("BENCH_CPU_FALLBACK"):
        # the axon plugin ignores JAX_PLATFORMS; pin programmatically
        jax.config.update("jax_platforms", "cpu")

    devices = jax.devices()
    ready.set()
    platform = devices[0].platform

    if os.environ.get("BENCH_CPU_FALLBACK"):
        # keep the fallback fast: tiny LM so the methodology still runs
        os.environ.setdefault("BENCH_LM_SEQ", "256")
        os.environ.setdefault("BENCH_LM_BATCH", "1")
        os.environ.setdefault("BENCH_LM_DMODEL", "256")
        os.environ.setdefault("BENCH_LM_LAYERS", "2")
        os.environ.setdefault("BENCH_LM_VOCAB", "1024")
        os.environ.setdefault("BENCH_LM_ITERS", "2")

    import horovod_tpu as hvd
    hvd.init()

    # leg watchdog: the relay can die MID-RUN (round 4 lost a kernels
    # leg that way) — once the headline exists, a stalled later leg
    # emits the partial record instead of losing everything to the
    # supervisor's subprocess timeout
    state = {"last": time.time(), "record": None}
    # one lock serializes the watchdog's partial emit against the main
    # thread's final print: without it either a complete record gets a
    # partial-labeled duplicate (watchdog fires during the final print)
    # or a blocked final print gets truncated by os._exit
    print_lock = threading.Lock()

    def leg_watchdog():
        limit = float(os.environ.get("BENCH_LEG_TIMEOUT", 600))
        while True:
            time.sleep(15)
            if state["record"] is None:
                # pre-headline: first compiles legitimately take
                # minutes (relay/loaded host); the supervisor's
                # subprocess timeout governs this phase
                continue
            if time.time() - state["last"] <= limit:
                continue
            with print_lock:
                if state.get("printed"):
                    # all legs done and the record fully printed; only
                    # shutdown is stalling — exit clean without
                    # relabeling a complete measurement as partial
                    os._exit(0)
                sys.stderr.write(
                    "bench worker: leg stalled; emitting partial\n")
                state["record"]["extra"]["partial"] = True
                print(json.dumps(state["record"]), flush=True)
                os._exit(0)

    threading.Thread(target=leg_watchdog, daemon=True).start()

    img_sec_per_device, mfu = _bench_resnet(devices)
    record = {
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(img_sec_per_device, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            img_sec_per_device / BASELINE_IMG_SEC_PER_DEVICE, 3),
        "extra": {
            "platform": platform,
            "n_devices": len(devices),
            "mfu": round(mfu, 4) if mfu is not None else None,
            "resnet_bs128": None,
            "transformer": None,
            "allreduce_gbs": None,
            "allreduce_gbs_device": None,
            "allreduce_latency_us": None,
            "allreduce_gbs_ring": None,
            "allreduce_gbs_int8": None,
            "allreduce_int8_speedup": None,
            "allreduce_gbs_ring_pipelined": None,
        },
    }
    state["record"] = record
    state["last"] = time.time()

    if platform == "tpu" and not os.environ.get("BENCH_SKIP_BS128"):
        # MXU occupancy leg: bs=64/chip is the reference-parity config
        # (headline); bs=128 fills the late small-spatial stages better
        try:
            v, m = _bench_resnet(devices, per_device_batch=128)
            record["extra"]["resnet_bs128"] = {
                "img_sec_per_chip": round(v, 2),
                "mfu": round(m, 4) if m is not None else None}
        except Exception as exc:  # noqa: BLE001 — OOM etc.: keep headline
            sys.stderr.write(f"bs128 leg failed: {exc!r}\n")
        state["last"] = time.time()
    if os.environ.get("BENCH_TEST_HANG_S"):
        # test hook: simulate a relay death between legs so the
        # partial-emit path is exercisable (tests/test_bench_gate.py)
        time.sleep(float(os.environ["BENCH_TEST_HANG_S"]))
    try:
        record["extra"]["transformer"] = _bench_transformer(devices)
    except Exception as exc:  # never lose the ResNet number to the LM leg
        sys.stderr.write(f"transformer bench failed: {exc!r}\n")
    state["last"] = time.time()
    gbs, gbs_device, lat_us = _bench_allreduce_bandwidth()
    record["extra"]["allreduce_gbs"] = gbs
    record["extra"]["allreduce_gbs_device"] = gbs_device
    record["extra"]["allreduce_latency_us"] = lat_us
    state["last"] = time.time()
    try:
        ring = _bench_ring_allreduce_bandwidth()
        record["extra"]["allreduce_gbs_ring"] = ring["ring"]
        record["extra"]["allreduce_gbs_int8"] = ring["int8"]
        record["extra"]["allreduce_int8_speedup"] = ring["speedup"]
    except Exception as exc:  # never lose the headline to the ring leg
        sys.stderr.write(f"int8 ring bench failed: {exc!r}\n")
    state["last"] = time.time()
    try:
        record["extra"]["allreduce_gbs_ring_pipelined"] = \
            _bench_ring_pipelined_bandwidth()
    except Exception as exc:  # never lose the headline to this leg
        sys.stderr.write(f"pipelined ring bench failed: {exc!r}\n")
    state["last"] = time.time()
    # print BEFORE shutdown: a shutdown stall (relay death at the
    # barrier) must not cost a complete measurement.  Under the lock,
    # so the watchdog can neither emit a partial-labeled duplicate nor
    # os._exit mid-print if this print blocks on a full pipe
    with print_lock:
        print(json.dumps(record), flush=True)
        state["printed"] = True
    hvd.shutdown()


def scaling_worker():
    """Scaling-efficiency harness (BASELINE.md north star: the
    reference's 8->64-GPU 90% scaling efficiency, ``docs/benchmarks.rst``).
    Runs on the virtual CPU mesh today (mesh sizes 1/2/4/8) and on real
    multi-chip unchanged when pod hardware exists: for each mesh size it
    measures the fused-SPMD allreduce bus bandwidth and a synthetic
    per-shard train step at FIXED per-device batch (weak scaling), and
    reports efficiency = step_ms(1) / step_ms(n) — 1.0 is perfect.

    Prints one JSON object (not the driver headline line)."""
    import jax

    if not os.environ.get("BENCH_SCALING_REAL"):
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel._compat import shard_map
    import horovod_tpu as hvd
    from horovod_tpu.models import MLP
    from horovod_tpu.parallel import make_mesh

    all_devices = jax.devices()
    sizes = [n for n in (1, 2, 4, 8, 16, 32, 64)
             if n <= len(all_devices)]
    per_device_batch = int(os.environ.get("BENCH_SCALING_BATCH", 8))
    ar_bytes = int(os.environ.get("BENCH_SCALING_AR_BYTES", 4 << 20))

    results = {}
    for n in sizes:
        devices = all_devices[:n]
        mesh = make_mesh({"hvd": n}, devices=devices)
        sharded = NamedSharding(mesh, P("hvd"))
        replicated = NamedSharding(mesh, P())

        # -- fused-SPMD allreduce (the DistributedOptimizer hot path):
        # one jitted psum program over the mesh
        x = jax.device_put(
            np.ones((n, ar_bytes // 4), np.float32),
            NamedSharding(mesh, P("hvd", None)))

        def ar_shard(x):
            return jax.lax.psum(x, "hvd")

        ar = jax.jit(shard_map(
            ar_shard, mesh=mesh, in_specs=P("hvd", None),
            out_specs=P("hvd", None)))
        out = ar(x)
        float(jax.device_get(out[0, 0]))  # warmup + sync
        iters = 20
        start = time.perf_counter()
        for _ in range(iters):
            out = ar(x)
        float(jax.device_get(out[0, 0]))
        elapsed = time.perf_counter() - start
        # bus bandwidth convention (NCCL tests): 2*(n-1)/n * bytes / time
        algo_gbs = ar_bytes * iters / elapsed / 1e9
        bus_gbs = algo_gbs * (2 * (n - 1) / n) if n > 1 else algo_gbs

        # -- synthetic train step, fixed per-device batch (weak scaling)
        model = MLP(features=(256, 128, 10))
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 784), jnp.float32))["params"]
        opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                       named_axes=("hvd",))
        opt_state = opt.init(params)
        xb = jax.device_put(
            np.random.RandomState(0).randn(
                per_device_batch * n, 784).astype(np.float32), sharded)
        yb = jax.device_put(
            np.random.RandomState(1).randint(
                0, 10, (per_device_batch * n,)), sharded)

        def per_shard_step(params, opt_state, xb, yb):
            def loss_fn(p):
                logits = model.apply({"params": p}, xb)
                one_hot = jax.nn.one_hot(yb, 10)
                return -jnp.mean(jnp.sum(
                    jax.nn.log_softmax(logits) * one_hot, axis=-1))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, \
                jax.lax.pmean(loss, "hvd")

        step = jax.jit(shard_map(
            per_shard_step, mesh=mesh,
            in_specs=(P(), P(), P("hvd"), P("hvd")),
            out_specs=(P(), P(), P())), donate_argnums=(0, 1))
        params = jax.device_put(params, replicated)
        opt_state = jax.device_put(opt_state, replicated)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, xb, yb)
        float(jax.device_get(loss))
        iters = 30
        start = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, xb, yb)
        float(jax.device_get(loss))
        step_ms = (time.perf_counter() - start) / iters * 1e3

        results[str(n)] = {"allreduce_bus_gbs": round(bus_gbs, 3),
                           "step_ms": round(step_ms, 3)}

    base = results[str(sizes[0])]["step_ms"]
    for n in sizes:
        results[str(n)]["efficiency"] = round(
            base / results[str(n)]["step_ms"], 3)
    print(json.dumps({"scaling": results,
                      "platform": all_devices[0].platform,
                      "per_device_batch": per_device_batch}))


def groups_worker():
    """Process-group legs (ISSUE 14, docs/groups.md) on the virtual
    CPU mesh (real chips unchanged: unset the CPU pin).  Two cells,
    one JSON object:

    - ``api_overlap``: two disjoint groups' allreduces through the
      REAL public API (``hvd.allreduce(..., group=...)``) from
      per-rank threads, a serialized pass vs a concurrent pass, with
      the registry's own ``max_concurrent_groups`` gauge snapshotted
      after each — the serialized pass must read 1 and the concurrent
      pass >= 2, which is the "verifiably in flight at once" evidence
      (asserted, not assumed).
    - ``dp_tp_step``: transformer train-step time with params sharded
      through ``hvd.grid(dp=2, tp=4)`` vs the explicit mesh — the
      grid resolves to the same device mesh, so the ratio is a
      regression tripwire for the grid-as-mesh path."""
    import jax

    if not os.environ.get("BENCH_GROUPS_REAL"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import groups as groups_mod
    from horovod_tpu.common import basics

    devices = jax.devices()
    hvd.init()
    n = hvd.size()
    half = n // 2
    g0 = hvd.new_group(list(range(half)), name="bench.g0")
    g1 = hvd.new_group(list(range(half, n)), name="bench.g1")
    n_elem = int(os.environ.get("BENCH_GROUPS_BYTES", 1 << 14)) // 4
    iters = int(os.environ.get("BENCH_GROUPS_ITERS", 4))
    compute_ms = float(os.environ.get("BENCH_GROUPS_COMPUTE_MS", 15.0))

    def member_steps(r, grp, tag):
        x = jnp.ones((n_elem,), jnp.float32) * (r + 1)
        for i in range(iters):
            time.sleep(compute_ms / 1e3)
            hvd.allreduce(x, op=hvd.Sum, name=f"bench.{tag}.{i}",
                          group=grp)

    def serial_pass(tag):
        start = time.perf_counter()
        for grp in (g0, g1):
            basics.run_parallel(
                lambda r, grp=grp: member_steps(r, grp, tag)
                if r in grp else None)
        return time.perf_counter() - start

    def concurrent_pass(tag):
        start = time.perf_counter()
        basics.run_parallel(
            lambda r: member_steps(r, g0 if r in g0 else g1, tag))
        return time.perf_counter() - start

    serial_pass("warm.s")
    serial_s = serial_pass("timed.s")
    inflight_serial = groups_mod.stats()["max_concurrent_groups"]
    concurrent_pass("warm.c")
    conc_s = concurrent_pass("timed.c")
    inflight_conc = groups_mod.stats()["max_concurrent_groups"]
    api_overlap = {
        "serial_ms": round(serial_s * 1e3, 3),
        "concurrent_ms": round(conc_s * 1e3, 3),
        "overlap_speedup": round(serial_s / conc_s, 3),
        "max_concurrent_groups_serialized": inflight_serial,
        "max_concurrent_groups": inflight_conc,
        "iters": iters, "payload_bytes": n_elem * 4,
        "compute_ms": compute_ms,
    }

    # -- DP x TP transformer step through the grid vs the explicit mesh
    import optax

    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel import make_mesh, shard_params

    cfg = TransformerConfig(
        vocab_size=int(os.environ.get("BENCH_GROUPS_VOCAB", 512)),
        n_layers=2, d_model=128, n_heads=8, d_ff=256, max_len=64,
        dtype=jnp.float32)
    model = Transformer(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 32)))
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    opt = optax.sgd(0.01)

    @jax.jit
    def step(p, opt_state, toks):
        def loss_fn(p):
            logits = model.apply({"params": p}, toks)
            one_hot = jax.nn.one_hot(toks, cfg.vocab_size)
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * one_hot, axis=-1))

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, opt_state = opt.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    def step_ms(mesh_or_grid):
        p = shard_params(params, mesh_or_grid)
        opt_state = opt.init(p)
        p, opt_state, loss = step(p, opt_state, tokens)
        float(jax.device_get(loss))  # compile + sync
        step_iters = int(os.environ.get("BENCH_GROUPS_STEP_ITERS", 6))
        start = time.perf_counter()
        for _ in range(step_iters):
            p, opt_state, loss = step(p, opt_state, tokens)
        float(jax.device_get(loss))
        return (time.perf_counter() - start) / step_iters * 1e3

    grd = hvd.grid(dp=2, tp=4)
    grid_ms = step_ms(grd)
    mesh_ms = step_ms(make_mesh({"dp": 2, "tp": 4}))
    dp_tp_step = {"grid_step_ms": round(grid_ms, 3),
                  "mesh_step_ms": round(mesh_ms, 3),
                  "grid_vs_mesh": round(grid_ms / mesh_ms, 3)}

    print(json.dumps({"api_overlap": api_overlap,
                      "dp_tp_step": dp_tp_step,
                      "platform": devices[0].platform}))
    hvd.shutdown()


def _run_groups(timeout=600):
    """Run the process-group harness in a CPU-forced subprocess, then
    attach the TCP-plane overlap probe (in-process: pure loopback
    sockets + threads, no JAX backend involved); returns the merged
    dict, or None when both legs failed."""
    line, _, _ = _run_worker_once(
        flag="--groups-worker",
        extra_env={"XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                                 " --xla_force_host_platform_device_count=8"
                                 ).strip()},
        timeout=timeout)
    result = {} if line is None else json.loads(line)
    try:
        result["tcp_plane_overlap"] = _bench_group_overlap()
    except Exception as exc:  # noqa: BLE001 — keep the XLA cells
        sys.stderr.write(f"tcp-plane group overlap probe failed: "
                         f"{exc!r}\n")
    return result or None


def _bench_pipeline(devices, steps=None, batch=None, img=None):
    """Input-pipeline overlap measurement: the same host-fed training
    loop with and without ``prefetch_to_device``.  The copy cost the
    prefetcher hides is the host→device batch transfer — negligible on
    the CPU mesh (gain ≈ 1.0 expected), large through the TPU relay,
    where round-2 notes measured the transfer dominating eager-path
    time.  Returns {img_sec_plain, img_sec_prefetch, overlap_gain}."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel import make_mesh
    from horovod_tpu.parallel._compat import shard_map
    from horovod_tpu.utils.data import prefetch_to_device

    n = len(devices)
    on_tpu = devices[0].platform == "tpu"
    # CPU-mesh smoke shapes vs real-chip shapes: the conv at full
    # ImageNet size is minutes per step on 8 virtual CPU devices
    steps = steps or (48 if on_tpu else 10)
    batch = batch or (32 if on_tpu else 4)
    img = img or (224 if on_tpu else 64)
    mesh = make_mesh({"hvd": n}, devices=devices)
    sharded = NamedSharding(mesh, P("hvd"))
    global_batch = batch * n

    # small conv stack: enough compute to overlap against, small enough
    # that the [B,224,224,3] host->device copy is a real fraction
    key = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(key, (3, 3, 3, 16), jnp.bfloat16) * 0.1,
        "w2": jax.random.normal(key, (3, 3, 16, 16), jnp.bfloat16) * 0.1,
    }

    def per_shard(params, x):
        h = jax.lax.conv_general_dilated(
            x.astype(jnp.bfloat16), params["w1"], (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jax.nn.relu(h)
        h = jax.lax.conv_general_dilated(
            h, params["w2"], (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jax.lax.pmean(jnp.mean(h.astype(jnp.float32)), "hvd")

    fwd = jax.jit(shard_map(per_shard, mesh=mesh,
                            in_specs=(P(), P("hvd")), out_specs=P()))

    rng = np.random.RandomState(0)
    host_batches = [rng.rand(global_batch, img, img, 3)
                    .astype(np.float32) for _ in range(8)]

    def batches():
        for i in range(steps):
            yield host_batches[i % len(host_batches)]

    # warmup compiles
    out = fwd(params, jax.device_put(host_batches[0], sharded))
    float(jax.device_get(out))

    t0 = time.perf_counter()
    for x in batches():
        out = fwd(params, jax.device_put(x, sharded))
    plain_s = _sync_elapsed(t0, out)

    t0 = time.perf_counter()
    for xd in prefetch_to_device(batches(), size=2, sharding=sharded):
        out = fwd(params, xd)
    prefetch_s = _sync_elapsed(t0, out)

    imgs = steps * global_batch
    return {"img_sec_plain": round(imgs / plain_s, 1),
            "img_sec_prefetch": round(imgs / prefetch_s, 1),
            "overlap_gain": round(plain_s / prefetch_s, 3),
            "batch_global": global_batch, "steps": steps, "img": img}


def _sync_elapsed(t0, out):
    """Elapsed seconds synchronized through a device_get of the final
    step's output (BENCH_NOTES: block_until_ready returns early on the
    relayed backend)."""
    import jax

    float(jax.device_get(out))
    return time.perf_counter() - t0


def pipeline_worker():
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu" or \
            os.environ.get("BENCH_CPU_FALLBACK"):
        # the axon plugin ignores JAX_PLATFORMS; pin programmatically
        # (a down relay otherwise BLOCKS jax.devices() forever) — and
        # give the CPU smoke a real 8-device mesh like the scaling leg
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    print(json.dumps({"pipeline": _bench_pipeline(devices),
                      "platform": devices[0].platform}))


def _run_scaling(timeout=600):
    """Run the scaling harness in a CPU-forced subprocess, then attach
    the TCP-plane schedule probe (runs in-process: pure loopback
    sockets + threads, no JAX backend involved); returns the merged
    dict, or None when both legs failed."""
    line, _, _ = _run_worker_once(
        flag="--scaling-worker",
        extra_env={"XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                                 " --xla_force_host_platform_device_count=8"
                                 ).strip()},
        timeout=timeout)
    result = {} if line is None else json.loads(line)
    try:
        result["tcp_plane"] = _bench_tcp_scaling()
    except Exception as exc:  # noqa: BLE001 — keep the XLA numbers
        sys.stderr.write(f"tcp-plane scaling probe failed: {exc!r}\n")
    return result or None


def _run_worker_once(extra_env=None, timeout=900, flag="--worker"):
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(
                       os.path.abspath(__file__)), ".jax_cache"))
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), flag],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=timeout)
    except subprocess.TimeoutExpired as exc:
        out = (exc.stdout or b"").decode("utf-8", "replace") \
            if isinstance(exc.stdout, bytes) else (exc.stdout or "")
        return None, out, "timeout"
    if proc.returncode == 0:
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    continue  # brace-delimited log noise, keep looking
                return line, proc.stdout, None
    return None, proc.stdout, f"rc={proc.returncode}"


# Most recent successful real-TPU measurement (update when a new
# on-chip run lands; history in BENCH_NOTES.md).
_LAST_TPU_MEASUREMENT = {
    "date": "2026-07-31",
    "resnet50_synthetic_img_sec_per_chip": 2105.75,
    "vs_baseline": 20.335,
    "mfu": 0.2556,
}
_CPU_FALLBACK_BATCH = 2


def _last_tpu_measurement():
    """Newest driver-verifiable banked real-TPU bench (bin/bank-tpu
    output), falling back to the hardcoded last-known figures."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))

    def mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    for path in sorted(glob.glob(os.path.join(here, "BANKED_TPU_*.json")),
                       key=mtime, reverse=True):
        try:
            with open(path) as f:
                d = json.load(f)
            b = d.get("bench") or {}
            if (b.get("extra") or {}).get("platform") == "tpu":
                # banked_at_utc is stamped when the bench leg itself
                # ran; the file-level date_utc is rewritten on every
                # bank-tpu invocation (resume re-stamps it)
                date = (b.get("banked_at_utc")
                        or d.get("date_utc", ""))[:10] \
                    or _LAST_TPU_MEASUREMENT["date"]
                return {
                    "date": date,
                    "resnet50_synthetic_img_sec_per_chip": b["value"],
                    "vs_baseline": b["vs_baseline"],
                    "mfu": b["extra"].get("mfu"),
                    "transformer": b["extra"].get("transformer"),
                    "source": os.path.basename(path),
                }
        except Exception:  # noqa: BLE001 — fallback must never crash
            continue
    return dict(_LAST_TPU_MEASUREMENT)


def _cpu_fallback():
    """All TPU attempts failed (observed failure mode: the axon relay
    blocks backend init for hours — see BENCH_NOTES.md).  Emit an
    HONEST, clearly-labeled measurement on the 8-device virtual CPU
    mesh rather than nothing: the methodology is identical, the number
    is a CPU number, and the extra block says so and carries the last
    real-TPU measurement for context."""
    line, out, err = _run_worker_once(
        extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "BENCH_CPU_FALLBACK": "1",
            "BENCH_BATCH": str(_CPU_FALLBACK_BATCH),
            "BENCH_ITERS": "2",
            "BENCH_WARMUP": "1",
        }, timeout=1800)
    if line is None:
        sys.stderr.write(f"cpu fallback also failed ({err}); "
                         f"tail:\n{out[-2000:]}\n")
        return None
    record = json.loads(line)
    record.setdefault("extra", {})
    record["extra"]["platform"] = "cpu-fallback"
    record["extra"]["cpu_fallback_batch_per_device"] = _CPU_FALLBACK_BATCH
    m = _last_tpu_measurement()
    record["extra"]["note"] = (
        "TPU relay unreachable after all retry attempts; this is a "
        "virtual 8-device CPU-mesh run of the same benchmark. Last "
        f"real-TPU measurement ({m['date']}, see BENCH_NOTES.md): "
        f"{m['resnet50_synthetic_img_sec_per_chip']} img/sec/chip, "
        f"{m['vs_baseline']:.1f}x baseline, MFU {m['mfu']}.")
    record["extra"]["last_tpu_measurement"] = dict(m)
    return json.dumps(record)


def profile_worker():
    """MFU ceiling analysis (VERDICT r3 prep): compile the ResNet step
    at bs 64 and 128, dump XLA's aggregate cost analysis (flops, bytes
    accessed, optimal seconds) + measured step time, and the same for
    the transformer leg — the per-op FLOP/time evidence for where the
    remaining time goes.  Run on real TPU; works on CPU for plumbing
    tests.  Prints one JSON object."""
    import jax

    if os.environ.get("BENCH_CPU_FALLBACK"):
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    peak = _peak_flops_per_chip(devices[0])

    import horovod_tpu as hvd
    hvd.init()

    out = {"device": getattr(devices[0], "device_kind", "unknown"),
           "peak_bf16_flops": peak, "legs": {}}
    legs = [("resnet_bs64", 64), ("resnet_bs128", 128)]
    if os.environ.get("BENCH_CPU_FALLBACK"):
        legs = [("resnet_bs2_cpu", 2)]  # plumbing smoke only
    for label, batch in legs:
        try:
            img_sec, mfu = _bench_resnet(devices, per_device_batch=batch)
            leg = {"img_sec_per_chip": round(img_sec, 2),
                   "mfu": round(mfu, 4) if mfu is not None else None,
                   "batch_per_chip": batch}
            # XLA's view of the compiled step — the ceiling evidence:
            # flops/peak vs optimal_seconds (compute-bound estimate)
            # vs bytes accessed/HBM bandwidth (memory-bound estimate)
            cost = getattr(_bench_resnet, "last_cost_analysis", None)
            if cost:
                leg["xla_cost_analysis"] = cost
                if peak and cost.get("flops"):
                    leg["compute_bound_step_ms"] = round(
                        cost["flops"] / peak * 1e3, 3)
                if cost.get("bytes accessed"):
                    hbm = 819e9  # v5e HBM bandwidth, bytes/s
                    leg["memory_bound_step_ms"] = round(
                        cost["bytes accessed"] / hbm * 1e3, 3)
            out["legs"][label] = leg
        except Exception as exc:  # noqa: BLE001
            out["legs"][label] = {"error": repr(exc)}
    try:
        out["legs"]["transformer"] = _bench_transformer(devices)
    except Exception as exc:  # noqa: BLE001
        out["legs"]["transformer"] = {"error": repr(exc)}
    hvd.shutdown()
    print(json.dumps(out))


def main():
    """Supervisor: run the worker in fresh subprocesses with retries, so
    a transiently-unavailable TPU backend doesn't fail the bench; if
    every TPU attempt fails, fall back to a labeled CPU-mesh run so the
    round always records SOME measurement."""
    attempts = 6
    delay = 30
    last_out = ""
    for attempt in range(attempts):
        line, out, err = _run_worker_once()
        last_out = out
        if line is not None:
            print(_attach_scaling(line))
            return 0
        sys.stderr.write(
            f"bench attempt {attempt + 1}/{attempts} failed ({err}); "
            f"tail:\n{out[-1500:]}\n")
        if attempt < attempts - 1:
            time.sleep(delay)
    sys.stderr.write("bench: all TPU attempts failed; "
                     "running labeled CPU fallback\n")
    line = _cpu_fallback()
    if line is not None:
        print(_attach_scaling(line))
        return 0
    sys.stderr.write(last_out[-3000:] + "\n")
    return 1


def _attach_scaling(line):
    """Merge the CPU-mesh scaling harness results into the headline
    record's extra (VERDICT r2 item 10: the 8->64-chip efficiency
    measurement machinery, pre-validated on the virtual mesh).
    ``BENCH_SCALING=0`` skips it (quick smoke runs)."""
    if os.environ.get("BENCH_SCALING", "1") in ("0", "false", "no"):
        return line
    scaling = _run_scaling()
    if scaling is None:
        return line
    try:
        record = json.loads(line)
    except json.JSONDecodeError:
        return line
    record.setdefault("extra", {})["scaling"] = scaling
    if os.environ.get("BENCH_SHARDING", "1") not in ("0", "false", "no"):
        sharding = _run_sharding()
        if sharding is not None:
            record["extra"]["sharding"] = sharding
    if os.environ.get("BENCH_GROUPS", "1") not in ("0", "false", "no"):
        grp = _run_groups()
        if grp is not None:
            record["extra"]["groups"] = grp
    if os.environ.get("BENCH_RECONNECT", "1") not in ("0", "false",
                                                      "no"):
        rec = _run_reconnect()
        if rec is not None:
            record["extra"]["reconnect"] = rec
    return json.dumps(record)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    elif "--profile" in sys.argv:
        profile_worker()
    elif "--scaling-worker" in sys.argv:
        scaling_worker()
    elif "--sharding-worker" in sys.argv:
        sharding_worker()
    elif "--sharding" in sys.argv:
        result = _run_sharding()
        print(json.dumps(result if result is not None else
                         {"error": "sharding run failed"}))
        sys.exit(0 if result is not None else 1)
    elif "--groups-worker" in sys.argv:
        groups_worker()
    elif "--groups" in sys.argv:
        result = _run_groups()
        print(json.dumps(result if result is not None else
                         {"error": "groups run failed"}))
        sys.exit(0 if result is not None else 1)
    elif "--reconnect-worker" in sys.argv:
        reconnect_worker()
    elif "--reconnect" in sys.argv:
        result = _run_reconnect()
        print(json.dumps(result if result is not None else
                         {"error": "reconnect run failed"}))
        sys.exit(0 if result is not None else 1)
    elif "--checkpoint" in sys.argv:
        sys.exit(checkpoint_bench())
    elif "--pipeline" in sys.argv:
        pipeline_worker()
    elif "--scaling" in sys.argv:
        result = _run_scaling()
        print(json.dumps(result if result is not None else
                         {"error": "scaling run failed"}))
        sys.exit(0 if result is not None else 1)
    else:
        sys.exit(main())
