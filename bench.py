"""Headline benchmark: ResNet-50 synthetic training throughput per chip.

Mirrors the reference's measurement vehicle
(``examples/pytorch_synthetic_benchmark.py:107-120``: img/sec mean over
timed iterations of a synthetic-data training loop).  Baseline for
``vs_baseline`` is the reference's published per-GPU throughput:
1656.82 images/sec on 16 Pascal GPUs => 103.55 img/sec/GPU
(``docs/benchmarks.rst:31-43``, BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16.0


def main():
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    import horovod_tpu as hvd
    from horovod_tpu.models import ResNet50
    from horovod_tpu.parallel import make_mesh

    devices = jax.devices()
    n = len(devices)
    mesh = make_mesh({"hvd": n}, devices=devices)

    per_device_batch = 64
    batch = per_device_batch * n
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)

    rng = jax.random.PRNGKey(0)
    x_host = np.random.RandomState(0).randn(
        batch, 224, 224, 3).astype(np.float32)
    y_host = np.random.RandomState(1).randint(0, 1000, (batch,))

    variables = jax.jit(lambda r, x: model.init(r, x, train=True))(
        rng, jnp.zeros((1, 224, 224, 3), jnp.float32))
    params, batch_stats = variables["params"], variables["batch_stats"]

    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                   named_axes=("hvd",))
    opt_state = opt.init(params)

    def per_shard_step(params, batch_stats, opt_state, x, y):
        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(y, 1000)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))
            return loss, updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_stats = jax.tree.map(
            lambda s: jax.lax.pmean(s, "hvd"), new_stats)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_stats, new_opt_state, \
            jax.lax.pmean(loss, "hvd")

    step = jax.jit(shard_map(
        per_shard_step, mesh=mesh,
        in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P(), P()),
    ), donate_argnums=(0, 1, 2))

    sharded = NamedSharding(mesh, P("hvd"))
    x = jax.device_put(x_host, sharded)
    y = jax.device_put(y_host, sharded)

    # warmup + compile
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y)
    jax.block_until_ready(loss)

    iters = 20
    start = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start

    img_sec = batch * iters / elapsed
    img_sec_per_device = img_sec / n
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(img_sec_per_device, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_sec_per_device / BASELINE_IMG_SEC_PER_DEVICE,
                             3),
    }))


if __name__ == "__main__":
    main()
