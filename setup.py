"""Build/install integration (reference: the custom ``setup.py`` that
compiles Horovod's C++ core at install time, ``setup.py:47-52,384-562``).

Builds ``libhvdcore.so`` (coordination core, response cache, wire
format, timeline, GP/EI autotuner) from ``csrc/`` with plain g++ —
no MPI/CUDA probing needed on the TPU stack — and ships it inside the
``horovod_tpu.lib`` package data.  Build-time knobs:

- ``HVD_CXX``: compiler override (default ``g++``)
- ``HVD_SKIP_NATIVE=1``: pure-Python install (the python controller is
  a full fallback; the native core also self-builds on first use via
  ``ops/native_controller.py``)
"""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNativeCore(build_py):
    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        csrc = os.path.join(here, "csrc")
        if os.environ.get("HVD_SKIP_NATIVE") != "1" \
                and os.path.isdir(csrc):
            env = dict(os.environ)
            if "HVD_CXX" in env:
                env["CXX"] = env["HVD_CXX"]
            try:
                subprocess.run(["make", "-C", csrc], check=True, env=env)
            except (subprocess.CalledProcessError, OSError) as exc:
                # pure-Python install is fully supported: the python
                # controller is a complete fallback, and the native core
                # also self-builds on first use where a toolchain exists
                print(f"WARNING: native core build skipped ({exc}); "
                      f"installing with the pure-Python controller")
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description=("TPU-native distributed deep-learning training "
                 "framework with the Horovod capability surface"),
    packages=[
        "horovod",        # drop-in import alias (horovod.* paths)
        "horovod_tpu",
        "horovod_tpu.checkpoint",
        "horovod_tpu.common",
        "horovod_tpu.cluster",
        "horovod_tpu.elastic",
        "horovod_tpu.keras",
        "horovod_tpu.models",
        "horovod_tpu.mxnet",
        "horovod_tpu.ops",
        "horovod_tpu.ops.pallas",
        "horovod_tpu.parallel",
        "horovod_tpu.run",
        "horovod_tpu.run.service",
        "horovod_tpu.sharding",
        "horovod_tpu.spark",
        "horovod_tpu.tensorflow",
        "horovod_tpu.tools",
        "horovod_tpu.tools.fuzz",
        "horovod_tpu.tools.fuzz.targets",
        "horovod_tpu.tools.lint",
        "horovod_tpu.tools.lint.checkers",
        "horovod_tpu.tools.proto",
        "horovod_tpu.tools.proto.checkers",
        "horovod_tpu.tools.race",
        "horovod_tpu.torch",
        "horovod_tpu.utils",
    ],
    package_data={"horovod_tpu": ["lib/libhvdcore.so"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy"],
    extras_require={
        "torch": ["torch"],
        "tensorflow": ["tensorflow", "keras"],
    },
    entry_points={
        "console_scripts": [
            "hvdrun = horovod_tpu.run.runner:main",
            "horovodrun = horovod_tpu.run.runner:main",
        ],
    },
    cmdclass={"build_py": BuildWithNativeCore},
)
