#include "common.h"

#include <ctime>
#include <mutex>

namespace hvd {

LogLevel MinLogLevel() {
  static LogLevel level = [] {
    std::string v = EnvStr("HVD_LOG_LEVEL", "warning");
    if (v == "trace") return LogLevel::kTrace;
    if (v == "debug") return LogLevel::kDebug;
    if (v == "info") return LogLevel::kInfo;
    if (v == "error") return LogLevel::kError;
    if (v == "fatal") return LogLevel::kFatal;
    return LogLevel::kWarning;
  }();
  return level;
}

bool LogHideTimestamps() {
  static bool hide = EnvBool("HVD_LOG_HIDE_TIME", false);
  return hide;
}

void LogMessage(LogLevel level, const std::string& msg) {
  static std::mutex mu;
  static const char* names[] = {"TRACE", "DEBUG", "INFO",
                                "WARNING", "ERROR", "FATAL"};
  std::lock_guard<std::mutex> lock(mu);
  if (!LogHideTimestamps()) {
    char buf[32];
    time_t now = time(nullptr);
    struct tm tm_buf;
    localtime_r(&now, &tm_buf);
    strftime(buf, sizeof(buf), "%F %T", &tm_buf);
    fprintf(stderr, "%s ", buf);
  }
  fprintf(stderr, "[%s] [hvd-core] %s\n",
          names[static_cast<int>(level)], msg.c_str());
  if (level == LogLevel::kFatal) abort();
}

int64_t EnvInt(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  int64_t out = strtoll(v, &end, 10);
  return end == v ? dflt : out;
}

double EnvDouble(const char* name, double dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  double out = strtod(v, &end);
  return end == v ? dflt : out;
}

bool EnvBool(const char* name, bool dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  std::string s(v);
  return s == "1" || s == "true" || s == "yes" || s == "on";
}

std::string EnvStr(const char* name, const std::string& dflt) {
  const char* v = getenv(name);
  return (v && *v) ? std::string(v) : dflt;
}

CoreConfig CoreConfig::FromEnv(int size) {
  CoreConfig c;
  c.size = size;
  c.fusion_threshold_bytes =
      EnvInt("HVD_FUSION_THRESHOLD", c.fusion_threshold_bytes);
  c.cycle_time_ms = EnvDouble("HVD_CYCLE_TIME", c.cycle_time_ms);
  c.cache_capacity = EnvInt("HVD_CACHE_CAPACITY", c.cache_capacity);
  c.timeline_path = EnvStr("HVD_TIMELINE", "");
  c.timeline_mark_cycles = EnvBool("HVD_TIMELINE_MARK_CYCLES", false);
  c.stall_check_disable = EnvBool("HVD_STALL_CHECK_DISABLE", false);
  c.stall_warning_sec =
      EnvDouble("HVD_STALL_CHECK_TIME_SECONDS", c.stall_warning_sec);
  c.stall_shutdown_sec =
      EnvDouble("HVD_STALL_SHUTDOWN_TIME_SECONDS", c.stall_shutdown_sec);
  c.autotune = EnvBool("HVD_AUTOTUNE", false);
  c.autotune_log = EnvStr("HVD_AUTOTUNE_LOG", "");
  c.autotune_warmup_samples = static_cast<int>(
      EnvInt("HVD_AUTOTUNE_WARMUP_SAMPLES", c.autotune_warmup_samples));
  c.autotune_steady_state_samples = static_cast<int>(EnvInt(
      "HVD_AUTOTUNE_STEADY_STATE_SAMPLES", c.autotune_steady_state_samples));
  c.autotune_bayes_opt_max_samples = static_cast<int>(EnvInt(
      "HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", c.autotune_bayes_opt_max_samples));
  c.autotune_gaussian_process_noise =
      EnvDouble("HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
                c.autotune_gaussian_process_noise);
  c.hierarchical_allreduce = EnvBool("HVD_HIERARCHICAL_ALLREDUCE", false);
  c.hierarchical_allgather = EnvBool("HVD_HIERARCHICAL_ALLGATHER", false);
  return c;
}

}  // namespace hvd
