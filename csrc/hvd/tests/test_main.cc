// Native test driver for the horovod_tpu C++ core — the sanitizer leg
// of the fuzz gate (docs/fuzzing.md).  Built and run by
// `bin/build-native --san=asan|ubsan|tsan --test` (gen-ci `native-san`
// job); everything here is deterministic, so a sanitizer report is the
// only nondeterministic outcome and always means a real bug.
//
// Covered:
//   - ResponseCache miss/hit/invalidate, LRU capacity eviction, and the
//     signature-matching regression pin: requests identical up to their
//     alltoall `splits` must NOT hit (a stale splits vector silently
//     reshapes every rank's output).
//   - message codec: roundtrip, truncation (every strict prefix ends
//     !ok(), never crashes), lying string-length words (no allocation,
//     no out-of-bounds read off the zero-page fallback), and a
//     deterministic garbage-decode sweep with output-size bounds (a
//     lying count word must not size the output).
//   - ParameterManager: the categorical+Bayesian tuning walk under a
//     synthetic clock is deterministic and lands inside the search box.
//   - BayesianOptimizer: suggestions stay in bounds, identical feeds
//     produce bitwise-identical walks, best_y tracks the max.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

#include "../core.h"
#include "../message.h"
#include "../optim/bayesian_optimization.h"
#include "../parameter_manager.h"

namespace {

int checks = 0;
int failures = 0;

#define CHECK(cond)                                                       \
  do {                                                                    \
    ++checks;                                                             \
    if (!(cond)) {                                                        \
      ++failures;                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                     \
  } while (0)

hvd::Request MakeRequest(const std::string& name) {
  hvd::Request req;
  req.req_id = 7;
  req.rank = 1;
  req.type = hvd::RequestType::kAlltoall;
  req.op = hvd::ReduceOp::kSum;
  req.dtype = hvd::DataType::kFloat32;
  req.root_rank = -1;
  req.prescale = 1.0;
  req.postscale = 1.0;
  req.name = name;
  req.shape = {4, 8};
  req.splits = {1, 3};
  return req;
}

// ------------------------------------------------------------ ResponseCache

void TestCacheMissHitInvalidate() {
  hvd::ResponseCache cache(8);
  hvd::Request req = MakeRequest("t0");
  CHECK(cache.Lookup(req) == hvd::ResponseCache::State::kMiss);
  int bit = cache.Put(req);
  CHECK(bit == 0);
  CHECK(cache.Lookup(req) == hvd::ResponseCache::State::kHit);
  CHECK(cache.hits() == 1 && cache.misses() == 1);

  hvd::Request changed = req;
  changed.dtype = hvd::DataType::kBFloat16;
  CHECK(cache.Lookup(changed) == hvd::ResponseCache::State::kInvalid);

  cache.Invalidate("t0");
  CHECK(cache.size() == 0);
  CHECK(cache.Lookup(req) == hvd::ResponseCache::State::kMiss);
}

// Regression pin: two requests identical except for `splits` must not
// match — the signature omitted splits once, and a cached alltoall with
// stale splits reshapes every rank's output silently.
void TestCacheSplitsRegression() {
  hvd::ResponseCache cache(8);
  hvd::Request req = MakeRequest("alltoall.grad");
  cache.Put(req);

  hvd::Request resplit = req;
  resplit.splits = {3, 1};  // same sum, same shape, different partition
  CHECK(cache.Lookup(resplit) != hvd::ResponseCache::State::kHit);
  CHECK(cache.Lookup(resplit) == hvd::ResponseCache::State::kInvalid);

  // Re-Put with the new splits refreshes the signature in place and
  // keeps the stable bit position.
  int bit = cache.Put(req);
  CHECK(cache.Put(resplit) == bit);
  CHECK(cache.Lookup(resplit) == hvd::ResponseCache::State::kHit);
  CHECK(cache.Lookup(req) == hvd::ResponseCache::State::kInvalid);
}

void TestCacheCapacityEviction() {
  hvd::ResponseCache cache(2);
  cache.Put(MakeRequest("a"));
  cache.Put(MakeRequest("b"));
  cache.Put(MakeRequest("a"));  // refresh: a is now most recent
  cache.Put(MakeRequest("c"));  // evicts b (LRU), not a
  CHECK(cache.size() == 2);
  CHECK(cache.Lookup(MakeRequest("b")) == hvd::ResponseCache::State::kMiss);
  CHECK(cache.Lookup(MakeRequest("a")) == hvd::ResponseCache::State::kHit);
  CHECK(cache.Lookup(MakeRequest("c")) == hvd::ResponseCache::State::kHit);
}

// ------------------------------------------------------------ message codec

void TestMessageRoundtrip() {
  hvd::Request req = MakeRequest("round.trip");
  hvd::Writer w;
  req.Encode(&w);
  hvd::Reader r(w.data().data(), w.data().size());
  hvd::Request out = hvd::Request::Decode(&r);
  CHECK(r.ok());
  CHECK(out.req_id == req.req_id && out.rank == req.rank);
  CHECK(out.type == req.type && out.dtype == req.dtype);
  CHECK(out.name == req.name);
  CHECK(out.shape == req.shape && out.splits == req.splits);

  hvd::ResponseBatch batch;
  batch.batch_id = 42;
  hvd::Response resp;
  resp.type = hvd::ResponseType::kAllreduce;
  resp.error = "";
  hvd::ResponseEntry entry;
  entry.name = "round.trip";
  entry.ranks = {0, 1};
  entry.req_ids = {10, 11};
  entry.joined = {2};
  entry.root_rank = -1;
  resp.entries.push_back(entry);
  batch.responses.push_back(resp);
  std::vector<uint8_t> bytes = batch.Encode();
  hvd::ResponseBatch out_batch =
      hvd::ResponseBatch::Decode(bytes.data(), bytes.size());
  CHECK(out_batch.batch_id == 42);
  CHECK(out_batch.responses.size() == 1);
  CHECK(out_batch.responses[0].entries.size() == 1);
  CHECK(out_batch.responses[0].entries[0].ranks == entry.ranks);
  CHECK(out_batch.responses[0].entries[0].req_ids == entry.req_ids);
}

void TestReaderTruncation() {
  hvd::Request req = MakeRequest("truncate.me");
  hvd::Writer w;
  req.Encode(&w);
  const std::vector<uint8_t>& full = w.data();
  // Decode consumes every byte of the exact encoding, so EVERY strict
  // prefix must end with the reader dry — and must never crash.
  for (size_t len = 0; len < full.size(); ++len) {
    hvd::Reader r(full.data(), len);
    hvd::Request out = hvd::Request::Decode(&r);
    CHECK(!r.ok());
    (void)out;
  }
}

void TestReaderLyingStrLen() {
  // A 4G string-length word backed by 2 real bytes: Str() must reject
  // without allocating and without reading past the buffer (pre-fix
  // this read 4G bytes off an 8-byte fallback array — ASan territory).
  const uint8_t lying[] = {0xFF, 0xFF, 0xFF, 0xFF, 'a', 'b'};
  hvd::Reader r(lying, sizeof(lying));
  std::string s = r.Str();
  CHECK(!r.ok());
  CHECK(s.empty());

  // Same lie one layer up, through Request::Decode's name field.
  hvd::Writer w;
  MakeRequest("x").Encode(&w);
  std::vector<uint8_t> frame = w.data();
  // name length word sits after u64 + i32 + 3*u8 + i32 + 2*f64 = 35 bytes
  frame[35] = 0xFF;
  frame[36] = 0xFF;
  frame[37] = 0xFF;
  frame[38] = 0xFF;
  hvd::Reader r2(frame.data(), frame.size());
  hvd::Request out = hvd::Request::Decode(&r2);
  CHECK(!r2.ok());
  CHECK(out.name.empty());
}

void TestGarbageDecodeBounded() {
  // Deterministic LCG garbage sweep: no crash under sanitizers, and a
  // lying count word never sizes the output — decoded vectors are
  // bounded by the bytes actually present, not by the claimed count.
  uint64_t state = 0x243F6A8885A308D3ull;  // fixed seed: deterministic
  auto next = [&state]() {
    state = state * 6364136223846793005ull + 1442695040888963407ull;
    return static_cast<uint8_t>(state >> 33);
  };
  for (int iter = 0; iter < 4096; ++iter) {
    size_t len = static_cast<size_t>(next()) % 96;
    std::vector<uint8_t> buf(len);
    for (size_t i = 0; i < len; ++i) buf[i] = next();

    hvd::Reader r(buf.data(), buf.size());
    hvd::Request req = hvd::Request::Decode(&r);
    CHECK(req.name.size() <= len);
    CHECK(req.shape.size() <= len / 8 + 1);
    CHECK(req.splits.size() <= len / 8 + 1);

    hvd::ResponseBatch batch = hvd::ResponseBatch::Decode(buf.data(),
                                                          buf.size());
    CHECK(batch.responses.size() <= len / 4 + 1);
    for (const auto& resp : batch.responses) {
      CHECK(resp.error.size() <= len);
      CHECK(resp.entries.size() <= len / 4 + 1);
    }
  }
}

// --------------------------------------------------- autotuner determinism

std::vector<std::pair<int64_t, double>> RunTuningWalk() {
  hvd::ParameterManager::Options opts;
  opts.active = true;
  opts.warmup_samples = 1;
  opts.steady_state_samples = 2;
  opts.bayes_opt_max_samples = 2;
  hvd::ParameterManager pm(opts);
  std::vector<std::pair<int64_t, double>> trace;
  double now = 0.0;
  // Synthetic clock + synthetic load: score is a deterministic function
  // of the published point, so the walk is fully reproducible.
  for (int step = 0; step < 4096 && pm.tuning(); ++step) {
    now += 1.0;
    int64_t fusion = pm.fusion_threshold_bytes();
    double cycle = pm.cycle_time_ms();
    pm.Record(fusion / 1024 + static_cast<int64_t>(cycle * 1000.0));
    if (pm.Update(now)) trace.emplace_back(pm.fusion_threshold_bytes(),
                                           pm.cycle_time_ms());
  }
  CHECK(!pm.tuning());  // the walk terminates
  CHECK(pm.best_score() > 0.0);
  CHECK(pm.fusion_threshold_bytes() >= (1 << 20));
  CHECK(pm.fusion_threshold_bytes() <= (256 << 20));
  CHECK(pm.cycle_time_ms() >= 1.0 && pm.cycle_time_ms() <= 25.0);
  trace.emplace_back(pm.fusion_threshold_bytes(), pm.cycle_time_ms());
  return trace;
}

void TestParameterManagerDeterministicWalk() {
  std::vector<std::pair<int64_t, double>> a = RunTuningWalk();
  std::vector<std::pair<int64_t, double>> b = RunTuningWalk();
  CHECK(!a.empty());
  CHECK(a == b);  // bitwise-identical published values, both runs
}

void TestBayesianOptimizer() {
  hvd::optim::BayesianOptimizer opt_a({0.0, 1.0}, {8.0, 25.0}, 0.8);
  hvd::optim::BayesianOptimizer opt_b({0.0, 1.0}, {8.0, 25.0}, 0.8);
  double best = -1e300;
  for (int i = 0; i < 24; ++i) {
    std::vector<double> xa = opt_a.Suggest();
    std::vector<double> xb = opt_b.Suggest();
    CHECK(xa.size() == 2);
    CHECK(xa == xb);  // identical feeds -> bitwise-identical suggestions
    CHECK(xa[0] >= 0.0 && xa[0] <= 8.0);
    CHECK(xa[1] >= 1.0 && xa[1] <= 25.0);
    double y = -(xa[0] - 3.0) * (xa[0] - 3.0)
               - (xa[1] - 10.0) * (xa[1] - 10.0) / 100.0;
    if (y > best) best = y;
    opt_a.AddSample(xa, y);
    opt_b.AddSample(xb, y);
  }
  CHECK(opt_a.num_samples() == 24);
  CHECK(opt_a.best_y() == best);
  CHECK(std::isfinite(opt_a.best_x()[0]) && std::isfinite(opt_a.best_x()[1]));
}

}  // namespace

int main() {
  TestCacheMissHitInvalidate();
  TestCacheSplitsRegression();
  TestCacheCapacityEviction();
  TestMessageRoundtrip();
  TestReaderTruncation();
  TestReaderLyingStrLen();
  TestGarbageDecodeBounded();
  TestParameterManagerDeterministicWalk();
  TestBayesianOptimizer();
  std::printf("hvd_tests: %d checks, %d failures\n", checks, failures);
  return failures == 0 ? 0 : 1;
}
