// Common types for the horovod_tpu native core.
//
// TPU-native re-design of the reference core runtime (reference:
// horovod/common/common.h, logging.h, utils/env_parser.cc).  The native core
// coordinates named collectives across logical ranks: it owns the background
// cycle loop, tensor queue, negotiation, response cache, fusion planning,
// stall inspection and timeline.  Tensor DATA never enters this layer — the
// XLA data plane (Python/JAX) executes the fused programs; the core works on
// metadata only.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <string>
#include <vector>

namespace hvd {

// ---------------------------------------------------------------- data types
enum class DataType : uint8_t {
  kFloat32 = 0,
  kFloat64 = 1,
  kBFloat16 = 2,
  kFloat16 = 3,
  kInt8 = 4,
  kInt16 = 5,
  kInt32 = 6,
  kInt64 = 7,
  kUInt8 = 8,
  kBool = 9,
};

inline size_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kFloat64:
    case DataType::kInt64:
      return 8;
    case DataType::kFloat32:
    case DataType::kInt32:
      return 4;
    case DataType::kBFloat16:
    case DataType::kFloat16:
    case DataType::kInt16:
      return 2;
    default:
      return 1;
  }
}

enum class RequestType : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kJoin = 3,
  kAdasum = 4,
  kAlltoall = 5,
  kReduceScatter = 6,
};

enum class ResponseType : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kJoin = 3,
  kAdasum = 4,
  kAlltoall = 5,
  kError = 6,  // pinned: the Python wire decoder keys errors on 6
  kReduceScatter = 7,
};

enum class ReduceOp : uint8_t { kAverage = 0, kSum = 1, kAdasum = 2 };

// -------------------------------------------------------------------- status
struct Status {
  bool ok = true;
  std::string message;
  static Status OK() { return {}; }
  static Status Error(std::string msg) { return {false, std::move(msg)}; }
};

// ------------------------------------------------------------------- logging
enum class LogLevel : int {
  kTrace = 0,
  kDebug = 1,
  kInfo = 2,
  kWarning = 3,
  kError = 4,
  kFatal = 5,
};

LogLevel MinLogLevel();       // from HVD_LOG_LEVEL
bool LogHideTimestamps();     // from HVD_LOG_HIDE_TIME
void LogMessage(LogLevel level, const std::string& msg);

class LogStream {
 public:
  explicit LogStream(LogLevel level) : level_(level) {}
  ~LogStream() {
    if (level_ >= MinLogLevel()) LogMessage(level_, stream_.str());
  }
  template <typename T>
  LogStream& operator<<(const T& v) {
    stream_ << v;
    return *this;
  }

 private:
  LogLevel level_;
  std::ostringstream stream_;
};

#define HVD_LOG(level) ::hvd::LogStream(::hvd::LogLevel::k##level)

// ----------------------------------------------------------------------- env
int64_t EnvInt(const char* name, int64_t dflt);
double EnvDouble(const char* name, double dflt);
bool EnvBool(const char* name, bool dflt);
std::string EnvStr(const char* name, const std::string& dflt);

// -------------------------------------------------------------------- config
// Reference knob set: horovod/common/operations.cc:404-500.
struct CoreConfig {
  int size = 1;
  int64_t fusion_threshold_bytes = 64 * 1024 * 1024;
  double cycle_time_ms = 1.0;
  int64_t cache_capacity = 1024;
  std::string timeline_path;
  bool timeline_mark_cycles = false;
  bool stall_check_disable = false;
  double stall_warning_sec = 60.0;
  double stall_shutdown_sec = 0.0;

  // Autotune (reference: HOROVOD_AUTOTUNE* knobs, operations.cc:404-500).
  bool autotune = false;
  std::string autotune_log;
  int autotune_warmup_samples = 3;
  int autotune_steady_state_samples = 10;
  int autotune_bayes_opt_max_samples = 20;
  double autotune_gaussian_process_noise = 0.8;
  bool hierarchical_allreduce = false;
  bool hierarchical_allgather = false;

  static CoreConfig FromEnv(int size);
};

}  // namespace hvd
