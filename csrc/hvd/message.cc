#include "message.h"

namespace hvd {

void Request::Encode(Writer* w) const {
  w->U64(req_id);
  w->I32(rank);
  w->U8(static_cast<uint8_t>(type));
  w->U8(static_cast<uint8_t>(op));
  w->U8(static_cast<uint8_t>(dtype));
  w->I32(root_rank);
  w->F64(prescale);
  w->F64(postscale);
  w->Str(name);
  w->U32(static_cast<uint32_t>(shape.size()));
  for (int64_t d : shape) w->I64(d);
  w->U32(static_cast<uint32_t>(splits.size()));
  for (int64_t s : splits) w->I64(s);
}

Request Request::Decode(Reader* r) {
  Request q;
  q.req_id = r->U64();
  q.rank = r->I32();
  q.type = static_cast<RequestType>(r->U8());
  q.op = static_cast<ReduceOp>(r->U8());
  q.dtype = static_cast<DataType>(r->U8());
  q.root_rank = r->I32();
  q.prescale = r->F64();
  q.postscale = r->F64();
  q.name = r->Str();
  // Every count-prefixed loop stops the moment the reader runs dry: a
  // lying count word must never size the output (4G-element vectors
  // from a 10-byte buffer), only the bytes actually present may.
  uint32_t nd = r->U32();
  for (uint32_t i = 0; i < nd && r->ok(); ++i) q.shape.push_back(r->I64());
  uint32_t ns = r->U32();
  for (uint32_t i = 0; i < ns && r->ok(); ++i) q.splits.push_back(r->I64());
  return q;
}

void ResponseEntry::Encode(Writer* w) const {
  w->Str(name);
  w->U32(static_cast<uint32_t>(ranks.size()));
  for (size_t i = 0; i < ranks.size(); ++i) {
    w->I32(ranks[i]);
    w->U64(req_ids[i]);
  }
  w->U32(static_cast<uint32_t>(joined.size()));
  for (int32_t j : joined) w->I32(j);
  w->I32(root_rank);
}

ResponseEntry ResponseEntry::Decode(Reader* r) {
  ResponseEntry e;
  e.name = r->Str();
  uint32_t n = r->U32();
  for (uint32_t i = 0; i < n && r->ok(); ++i) {
    e.ranks.push_back(r->I32());
    e.req_ids.push_back(r->U64());
  }
  uint32_t nj = r->U32();
  for (uint32_t i = 0; i < nj && r->ok(); ++i) e.joined.push_back(r->I32());
  e.root_rank = r->I32();
  return e;
}

void Response::Encode(Writer* w) const {
  w->U8(static_cast<uint8_t>(type));
  w->U8(static_cast<uint8_t>(op));
  w->U8(static_cast<uint8_t>(dtype));
  w->F64(prescale);
  w->F64(postscale);
  w->Str(error);
  w->U32(static_cast<uint32_t>(entries.size()));
  for (const auto& e : entries) e.Encode(w);
}

Response Response::Decode(Reader* r) {
  Response resp;
  resp.type = static_cast<ResponseType>(r->U8());
  resp.op = static_cast<ReduceOp>(r->U8());
  resp.dtype = static_cast<DataType>(r->U8());
  resp.prescale = r->F64();
  resp.postscale = r->F64();
  resp.error = r->Str();
  uint32_t n = r->U32();
  for (uint32_t i = 0; i < n && r->ok(); ++i) {
    resp.entries.push_back(ResponseEntry::Decode(r));
  }
  return resp;
}

std::vector<uint8_t> ResponseBatch::Encode() const {
  Writer w;
  w.U64(batch_id);
  w.U8(shutdown ? 1 : 0);
  w.U32(static_cast<uint32_t>(responses.size()));
  for (const auto& resp : responses) resp.Encode(&w);
  return w.data();
}

ResponseBatch ResponseBatch::Decode(const uint8_t* data, size_t len) {
  Reader r(data, len);
  ResponseBatch b;
  b.batch_id = r.U64();
  b.shutdown = r.U8() != 0;
  uint32_t n = r.U32();
  for (uint32_t i = 0; i < n && r.ok(); ++i) {
    b.responses.push_back(Response::Decode(&r));
  }
  return b;
}

}  // namespace hvd
