// Chrome-tracing timeline writer with a dedicated IO thread.
//
// Native analog of the reference Timeline (horovod/common/timeline.{h,cc}):
// per-tensor trace rows (pid per tensor name), NEGOTIATE_* phases with
// per-rank ready ticks, op phases, cycle markers; a writer thread drains a
// queue so the coordination loop never blocks on file IO (the reference uses
// a boost lockfree SPSC queue; a mutex+cv deque serves the same contract
// here, with the enqueue path O(1) and non-blocking in the common case).
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvd {

class Timeline {
 public:
  Timeline() = default;
  ~Timeline() { Close(); }

  void Open(const std::string& path, bool mark_cycles);
  bool enabled() const { return file_ != nullptr; }

  void Begin(const std::string& tensor, const std::string& phase);
  void End(const std::string& tensor);
  void Instant(const std::string& tensor, const std::string& name);
  void MarkCycle();
  void Close();

 private:
  int64_t NowUs() const;
  int Pid(const std::string& tensor);  // registers metadata on first use
  void Enqueue(std::string record);
  void WriterLoop();

  FILE* file_ = nullptr;
  bool mark_cycles_ = false;
  std::chrono::steady_clock::time_point start_;
  std::mutex pid_mu_;  // pids_/next_pid_: bg thread + dispatcher thread
  std::unordered_map<std::string, int> pids_;
  int next_pid_ = 1;
  bool first_record_ = true;

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  bool running_ = false;
  std::thread writer_;
};

}  // namespace hvd
