#include "timeline.h"

#include <cinttypes>

namespace hvd {

void Timeline::Open(const std::string& path, bool mark_cycles) {
  if (path.empty()) return;
  file_ = fopen(path.c_str(), "w");
  if (!file_) return;
  mark_cycles_ = mark_cycles;
  start_ = std::chrono::steady_clock::now();
  fputs("[\n", file_);
  running_ = true;
  writer_ = std::thread(&Timeline::WriterLoop, this);
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

int Timeline::Pid(const std::string& tensor) {
  // called from both the background thread (Begin/Instant) and the
  // dispatcher thread (End via MarkDone) — the map needs the lock
  int pid;
  bool fresh = false;
  {
    std::lock_guard<std::mutex> lock(pid_mu_);
    auto it = pids_.find(tensor);
    if (it != pids_.end()) {
      pid = it->second;
    } else {
      pid = next_pid_++;
      pids_[tensor] = pid;
      fresh = true;
    }
  }
  if (fresh) {
    char buf[512];
    snprintf(buf, sizeof(buf),
             "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
             "\"args\": {\"name\": \"%s\"}}",
             pid, tensor.c_str());
    Enqueue(buf);
  }
  return pid;
}

void Timeline::Begin(const std::string& tensor, const std::string& phase) {
  if (!enabled()) return;
  int pid = Pid(tensor);
  char buf[512];
  snprintf(buf, sizeof(buf),
           "{\"name\": \"%s\", \"ph\": \"B\", \"ts\": %" PRId64
           ", \"pid\": %d, \"tid\": 0}",
           phase.c_str(), NowUs(), pid);
  Enqueue(buf);
}

void Timeline::End(const std::string& tensor) {
  if (!enabled()) return;
  int pid = Pid(tensor);
  char buf[256];
  snprintf(buf, sizeof(buf),
           "{\"ph\": \"E\", \"ts\": %" PRId64 ", \"pid\": %d, \"tid\": 0}",
           NowUs(), pid);
  Enqueue(buf);
}

void Timeline::Instant(const std::string& tensor, const std::string& name) {
  if (!enabled()) return;
  int pid = Pid(tensor);
  char buf[512];
  snprintf(buf, sizeof(buf),
           "{\"name\": \"%s\", \"ph\": \"i\", \"ts\": %" PRId64
           ", \"pid\": %d, \"tid\": 0, \"s\": \"p\"}",
           name.c_str(), NowUs(), pid);
  Enqueue(buf);
}

void Timeline::MarkCycle() {
  if (!enabled() || !mark_cycles_) return;
  int pid = Pid("CYCLE");
  char buf[256];
  snprintf(buf, sizeof(buf),
           "{\"name\": \"CYCLE\", \"ph\": \"i\", \"ts\": %" PRId64
           ", \"pid\": %d, \"tid\": 0, \"s\": \"g\"}",
           NowUs(), pid);
  Enqueue(buf);
}

void Timeline::Enqueue(std::string record) {
  {
    std::lock_guard<std::mutex> lock(mu_);
    queue_.push_back(std::move(record));
  }
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  std::unique_lock<std::mutex> lock(mu_);
  while (true) {
    cv_.wait(lock, [&] { return !queue_.empty() || !running_; });
    while (!queue_.empty()) {
      std::string rec = std::move(queue_.front());
      queue_.pop_front();
      lock.unlock();
      if (!first_record_) fputs(",\n", file_);
      first_record_ = false;
      fputs(rec.c_str(), file_);
      lock.lock();
    }
    if (!running_ && queue_.empty()) return;
  }
}

void Timeline::Close() {
  if (!file_) return;
  {
    std::lock_guard<std::mutex> lock(mu_);
    running_ = false;
  }
  cv_.notify_one();
  if (writer_.joinable()) writer_.join();
  fputs("\n]\n", file_);
  fclose(file_);
  file_ = nullptr;
}

}  // namespace hvd
