// Online autotuning of runtime knobs.
//
// TPU-native re-design of the reference's ParameterManager (reference:
// horovod/common/parameter_manager.{h,cc} — Bayesian-optimized tuning of
// fusion threshold and cycle time plus sequentially-tried categorical
// parameters, scored by negotiated bytes/sec; rank 0 tunes and broadcasts
// winners via Controller::SynchronizeParameters, controller.cc:33).
//
// Differences by design: scoring and search are fully deterministic given
// the same (bytes, time) observations, and in single-controller mode (this
// build's native core owns negotiation for all ranks) no cross-rank
// synchronization step is needed — the tuned values are published to the
// dispatcher through atomic getters instead.
//
// Tuning walk: for each categorical configuration
//     (hierarchical_allreduce, hierarchical_allgather, cache_enabled)
// in a fixed order, run `bayes_opt_max_samples` Bayesian-optimization
// evaluations over (log2 fusion MB, cycle time ms).  Each evaluation point
// is held for `steady_state_samples` score windows (median taken); the
// first `warmup_samples` windows after every parameter change are
// discarded.  When the walk finishes, the globally best configuration is
// pinned and tuning stops (reference semantics: ParameterManager
// `SetAutoTuning(false)` once tuning completes).
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <string>
#include <vector>

#include "optim/bayesian_optimization.h"

namespace hvd {

class ParameterManager {
 public:
  struct Options {
    bool active = false;
    int warmup_samples = 3;          // HVD_AUTOTUNE_WARMUP_SAMPLES
    int steady_state_samples = 10;   // HVD_AUTOTUNE_STEADY_STATE_SAMPLES
    int bayes_opt_max_samples = 20;  // HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES
    double gaussian_process_noise = 0.8;  // HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE
    std::string log_path;            // HVD_AUTOTUNE_LOG (CSV)

    // Starting values (the pinned result if tuning is off).
    int64_t fusion_threshold_bytes = 64 * 1024 * 1024;
    double cycle_time_ms = 1.0;
    bool hierarchical_allreduce = false;
    bool hierarchical_allgather = false;
    bool cache_enabled = true;
    // On-the-wire compression toggle (HVD_TPU_COMPRESSION).  Only part
    // of the categorical walk when `compression_available` — unlike the
    // hierarchical switches it changes numerics, so it is explored only
    // when the operator configured a compressor.
    bool compression = false;
    bool compression_available = false;
    // TCP-ring transfer-engine knobs (HVD_TPU_RING_SEGMENT_BYTES /
    // HVD_TPU_RING_STRIPES).  Joined to the categorical walk only when
    // `ring_tunable` (tcp-controller jobs — the knobs are inert on the
    // in-process planes): a short probe set around the configured
    // values, scored like every other categorical.
    int64_t ring_segment_bytes = 1 << 20;
    int ring_stripes = 2;
    bool ring_tunable = false;
    // Collective schedule for the tcp plane (HVD_TPU_SCHEDULE), encoded
    // as the index into the canonical name tuple
    // ("auto","flat_ring","hierarchical","rhd","star") shared with
    // ops/tcp_dataplane.py SCHEDULES.  Joined to the categorical walk
    // only when `schedule_tunable` (tcp-controller jobs): explicit
    // flat-ring and hierarchical probes let the score decide whether
    // the two-level schedule pays on this job's topology.
    int schedule = 0;
    bool schedule_tunable = false;
  };

  explicit ParameterManager(const Options& opts);
  ~ParameterManager();

  // Record negotiated tensor bytes (coordinator thread, per published
  // data-plane response).
  void Record(int64_t bytes);

  // Close a score window at `now_seconds` (any monotonically increasing
  // clock; the core passes steady-clock seconds, tests pass synthetic
  // time).  Returns true if the tuned values changed.
  bool Update(double now_seconds);

  // Current values (any thread).
  int64_t fusion_threshold_bytes() const { return fusion_bytes_.load(); }
  double cycle_time_ms() const { return cycle_ms_.load(); }
  bool hierarchical_allreduce() const { return hier_allreduce_.load(); }
  bool hierarchical_allgather() const { return hier_allgather_.load(); }
  bool cache_enabled() const { return cache_enabled_.load(); }
  bool compression_enabled() const { return compression_.load(); }
  int64_t ring_segment_bytes() const { return ring_segment_bytes_.load(); }
  int ring_stripes() const { return ring_stripes_.load(); }
  int schedule() const { return schedule_.load(); }

  bool tuning() const { return tuning_.load(); }
  double best_score() const { return best_score_.load(); }  // bytes/sec

 private:
  struct Categorical {
    bool hier_allreduce, hier_allgather, cache_enabled, compression;
    int64_t ring_segment_bytes;
    int ring_stripes;
    int schedule;
  };

  void ApplyPoint(const std::vector<double>& point);
  void ApplyBest();
  void NextCategorical();
  void LogRow(double score);

  Options opts_;
  std::vector<Categorical> walk_;
  size_t walk_index_ = 0;
  std::unique_ptr<optim::BayesianOptimizer> bayes_;
  std::vector<double> current_point_;

  // Window accounting (coordinator thread only).
  int64_t window_bytes_ = 0;
  double window_start_ = -1.0;
  int discard_left_;
  std::vector<double> window_scores_;

  // Best seen across the whole walk.
  double best_fusion_log2_mb_;
  double best_cycle_ms_;
  Categorical best_cat_;

  // Published values.
  std::atomic<int64_t> fusion_bytes_;
  std::atomic<double> cycle_ms_;
  std::atomic<bool> hier_allreduce_;
  std::atomic<bool> hier_allgather_;
  std::atomic<bool> cache_enabled_;
  std::atomic<bool> compression_;
  std::atomic<int64_t> ring_segment_bytes_;
  std::atomic<int> ring_stripes_;
  std::atomic<int> schedule_;
  std::atomic<bool> tuning_;
  std::atomic<double> best_score_;

  FILE* log_ = nullptr;
};

}  // namespace hvd
