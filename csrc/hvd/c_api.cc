// C boundary for ctypes (the reference exposes extern "C" basics the same
// way: horovod/common/operations.cc:663-797 consumed by
// horovod/common/basics.py).  All blocking entry points release the GIL on
// the Python side automatically because ctypes drops it around foreign
// calls.
#include <cstring>
#include <vector>

#include "core.h"
#include "optim/bayesian_optimization.h"

using hvd::Core;
using hvd::CoreConfig;

extern "C" {

void* hvd_core_create(int size) {
  return new Core(CoreConfig::FromEnv(size));
}

void hvd_core_start(void* core) { static_cast<Core*>(core)->Start(); }

void hvd_core_shutdown(void* core) { static_cast<Core*>(core)->Shutdown(); }

void hvd_core_finalize(void* core) { static_cast<Core*>(core)->Finalize(); }

void hvd_core_destroy(void* core) { delete static_cast<Core*>(core); }

// Returns 0 on success; -1 with the error copied into err_buf otherwise.
int hvd_core_enqueue(void* core, const uint8_t* data, size_t len,
                     char* err_buf, size_t err_cap) {
  std::string error;
  if (static_cast<Core*>(core)->Enqueue(data, len, &error)) return 0;
  if (err_buf && err_cap > 0) {
    strncpy(err_buf, error.c_str(), err_cap - 1);
    err_buf[err_cap - 1] = '\0';
  }
  return -1;
}

void hvd_core_join(void* core, int rank, uint64_t req_id) {
  static_cast<Core*>(core)->Join(rank, req_id);
}

// Blocks until a batch is available (GIL released by ctypes).  The returned
// buffer is owned by the caller; free with hvd_core_free.
uint8_t* hvd_core_next_batch(void* core, size_t* out_len) {
  std::vector<uint8_t> batch = static_cast<Core*>(core)->NextBatch();
  uint8_t* out = static_cast<uint8_t*>(malloc(batch.size()));
  memcpy(out, batch.data(), batch.size());
  *out_len = batch.size();
  return out;
}

void hvd_core_free(uint8_t* buf) { free(buf); }

void hvd_core_mark_done(void* core, uint64_t batch_id, const char* error) {
  static_cast<Core*>(core)->MarkDone(batch_id, error);
}

uint64_t hvd_core_cache_hits(void* core) {
  return static_cast<Core*>(core)->cache_hits();
}

uint64_t hvd_core_cache_misses(void* core) {
  return static_cast<Core*>(core)->cache_misses();
}

uint64_t hvd_core_cache_size(void* core) {
  return static_cast<Core*>(core)->cache_size();
}

// ---- autotuned runtime parameters (reference: ParameterManager values
// broadcast via Controller::SynchronizeParameters; here the dispatcher
// polls them) ----

int64_t hvd_core_param_fusion_bytes(void* core) {
  return static_cast<Core*>(core)->params().fusion_threshold_bytes();
}

double hvd_core_param_cycle_ms(void* core) {
  return static_cast<Core*>(core)->params().cycle_time_ms();
}

int hvd_core_param_hierarchical_allreduce(void* core) {
  return static_cast<Core*>(core)->params().hierarchical_allreduce() ? 1 : 0;
}

int hvd_core_param_hierarchical_allgather(void* core) {
  return static_cast<Core*>(core)->params().hierarchical_allgather() ? 1 : 0;
}

int hvd_core_param_cache_enabled(void* core) {
  return static_cast<Core*>(core)->params().cache_enabled() ? 1 : 0;
}

int hvd_core_autotune_tuning(void* core) {
  return static_cast<Core*>(core)->params().tuning() ? 1 : 0;
}

double hvd_core_autotune_best_score(void* core) {
  return static_cast<Core*>(core)->params().best_score();
}

// ---- standalone autotune math (unit-tested against numpy oracles) ----

void* hvd_gp_create(double length_scale, double signal_variance,
                    double noise_variance) {
  return new hvd::optim::GaussianProcess(length_scale, signal_variance,
                                         noise_variance);
}

void hvd_gp_destroy(void* gp) {
  delete static_cast<hvd::optim::GaussianProcess*>(gp);
}

// x: n*d row-major.  Returns 0 on success.
int hvd_gp_fit(void* gp, const double* x, const double* y, int n, int d) {
  std::vector<std::vector<double>> xv(n, std::vector<double>(d));
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < d; ++j) xv[i][j] = x[i * d + j];
  std::vector<double> yv(y, y + n);
  return static_cast<hvd::optim::GaussianProcess*>(gp)->Fit(xv, yv) ? 0 : -1;
}

void hvd_gp_predict(void* gp, const double* x, int d, double* mean,
                    double* variance) {
  std::vector<double> xv(x, x + d);
  static_cast<hvd::optim::GaussianProcess*>(gp)->Predict(xv, mean, variance);
}

double hvd_expected_improvement(double mean, double stddev, double best,
                                double xi) {
  return hvd::optim::ExpectedImprovement(mean, stddev, best, xi);
}

void* hvd_bo_create(const double* low, const double* high, int d,
                    double gp_noise, int num_candidates) {
  return new hvd::optim::BayesianOptimizer(
      std::vector<double>(low, low + d), std::vector<double>(high, high + d),
      gp_noise, num_candidates);
}

void hvd_bo_destroy(void* bo) {
  delete static_cast<hvd::optim::BayesianOptimizer*>(bo);
}

void hvd_bo_add_sample(void* bo, const double* x, int d, double y) {
  static_cast<hvd::optim::BayesianOptimizer*>(bo)->AddSample(
      std::vector<double>(x, x + d), y);
}

void hvd_bo_suggest(void* bo, double* out, int d) {
  std::vector<double> x =
      static_cast<hvd::optim::BayesianOptimizer*>(bo)->Suggest();
  for (int i = 0; i < d && i < static_cast<int>(x.size()); ++i) out[i] = x[i];
}

double hvd_bo_best_y(void* bo) {
  return static_cast<hvd::optim::BayesianOptimizer*>(bo)->best_y();
}

// ---- standalone ParameterManager (virtual-clock driven, for tests) ----

void* hvd_pm_create(int warmup, int steady_state, int bayes_max,
                    double gp_noise, const char* log_path,
                    int64_t fusion_bytes, double cycle_ms,
                    int hier_allreduce, int hier_allgather,
                    int cache_enabled, int compression,
                    int compression_available,
                    int64_t ring_segment_bytes, int ring_stripes,
                    int ring_tunable, int schedule, int schedule_tunable) {
  hvd::ParameterManager::Options o;
  o.active = true;
  o.warmup_samples = warmup;
  o.steady_state_samples = steady_state;
  o.bayes_opt_max_samples = bayes_max;
  o.gaussian_process_noise = gp_noise;
  if (log_path) o.log_path = log_path;
  o.fusion_threshold_bytes = fusion_bytes;
  o.cycle_time_ms = cycle_ms;
  // Seed the categorical walk and the fallback best from the configured
  // values so tuning starts from — and on no-improvement converges back
  // to — the operator's explicit hierarchical/cache choices, matching
  // the reference's SetHierarchicalAllreduce/SetCacheEnabled seeding
  // before tuning begins.
  o.hierarchical_allreduce = hier_allreduce != 0;
  o.hierarchical_allgather = hier_allgather != 0;
  o.cache_enabled = cache_enabled != 0;
  o.compression = compression != 0;
  o.compression_available = compression_available != 0;
  o.ring_segment_bytes = ring_segment_bytes;
  o.ring_stripes = ring_stripes;
  o.ring_tunable = ring_tunable != 0;
  o.schedule = schedule;
  o.schedule_tunable = schedule_tunable != 0;
  return new hvd::ParameterManager(o);
}

void hvd_pm_destroy(void* pm) {
  delete static_cast<hvd::ParameterManager*>(pm);
}

void hvd_pm_record(void* pm, int64_t bytes) {
  static_cast<hvd::ParameterManager*>(pm)->Record(bytes);
}

int hvd_pm_update(void* pm, double now_seconds) {
  return static_cast<hvd::ParameterManager*>(pm)->Update(now_seconds) ? 1 : 0;
}

int64_t hvd_pm_fusion_bytes(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->fusion_threshold_bytes();
}

double hvd_pm_cycle_ms(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->cycle_time_ms();
}

int hvd_pm_hierarchical_allreduce(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->hierarchical_allreduce()
             ? 1
             : 0;
}

int hvd_pm_hierarchical_allgather(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->hierarchical_allgather()
             ? 1
             : 0;
}

int hvd_pm_cache_enabled(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->cache_enabled() ? 1 : 0;
}

int hvd_pm_compression_enabled(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->compression_enabled() ? 1
                                                                        : 0;
}

int64_t hvd_pm_ring_segment_bytes(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->ring_segment_bytes();
}

int hvd_pm_ring_stripes(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->ring_stripes();
}

int hvd_pm_schedule(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->schedule();
}

int hvd_pm_tuning(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->tuning() ? 1 : 0;
}

double hvd_pm_best_score(void* pm) {
  return static_cast<hvd::ParameterManager*>(pm)->best_score();
}

}  // extern "C"
