// C boundary for ctypes (the reference exposes extern "C" basics the same
// way: horovod/common/operations.cc:663-797 consumed by
// horovod/common/basics.py).  All blocking entry points release the GIL on
// the Python side automatically because ctypes drops it around foreign
// calls.
#include <cstring>

#include "core.h"

using hvd::Core;
using hvd::CoreConfig;

extern "C" {

void* hvd_core_create(int size) {
  return new Core(CoreConfig::FromEnv(size));
}

void hvd_core_start(void* core) { static_cast<Core*>(core)->Start(); }

void hvd_core_shutdown(void* core) { static_cast<Core*>(core)->Shutdown(); }

void hvd_core_destroy(void* core) { delete static_cast<Core*>(core); }

// Returns 0 on success; -1 with the error copied into err_buf otherwise.
int hvd_core_enqueue(void* core, const uint8_t* data, size_t len,
                     char* err_buf, size_t err_cap) {
  std::string error;
  if (static_cast<Core*>(core)->Enqueue(data, len, &error)) return 0;
  if (err_buf && err_cap > 0) {
    strncpy(err_buf, error.c_str(), err_cap - 1);
    err_buf[err_cap - 1] = '\0';
  }
  return -1;
}

void hvd_core_join(void* core, int rank, uint64_t req_id) {
  static_cast<Core*>(core)->Join(rank, req_id);
}

// Blocks until a batch is available (GIL released by ctypes).  The returned
// buffer is owned by the caller; free with hvd_core_free.
uint8_t* hvd_core_next_batch(void* core, size_t* out_len) {
  std::vector<uint8_t> batch = static_cast<Core*>(core)->NextBatch();
  uint8_t* out = static_cast<uint8_t*>(malloc(batch.size()));
  memcpy(out, batch.data(), batch.size());
  *out_len = batch.size();
  return out;
}

void hvd_core_free(uint8_t* buf) { free(buf); }

void hvd_core_mark_done(void* core, uint64_t batch_id, const char* error) {
  static_cast<Core*>(core)->MarkDone(batch_id, error);
}

uint64_t hvd_core_cache_hits(void* core) {
  return static_cast<Core*>(core)->cache_hits();
}

uint64_t hvd_core_cache_misses(void* core) {
  return static_cast<Core*>(core)->cache_misses();
}

uint64_t hvd_core_cache_size(void* core) {
  return static_cast<Core*>(core)->cache_size();
}

}  // extern "C"
