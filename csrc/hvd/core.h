// The native coordination core: background cycle loop + negotiation.
//
// TPU-native re-design of the reference's core runtime
// (horovod/common/operations.cc BackgroundThreadLoop/RunLoopOnce,
// controller.cc ComputeResponseList/FuseResponses, tensor_queue.cc,
// response_cache.cc, stall_inspector.cc).  Differences by design:
//
// - Tensor data never crosses into this layer.  Rank threads enqueue
//   METADATA requests; the core negotiates readiness, validates cross-rank
//   agreement, fuses compatible allreduces into buckets, and publishes
//   ResponseBatches.  A Python dispatcher thread (blocked in NextBatch with
//   the GIL released) executes each batch as ONE compiled XLA program over
//   the device mesh and reports completion via MarkDone.
// - The reference's network control plane (MPI gather/bcast of request
//   lists) collapses to a process-local table in single-process mode; the
//   TCP controller (multi-process mode) reuses this same negotiation code
//   with a socket transport underneath.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <list>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "message.h"
#include "parameter_manager.h"
#include "timeline.h"

namespace hvd {

using Clock = std::chrono::steady_clock;

// ---------------------------------------------------------------------------
// TensorQueue: producer side of the coordination loop (reference:
// horovod/common/tensor_queue.{h,cc} — mutex-protected FIFO of pending
// requests, drained once per cycle).
class TensorQueue {
 public:
  void Push(Request req) {
    std::lock_guard<std::mutex> lock(mu_);
    queue_.push_back(std::move(req));
  }
  std::vector<Request> Drain() {
    std::lock_guard<std::mutex> lock(mu_);
    std::vector<Request> out(std::make_move_iterator(queue_.begin()),
                             std::make_move_iterator(queue_.end()));
    queue_.clear();
    return out;
  }
  size_t Size() {
    std::lock_guard<std::mutex> lock(mu_);
    return queue_.size();
  }

 private:
  std::mutex mu_;
  std::deque<Request> queue_;
};

// ---------------------------------------------------------------------------
// ResponseCache: steady-state signature cache (reference:
// horovod/common/response_cache.{h,cc}).  In the reference a cache hit lets
// workers skip the coordinator round trip by agreeing on cached bit
// positions.  Here the position list plays the same role for the TCP
// controller's bitvector fast path, and hit statistics feed autotuning.
class ResponseCache {
 public:
  enum class State { kMiss, kHit, kInvalid };

  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}

  // Classify a request against the cached signature for its name.
  State Lookup(const Request& req) const;
  // Record the signature of an executed response; evicts LRU beyond
  // capacity.  Returns the cache bit position assigned to this name.
  int Put(const Request& req);
  void Invalidate(const std::string& name);
  size_t size() const {
    std::lock_guard<std::mutex> lock(mu_);
    return entries_.size();
  }
  uint64_t hits() const {
    std::lock_guard<std::mutex> lock(mu_);
    return hits_;
  }
  uint64_t misses() const {
    std::lock_guard<std::mutex> lock(mu_);
    return misses_;
  }

 private:
  struct Signature {
    RequestType type;
    DataType dtype;
    std::vector<int64_t> shape;
    ReduceOp op;
    int32_t root_rank;
    double prescale, postscale;
    std::vector<int64_t> splits;
    int bit;  // stable position for cross-rank bitvector agreement
  };
  bool Matches(const Signature& sig, const Request& req) const;

  size_t capacity_;
  // Lookup/Put run on the background thread; Invalidate on the
  // dispatcher thread (MarkDone with an error); stats from any Python
  // thread — one lock guards it all.
  mutable std::mutex mu_;
  mutable uint64_t hits_ = 0;
  mutable uint64_t misses_ = 0;
  int next_bit_ = 0;
  std::list<std::string> lru_;  // front = most recent
  std::unordered_map<std::string,
                     std::pair<Signature, std::list<std::string>::iterator>>
      entries_;
};

// ---------------------------------------------------------------------------
// Core: the background coordination loop.
class Core {
 public:
  explicit Core(const CoreConfig& config);
  ~Core();

  void Start();
  void Shutdown();
  // Closes the timeline.  Separate from Shutdown(): the dispatcher
  // thread may still deliver MarkDone (timeline End events) after the
  // bg loop stops; callers invoke Finalize once the dispatcher has
  // drained.  Idempotent.
  void Finalize();

  // Producer API (rank threads, via the C boundary).  Returns false with
  // *error set if the core is shut down or in a stall-shutdown state.
  bool Enqueue(const uint8_t* data, size_t len, std::string* error);
  void Join(int32_t rank, uint64_t req_id);

  // Dispatcher API.  NextBatch blocks until work or shutdown.
  std::vector<uint8_t> NextBatch();
  void MarkDone(uint64_t batch_id, const char* error_or_null);

  // Introspection (tests, autotune).
  uint64_t cache_hits() const { return cache_.hits(); }
  uint64_t cache_misses() const { return cache_.misses(); }
  size_t cache_size() const { return cache_.size(); }

  // Live tuned values (the Python dispatcher polls these to pick the data
  // plane's fusion limit, cycle time and hierarchy; reference: tuned
  // parameters broadcast via Controller::SynchronizeParameters).
  const ParameterManager& params() const { return params_; }

 private:
  struct NameEntry {
    Clock::time_point first_ts;
    RequestType type;
    std::map<int32_t, Request> requests;  // rank -> request
    bool stall_warned = false;
  };

  void BackgroundLoop();
  void RunCycle();
  // Validate cross-rank agreement and build an (unfused) response
  // (reference: controller.cc:378 ConstructResponse).
  Response ConstructResponse(const std::string& name, NameEntry& entry);
  // Bucket compatible allreduces under the fusion threshold (reference:
  // controller.cc:640 FuseResponses).
  void FuseAndPublish(std::vector<Response> ready);
  void PublishBatch(std::vector<Response> responses);
  void CheckStalls();
  void FailAllPending(const std::string& message);

  CoreConfig config_;
  Timeline timeline_;
  TensorQueue tensor_queue_;
  ResponseCache cache_;
  ParameterManager params_;
  Clock::time_point epoch_;

  std::mutex state_mu_;
  std::condition_variable wakeup_;
  bool running_ = false;
  std::string shutdown_error_;
  std::set<int32_t> joined_;
  std::vector<int32_t> join_order_;
  std::map<int32_t, uint64_t> join_req_ids_;
  std::thread bg_thread_;

  // Coordinator-thread-only state.
  std::vector<std::pair<std::string, NameEntry>> table_;  // arrival order
  std::set<int32_t> joined_view_;

  // Completion queue toward the dispatcher.
  std::mutex out_mu_;
  std::condition_variable out_cv_;
  std::deque<std::vector<uint8_t>> out_queue_;
  uint64_t next_batch_id_ = 1;
  std::unordered_map<uint64_t, std::vector<std::string>> in_flight_;
};

}  // namespace hvd
