#include "core.h"

#include <algorithm>
#include <sstream>

namespace hvd {

// ------------------------------------------------------------ ResponseCache
// sig-exempt: compression, schedule, group, group_ranks, ring — the
// native Request does not carry the wire/transport knobs: the Python
// layer resolves them before dispatch and the native plane keys on the
// tensor facts only (message.h:78).
// req-exempt: JOIN — joins never travel through the native collective
// dispatch; the native core has no elastic path.
bool ResponseCache::Matches(const Signature& sig, const Request& req) const {
  return sig.type == req.type && sig.dtype == req.dtype &&
         sig.shape == req.shape && sig.op == req.op &&
         sig.root_rank == req.root_rank && sig.prescale == req.prescale &&
         sig.postscale == req.postscale && sig.splits == req.splits;
}

ResponseCache::State ResponseCache::Lookup(const Request& req) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(req.name);
  if (it == entries_.end()) {
    ++misses_;
    return State::kMiss;
  }
  if (Matches(it->second.first, req)) {
    ++hits_;
    return State::kHit;
  }
  return State::kInvalid;
}

int ResponseCache::Put(const Request& req) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(req.name);
  if (it != entries_.end()) {
    lru_.erase(it->second.second);
    lru_.push_front(req.name);
    it->second.first = Signature{req.type,      req.dtype,   req.shape,
                                 req.op,        req.root_rank, req.prescale,
                                 req.postscale, req.splits,
                                 it->second.first.bit};
    it->second.second = lru_.begin();
    return it->second.first.bit;
  }
  if (entries_.size() >= capacity_ && !lru_.empty()) {
    entries_.erase(lru_.back());
    lru_.pop_back();
  }
  lru_.push_front(req.name);
  int bit = next_bit_++;
  entries_.emplace(req.name,
                   std::make_pair(Signature{req.type, req.dtype, req.shape,
                                            req.op, req.root_rank,
                                            req.prescale, req.postscale,
                                            req.splits, bit},
                                  lru_.begin()));
  return bit;
}

void ResponseCache::Invalidate(const std::string& name) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(name);
  if (it == entries_.end()) return;
  lru_.erase(it->second.second);
  entries_.erase(it);
}

// --------------------------------------------------------------------- Core
namespace {
ParameterManager::Options PmOptions(const CoreConfig& c) {
  ParameterManager::Options o;
  o.active = c.autotune;
  o.warmup_samples = c.autotune_warmup_samples;
  o.steady_state_samples = c.autotune_steady_state_samples;
  o.bayes_opt_max_samples = c.autotune_bayes_opt_max_samples;
  o.gaussian_process_noise = c.autotune_gaussian_process_noise;
  o.log_path = c.autotune_log;
  o.fusion_threshold_bytes = c.fusion_threshold_bytes;
  o.cycle_time_ms = c.cycle_time_ms;
  o.hierarchical_allreduce = c.hierarchical_allreduce;
  o.hierarchical_allgather = c.hierarchical_allgather;
  return o;
}
}  // namespace

Core::Core(const CoreConfig& config)
    : config_(config),
      cache_(static_cast<size_t>(config.cache_capacity)),
      params_(PmOptions(config)),
      epoch_(Clock::now()) {}

Core::~Core() {
  Shutdown();
  Finalize();
}

void Core::Start() {
  {
    std::lock_guard<std::mutex> lock(state_mu_);
    if (running_) return;
    running_ = true;
  }
  timeline_.Open(config_.timeline_path, config_.timeline_mark_cycles);
  bg_thread_ = std::thread(&Core::BackgroundLoop, this);
}

void Core::Shutdown() {
  {
    std::lock_guard<std::mutex> lock(state_mu_);
    if (!running_) return;
    running_ = false;
  }
  wakeup_.notify_all();
  if (bg_thread_.joinable()) bg_thread_.join();
  // Publish the shutdown sentinel so the dispatcher exits (reference:
  // ResponseList::shutdown flag).
  ResponseBatch batch;
  batch.shutdown = true;
  {
    std::lock_guard<std::mutex> lock(out_mu_);
    out_queue_.push_back(batch.Encode());
  }
  out_cv_.notify_all();
}

void Core::Finalize() { timeline_.Close(); }

bool Core::Enqueue(const uint8_t* data, size_t len, std::string* error) {
  Reader r(data, len);
  Request req = Request::Decode(&r);
  if (!r.ok()) {
    *error = "malformed request";
    return false;
  }
  {
    std::lock_guard<std::mutex> lock(state_mu_);
    if (!running_) {
      *error = "horovod_tpu has been shut down";
      return false;
    }
    if (!shutdown_error_.empty()) {
      *error = shutdown_error_;
      return false;
    }
  }
  tensor_queue_.Push(std::move(req));
  wakeup_.notify_one();
  return true;
}

void Core::Join(int32_t rank, uint64_t req_id) {
  {
    std::lock_guard<std::mutex> lock(state_mu_);
    if (joined_.insert(rank).second) {
      join_order_.push_back(rank);
    }
    join_req_ids_[rank] = req_id;
  }
  wakeup_.notify_one();
}

std::vector<uint8_t> Core::NextBatch() {
  std::unique_lock<std::mutex> lock(out_mu_);
  out_cv_.wait(lock, [&] { return !out_queue_.empty(); });
  std::vector<uint8_t> out = std::move(out_queue_.front());
  out_queue_.pop_front();
  return out;
}

void Core::MarkDone(uint64_t batch_id, const char* error_or_null) {
  std::vector<std::string> names;
  {
    std::lock_guard<std::mutex> lock(out_mu_);
    auto it = in_flight_.find(batch_id);
    if (it == in_flight_.end()) return;
    names = std::move(it->second);
    in_flight_.erase(it);
  }
  for (const auto& name : names) {
    timeline_.End(name);
    if (error_or_null != nullptr) cache_.Invalidate(name);
  }
}

void Core::BackgroundLoop() {
  // Reference: operations.cc:550 RunLoopOnce under a ~cycle_time wait.  The
  // cycle time is re-read each iteration so the autotuner can steer it.
  std::unique_lock<std::mutex> lock(state_mu_);
  while (running_) {
    auto cycle =
        std::chrono::duration<double, std::milli>(params_.cycle_time_ms());
    wakeup_.wait_for(lock, cycle);
    if (!running_) break;
    lock.unlock();
    timeline_.MarkCycle();
    RunCycle();
    lock.lock();
  }
  lock.unlock();
  // Drain: fail anything still pending so no handle hangs.
  FailAllPending("horovod_tpu has been shut down");
}

void Core::RunCycle() {
  {
    std::lock_guard<std::mutex> lock(state_mu_);
    joined_view_ = joined_;
  }

  // 1. absorb new requests (reference: PopMessagesFromQueue).
  for (Request& req : tensor_queue_.Drain()) {
    auto it = std::find_if(table_.begin(), table_.end(),
                           [&](const auto& kv) { return kv.first == req.name; });
    if (it == table_.end()) {
      NameEntry entry;
      entry.first_ts = Clock::now();
      entry.type = req.type;
      timeline_.Begin(req.name,
                      std::string("NEGOTIATE_") +
                          (req.type == RequestType::kAllreduce ? "ALLREDUCE"
                           : req.type == RequestType::kAllgather ? "ALLGATHER"
                           : req.type == RequestType::kBroadcast ? "BROADCAST"
                           : req.type == RequestType::kAlltoall ? "ALLTOALL"
                           : req.type == RequestType::kAdasum   ? "ADASUM"
                           : req.type == RequestType::kReduceScatter
                               ? "REDUCE_SCATTER"
                               : "JOIN"));
      table_.emplace_back(req.name, std::move(entry));
      it = std::prev(table_.end());
    }
    NameEntry& entry = it->second;
    if (entry.requests.count(req.rank)) {
      // Duplicate before completion: error just this request.
      Response resp;
      resp.type = ResponseType::kError;
      resp.error = "duplicate request for tensor '" + req.name +
                   "' from rank " + std::to_string(req.rank) +
                   " before previous one completed";
      ResponseEntry re;
      re.name = req.name;
      re.ranks.push_back(req.rank);
      re.req_ids.push_back(req.req_id);
      resp.entries.push_back(std::move(re));
      PublishBatch({std::move(resp)});
      continue;
    }
    timeline_.Instant(req.name, std::to_string(req.rank));
    entry.requests.emplace(req.rank, std::move(req));
  }

  // 2. stall inspection (reference: stall_inspector.cc).
  if (!config_.stall_check_disable) CheckStalls();

  // 3. collect ready names in arrival order — the deterministic execution
  // order all ranks observe (reference: rank-0 response ordering).
  std::vector<Response> ready;
  size_t needed = static_cast<size_t>(config_.size) - joined_view_.size();
  for (auto it = table_.begin(); it != table_.end();) {
    NameEntry& entry = it->second;
    size_t have = 0;
    for (const auto& kv : entry.requests) {
      if (!joined_view_.count(kv.first)) ++have;
    }
    // ready once every live (non-joined) rank contributed; when ALL
    // ranks have joined (needed == 0) a leftover entry — submitted
    // before its ranks joined — is trivially ready and reduces over the
    // submitters, otherwise the join barrier below (which requires an
    // empty table) could never fire
    if (have >= needed) {
      timeline_.End(it->first);
      ready.push_back(ConstructResponse(it->first, entry));
      it = table_.erase(it);
    } else {
      ++it;
    }
  }

  // 4. fuse + publish.
  FuseAndPublish(std::move(ready));

  // 4b. autotune window bookkeeping (reference: ParameterManager::Update
  // called from the controller per response list).
  if (params_.tuning()) {
    params_.Update(std::chrono::duration<double>(Clock::now() - epoch_)
                       .count());
  }

  // 5. join barrier: all ranks joined and nothing pending -> complete joins
  // with the last rank to join (reference: controller joined handling).
  std::vector<std::pair<int32_t, uint64_t>> join_done;
  int32_t last_rank = -1;
  {
    std::lock_guard<std::mutex> lock(state_mu_);
    if (!joined_.empty() &&
        joined_.size() == static_cast<size_t>(config_.size) &&
        table_.empty() && tensor_queue_.Size() == 0) {
      last_rank = join_order_.back();
      for (const auto& kv : join_req_ids_) {
        join_done.emplace_back(kv.first, kv.second);
      }
      join_req_ids_.clear();
      join_order_.clear();
      joined_.clear();
    }
  }
  if (!join_done.empty()) {
    Response resp;
    resp.type = ResponseType::kJoin;
    ResponseEntry re;
    re.name = "join";
    re.root_rank = last_rank;  // payload: the last rank to join
    for (const auto& kv : join_done) {
      re.ranks.push_back(kv.first);
      re.req_ids.push_back(kv.second);
    }
    resp.entries.push_back(std::move(re));
    PublishBatch({std::move(resp)});
  }
}

Response Core::ConstructResponse(const std::string& name, NameEntry& entry) {
  auto error = [&](const std::string& message) {
    Response resp;
    resp.type = ResponseType::kError;
    resp.error = message;
    ResponseEntry re;
    re.name = name;
    for (const auto& kv : entry.requests) {
      re.ranks.push_back(kv.first);
      re.req_ids.push_back(kv.second.req_id);
    }
    resp.entries.push_back(std::move(re));
    return resp;
  };

  const Request& first = entry.requests.begin()->second;

  for (const auto& kv : entry.requests) {
    if (kv.second.type != entry.type) {
      return error("mismatched collective types for tensor '" + name + "'");
    }
  }

  if (!joined_view_.empty() && (entry.type == RequestType::kAllgather ||
                                entry.type == RequestType::kBroadcast ||
                                entry.type == RequestType::kAlltoall ||
                                entry.type == RequestType::kReduceScatter)) {
    const char* tname =
        entry.type == RequestType::kAllgather ? "ALLGATHER"
        : entry.type == RequestType::kBroadcast ? "BROADCAST"
        : entry.type == RequestType::kReduceScatter ? "REDUCE_SCATTER"
                                                    : "ALLTOALL";
    return error(std::string(tname) +
                 " is not supported while ranks have joined");
  }

  for (const auto& kv : entry.requests) {
    if (kv.second.dtype != first.dtype) {
      return error("mismatched dtypes for tensor '" + name + "'");
    }
  }

  switch (entry.type) {
    case RequestType::kAllreduce:
    case RequestType::kAdasum:
    case RequestType::kReduceScatter: {
      if (entry.type == RequestType::kReduceScatter && first.shape.empty()) {
        return error("reduce_scatter '" + name +
                     "': 0-d tensors are not supported; reshape to (1,) "
                     "first");
      }
      for (const auto& kv : entry.requests) {
        const Request& r = kv.second;
        if (r.op != first.op) {
          return error("mismatched reduce ops for tensor '" + name + "'");
        }
        if (r.prescale != first.prescale || r.postscale != first.postscale) {
          return error("mismatched scale factors for tensor '" + name + "'");
        }
        if (r.shape != first.shape) {
          return error("mismatched shapes for allreduce '" + name + "'");
        }
      }
      break;
    }
    case RequestType::kAllgather: {
      for (const auto& kv : entry.requests) {
        const Request& r = kv.second;
        if (r.shape.size() != first.shape.size()) {
          return error("mismatched tensor ranks for allgather '" + name +
                       "'");
        }
        if (r.shape.empty()) {
          return error("allgather '" + name +
                       "': 0-d tensors are not supported; reshape to (1,) "
                       "first");
        }
        if (!std::equal(r.shape.begin() + 1, r.shape.end(),
                        first.shape.begin() + 1, first.shape.end())) {
          return error("mismatched trailing dimensions for allgather '" +
                       name + "'");
        }
      }
      break;
    }
    case RequestType::kBroadcast: {
      for (const auto& kv : entry.requests) {
        const Request& r = kv.second;
        if (r.root_rank != first.root_rank) {
          return error("mismatched root ranks for broadcast '" + name + "'");
        }
        if (r.shape != first.shape) {
          return error("mismatched shapes for broadcast '" + name + "'");
        }
      }
      break;
    }
    case RequestType::kAlltoall: {
      for (const auto& kv : entry.requests) {
        const Request& r = kv.second;
        if (r.splits.size() != static_cast<size_t>(config_.size)) {
          return error("alltoall '" + name +
                       "': splits must have one entry per rank (" +
                       std::to_string(config_.size) + "), got " +
                       std::to_string(r.splits.size()));
        }
        int64_t total = 0;
        for (int64_t s : r.splits) total += s;
        int64_t dim0 = r.shape.empty() ? 0 : r.shape[0];
        if (total != dim0) {
          return error("alltoall '" + name + "': splits sum " +
                       std::to_string(total) + " != first dimension " +
                       std::to_string(dim0));
        }
      }
      break;
    }
    default:
      break;
  }

  // Cache bookkeeping: record the steady-state signature (reference puts
  // executed responses in the cache so the next cycle takes the fast path).
  // The autotuner may switch the cache off (reference: CacheEnabled
  // categorical parameter).
  if (params_.cache_enabled()) {
    cache_.Lookup(first);
    cache_.Put(first);
  }

  Response resp;
  switch (entry.type) {
    case RequestType::kAllreduce: resp.type = ResponseType::kAllreduce; break;
    case RequestType::kAllgather: resp.type = ResponseType::kAllgather; break;
    case RequestType::kBroadcast: resp.type = ResponseType::kBroadcast; break;
    case RequestType::kAdasum:    resp.type = ResponseType::kAdasum;    break;
    case RequestType::kAlltoall:  resp.type = ResponseType::kAlltoall;  break;
    case RequestType::kReduceScatter:
      resp.type = ResponseType::kReduceScatter;
      break;
    default:                      resp.type = ResponseType::kError;     break;
  }
  resp.op = first.op;
  resp.dtype = first.dtype;
  resp.prescale = first.prescale;
  resp.postscale = first.postscale;
  resp.fused_bytes = first.ByteSize();
  ResponseEntry re;
  re.name = name;
  re.root_rank = first.root_rank;
  for (const auto& kv : entry.requests) {
    re.ranks.push_back(kv.first);
    re.req_ids.push_back(kv.second.req_id);
  }
  for (int32_t j : joined_view_) {
    if (!entry.requests.count(j)) re.joined.push_back(j);
  }
  resp.entries.push_back(std::move(re));
  return resp;
}

void Core::FuseAndPublish(std::vector<Response> ready) {
  if (ready.empty()) return;
  std::vector<Response> out;
  ptrdiff_t bucket = -1;  // index into out (push_back may reallocate)
  int64_t bucket_bytes = 0;

  const int64_t fusion_threshold = params_.fusion_threshold_bytes();
  for (Response& resp : ready) {
    if (resp.type != ResponseType::kError) params_.Record(resp.fused_bytes);
    if (resp.type == ResponseType::kAllreduce && resp.error.empty()) {
      bool compatible =
          bucket >= 0 && out[bucket].dtype == resp.dtype &&
          out[bucket].op == resp.op && out[bucket].prescale == resp.prescale &&
          out[bucket].postscale == resp.postscale &&
          bucket_bytes + resp.fused_bytes <= fusion_threshold;
      if (compatible) {
        bucket_bytes += resp.fused_bytes;
        out[bucket].fused_bytes = bucket_bytes;
        for (auto& e : resp.entries) {
          out[bucket].entries.push_back(std::move(e));
        }
      } else {
        out.push_back(std::move(resp));
        bucket = static_cast<ptrdiff_t>(out.size()) - 1;
        bucket_bytes = out[bucket].fused_bytes;
      }
    } else {
      out.push_back(std::move(resp));
      bucket = -1;
      bucket_bytes = 0;
    }
  }
  PublishBatch(std::move(out));
}

void Core::PublishBatch(std::vector<Response> responses) {
  if (responses.empty()) return;
  ResponseBatch batch;
  std::vector<std::string> names;
  for (auto& resp : responses) {
    const char* phase =
        resp.type == ResponseType::kAllreduce ? "ALLREDUCE"
        : resp.type == ResponseType::kAllgather ? "ALLGATHER"
        : resp.type == ResponseType::kBroadcast ? "BROADCAST"
        : resp.type == ResponseType::kAlltoall ? "ALLTOALL"
        : resp.type == ResponseType::kAdasum   ? "ADASUM"
        : resp.type == ResponseType::kReduceScatter ? "REDUCE_SCATTER"
        : resp.type == ResponseType::kJoin     ? "JOIN"
                                               : "ERROR";
    if (resp.type != ResponseType::kError &&
        resp.type != ResponseType::kJoin) {
      for (const auto& e : resp.entries) {
        timeline_.Begin(e.name, phase);
        names.push_back(e.name);
      }
    }
    batch.responses.push_back(std::move(resp));
  }
  {
    std::lock_guard<std::mutex> lock(out_mu_);
    batch.batch_id = next_batch_id_++;
    if (!names.empty()) in_flight_[batch.batch_id] = std::move(names);
    out_queue_.push_back(batch.Encode());
  }
  out_cv_.notify_one();
}

void Core::CheckStalls() {
  auto now = Clock::now();
  for (auto& kv : table_) {
    NameEntry& entry = kv.second;
    double age =
        std::chrono::duration<double>(now - entry.first_ts).count();
    if (age > config_.stall_warning_sec && !entry.stall_warned) {
      std::ostringstream ready, missing;
      ready << "[";
      bool first = true;
      for (const auto& r : entry.requests) {
        ready << (first ? "" : ", ") << r.first;
        first = false;
      }
      ready << "]";
      missing << "[";
      first = true;
      for (int32_t r = 0; r < config_.size; ++r) {
        if (!entry.requests.count(r) && !joined_view_.count(r)) {
          missing << (first ? "" : ", ") << r;
          first = false;
        }
      }
      missing << "]";
      HVD_LOG(Warning)
          << "One or more tensors were submitted to be reduced, gathered or "
             "broadcasted by subset of ranks and are waiting for remainder "
             "of ranks for more than "
          << static_cast<int>(config_.stall_warning_sec)
          << "s. Stalled tensor: " << kv.first
          << " ready ranks: " << ready.str()
          << ", waiting on: " << missing.str();
      entry.stall_warned = true;
    }
    if (config_.stall_shutdown_sec > 0 && age > config_.stall_shutdown_sec) {
      std::string message = "stalled tensor '" + kv.first +
                            "' exceeded shutdown threshold of " +
                            std::to_string(config_.stall_shutdown_sec) + "s";
      HVD_LOG(Error) << message;
      {
        std::lock_guard<std::mutex> lock(state_mu_);
        shutdown_error_ = message;
      }
      FailAllPending(message);
      return;
    }
  }
}

void Core::FailAllPending(const std::string& message) {
  std::vector<Response> errors;
  for (auto& kv : table_) {
    Response resp;
    resp.type = ResponseType::kError;
    resp.error = message;
    ResponseEntry re;
    re.name = kv.first;
    for (const auto& r : kv.second.requests) {
      re.ranks.push_back(r.first);
      re.req_ids.push_back(r.second.req_id);
    }
    resp.entries.push_back(std::move(re));
    errors.push_back(std::move(resp));
  }
  table_.clear();
  for (Request& req : tensor_queue_.Drain()) {
    Response resp;
    resp.type = ResponseType::kError;
    resp.error = message;
    ResponseEntry re;
    re.name = req.name;
    re.ranks.push_back(req.rank);
    re.req_ids.push_back(req.req_id);
    resp.entries.push_back(std::move(re));
    errors.push_back(std::move(resp));
  }
  if (!errors.empty()) PublishBatch(std::move(errors));
}

}  // namespace hvd
