// Wire messages: what ranks ask for and what the coordinator answers.
//
// TPU-native analog of the reference's Request/Response message classes and
// FlatBuffers schema (reference: horovod/common/message.{h,cc},
// horovod/common/wire/message.fbs).  Serialization is a compact hand-rolled
// little-endian codec (wire.h-style length-prefixed fields) shared by the
// C-API boundary (core <-> Python dispatcher) and the TCP controller
// transport, so one format serves both the in-process and the
// cross-process paths.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvd {

// ------------------------------------------------------------------- codec
class Writer {
 public:
  void U8(uint8_t v) { buf_.push_back(v); }
  void I32(int32_t v) { Raw(&v, 4); }
  void U32(uint32_t v) { Raw(&v, 4); }
  void U64(uint64_t v) { Raw(&v, 8); }
  void I64(int64_t v) { Raw(&v, 8); }
  void F64(double v) { Raw(&v, 8); }
  void Str(const std::string& s) {
    U32(static_cast<uint32_t>(s.size()));
    Raw(s.data(), s.size());
  }
  const std::vector<uint8_t>& data() const { return buf_; }

 private:
  void Raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  std::vector<uint8_t> buf_;
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : p_(data), end_(data + len) {}
  uint8_t U8() { return *Take(1); }
  int32_t I32() { int32_t v; memcpy(&v, Take(4), 4); return v; }
  uint32_t U32() { uint32_t v; memcpy(&v, Take(4), 4); return v; }
  uint64_t U64() { uint64_t v; memcpy(&v, Take(8), 8); return v; }
  int64_t I64() { int64_t v; memcpy(&v, Take(8), 8); return v; }
  double F64() { double v; memcpy(&v, Take(8), 8); return v; }
  std::string Str() {
    uint32_t n = U32();
    // Bound the claimed length by the bytes actually present BEFORE
    // sizing anything: a lying length word must not buy an allocation,
    // and Take()'s zero-page fallback is only 8 bytes wide.
    if (!ok_ || static_cast<size_t>(end_ - p_) < n) {
      ok_ = false;
      return std::string();
    }
    const uint8_t* p = p_;
    p_ += n;
    return std::string(reinterpret_cast<const char*>(p), n);
  }
  bool ok() const { return ok_; }

 private:
  const uint8_t* Take(size_t n) {
    if (p_ + n > end_) {
      ok_ = false;
      static uint8_t zeros[8] = {0};
      return zeros;
    }
    const uint8_t* out = p_;
    p_ += n;
    return out;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

// ----------------------------------------------------------------- messages
// One rank's announcement that a named tensor is ready (reference:
// message.h:47 Request).
struct Request {
  uint64_t req_id = 0;
  int32_t rank = 0;
  RequestType type = RequestType::kAllreduce;
  ReduceOp op = ReduceOp::kSum;
  DataType dtype = DataType::kFloat32;
  int32_t root_rank = -1;
  double prescale = 1.0;
  double postscale = 1.0;
  std::string name;
  std::vector<int64_t> shape;
  std::vector<int64_t> splits;

  int64_t NumElements() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
  int64_t ByteSize() const {
    return NumElements() * static_cast<int64_t>(DataTypeSize(dtype));
  }

  void Encode(Writer* w) const;
  static Request Decode(Reader* r);
};

// One fused group entry: a named tensor with the per-rank request ids the
// dispatcher uses to look tensors up (reference: TensorTableEntry).
struct ResponseEntry {
  std::string name;
  std::vector<int32_t> ranks;      // ranks that submitted
  std::vector<uint64_t> req_ids;   // parallel to ranks
  std::vector<int32_t> joined;     // ranks substituted with zeros
  int32_t root_rank = -1;

  void Encode(Writer* w) const;
  static ResponseEntry Decode(Reader* r);
};

// A fused bucket: one XLA program's worth of work (reference: message.h:132
// Response after FuseResponses).
struct Response {
  ResponseType type = ResponseType::kAllreduce;
  ReduceOp op = ReduceOp::kSum;
  DataType dtype = DataType::kFloat32;
  double prescale = 1.0;
  double postscale = 1.0;
  std::string error;  // for kError: applies to every entry
  std::vector<ResponseEntry> entries;
  int64_t fused_bytes = 0;  // fusion accounting only; not serialized

  void Encode(Writer* w) const;
  static Response Decode(Reader* r);
};

// What the dispatcher receives per wakeup (reference: ResponseList with
// shutdown flag).
struct ResponseBatch {
  uint64_t batch_id = 0;
  bool shutdown = false;
  std::vector<Response> responses;

  std::vector<uint8_t> Encode() const;
  static ResponseBatch Decode(const uint8_t* data, size_t len);
};

}  // namespace hvd
