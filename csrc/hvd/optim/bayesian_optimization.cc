#include "bayesian_optimization.h"

#include <cmath>
#include <limits>

namespace hvd {
namespace optim {

namespace {
const double kInvSqrt2 = 0.7071067811865476;
const double kInvSqrt2Pi = 0.3989422804014327;

double NormCdf(double z) { return 0.5 * (1.0 + std::erf(z * kInvSqrt2)); }
double NormPdf(double z) { return kInvSqrt2Pi * std::exp(-0.5 * z * z); }
}  // namespace

double ExpectedImprovement(double mean, double stddev, double best,
                           double xi) {
  double improvement = mean - best - xi;
  if (stddev <= 0.0) return improvement > 0.0 ? improvement : 0.0;
  double z = improvement / stddev;
  return improvement * NormCdf(z) + stddev * NormPdf(z);
}

double HaltonElement(int index, int base) {
  double f = 1.0, r = 0.0;
  int i = index;
  while (i > 0) {
    f /= base;
    r += f * (i % base);
    i /= base;
  }
  return r;
}

BayesianOptimizer::BayesianOptimizer(std::vector<double> low,
                                     std::vector<double> high,
                                     double gp_noise_variance,
                                     int num_candidates)
    : low_(std::move(low)),
      high_(std::move(high)),
      gp_noise_variance_(gp_noise_variance),
      num_candidates_(num_candidates),
      best_y_(-std::numeric_limits<double>::infinity()) {}

void BayesianOptimizer::AddSample(const std::vector<double>& x, double y) {
  x_.push_back(x);
  y_.push_back(y);
  if (y > best_y_) {
    best_y_ = y;
    best_x_ = x;
  }
}

std::vector<double> BayesianOptimizer::Candidate(int index) const {
  // Low-discrepancy point: per-dimension Halton with coprime bases.
  static const int kBases[] = {2, 3, 5, 7, 11, 13};
  std::vector<double> x(low_.size());
  for (size_t d = 0; d < low_.size(); ++d) {
    double u = HaltonElement(index + 1, kBases[d % 6]);
    x[d] = low_[d] + u * (high_[d] - low_[d]);
  }
  return x;
}

std::vector<double> BayesianOptimizer::Suggest() {
  size_t dim = low_.size();
  // Seed phase: center first, then Halton points, until the surrogate has
  // enough support (>= dim + 2 samples).
  if (x_.size() < dim + 2) {
    if (seeds_used_ == 0) {
      ++seeds_used_;
      std::vector<double> center(dim);
      for (size_t d = 0; d < dim; ++d) center[d] = 0.5 * (low_[d] + high_[d]);
      return center;
    }
    return Candidate(17 * seeds_used_++);  // stride the sequence for spread
  }

  // Normalize y to zero mean / unit scale for GP conditioning.
  double mean_y = 0.0;
  for (double y : y_) mean_y += y;
  mean_y /= y_.size();
  double var_y = 0.0;
  for (double y : y_) var_y += (y - mean_y) * (y - mean_y);
  var_y /= y_.size();
  double scale = var_y > 1e-12 ? std::sqrt(var_y) : 1.0;

  // Normalize x into the unit box so one length scale fits all dims.
  auto norm = [&](const std::vector<double>& x) {
    std::vector<double> u(dim);
    for (size_t d = 0; d < dim; ++d) {
      double span = high_[d] - low_[d];
      u[d] = span > 0 ? (x[d] - low_[d]) / span : 0.0;
    }
    return u;
  };
  std::vector<std::vector<double>> xu(x_.size());
  std::vector<double> yn(y_.size());
  for (size_t i = 0; i < x_.size(); ++i) {
    xu[i] = norm(x_[i]);
    yn[i] = (y_[i] - mean_y) / scale;
  }

  GaussianProcess gp(/*length_scale=*/0.25, /*signal_variance=*/1.0,
                     gp_noise_variance_);
  if (!gp.Fit(xu, yn)) {
    return Candidate(17 * seeds_used_++);
  }

  double best_norm = (best_y_ - mean_y) / scale;
  double best_ei = -1.0;
  std::vector<double> best_cand = Candidate(0);
  for (int c = 0; c < num_candidates_; ++c) {
    std::vector<double> cand = Candidate(c);
    double m, v;
    gp.Predict(norm(cand), &m, &v);
    double ei = ExpectedImprovement(m, std::sqrt(v), best_norm);
    if (ei > best_ei) {
      best_ei = ei;
      best_cand = cand;
    }
  }
  return best_cand;
}

}  // namespace optim
}  // namespace hvd
