// Bayesian optimization for the autotuner.
//
// TPU-native re-design of the reference's optimizer (reference:
// horovod/common/optim/bayesian_optimization.{h,cc} — GP surrogate +
// expected-improvement acquisition, maximized with L-BFGS from random
// restarts).  This implementation maximizes EI over a deterministic
// low-discrepancy (Halton) candidate sweep instead of L-BFGS: the search
// space is 2-dimensional and tiny, a 256-point sweep is exhaustive enough,
// and determinism keeps every rank's tuner in lockstep without an extra
// broadcast (the reference must SynchronizeParameters from rank 0;
// determinism makes that a no-op here, though the PM still exposes the
// sync'd values).
#pragma once

#include <cstdint>
#include <vector>

#include "gaussian_process.h"

namespace hvd {
namespace optim {

// Expected improvement for MAXIMIZATION at a point with posterior
// (mean, stddev), given the best observed value so far and exploration
// margin xi.
double ExpectedImprovement(double mean, double stddev, double best,
                           double xi = 0.01);

// Element i of the base-`base` Halton sequence (1-indexed), in (0, 1).
double HaltonElement(int index, int base);

class BayesianOptimizer {
 public:
  // Bounds: per-dimension [low, high]; all suggestions live inside.
  BayesianOptimizer(std::vector<double> low, std::vector<double> high,
                    double gp_noise_variance = 1e-4,
                    int num_candidates = 256);

  void AddSample(const std::vector<double>& x, double y);

  // Next point to evaluate: the first few calls walk seed points (corners +
  // center of the box, then Halton points) before enough samples exist for
  // the surrogate; afterwards it is the EI argmax over the candidate sweep.
  std::vector<double> Suggest();

  size_t num_samples() const { return x_.size(); }
  const std::vector<double>& best_x() const { return best_x_; }
  double best_y() const { return best_y_; }

 private:
  std::vector<double> Candidate(int index) const;

  std::vector<double> low_, high_;
  double gp_noise_variance_;
  int num_candidates_;
  int seeds_used_ = 0;

  std::vector<std::vector<double>> x_;
  std::vector<double> y_;
  std::vector<double> best_x_;
  double best_y_;
};

}  // namespace optim
}  // namespace hvd
