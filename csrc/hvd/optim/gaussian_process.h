// Gaussian-process regression for the autotuner.
//
// TPU-native re-design of the reference's GP (reference:
// horovod/common/optim/gaussian_process.{h,cc} — Eigen-based GP with an
// RBF kernel used by the Bayesian-optimization autotuner).  This
// implementation is dependency-free: the (tiny — tens of samples) dense
// linear algebra is done with a hand-rolled Cholesky factorization.
//
// Model:  y ~ GP(0, k) + N(0, noise_variance)
//         k(a, b) = signal_variance * exp(-||a - b||^2 / (2 * length_scale^2))
// Posterior at x*:
//         mean = k*^T (K + noise I)^-1 y
//         var  = k(x*,x*) - k*^T (K + noise I)^-1 k*
#pragma once

#include <cstddef>
#include <vector>

namespace hvd {
namespace optim {

class GaussianProcess {
 public:
  explicit GaussianProcess(double length_scale = 1.0,
                           double signal_variance = 1.0,
                           double noise_variance = 1e-6)
      : length_scale_(length_scale),
        signal_variance_(signal_variance),
        noise_variance_(noise_variance) {}

  // Fit on n points of dimension d.  Returns false if the kernel matrix is
  // not positive definite (degenerate inputs).
  bool Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y);

  // Posterior mean and variance at a query point.  Requires Fit.  Variance
  // is clamped at zero.
  void Predict(const std::vector<double>& x, double* mean,
               double* variance) const;

  size_t num_samples() const { return x_.size(); }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double length_scale_;
  double signal_variance_;
  double noise_variance_;

  std::vector<std::vector<double>> x_;  // training inputs
  std::vector<double> alpha_;           // (K + noise I)^-1 y
  std::vector<double> chol_;            // lower Cholesky factor, row-major
};

// In-place Cholesky factorization of a symmetric positive-definite n x n
// row-major matrix; on success the lower triangle holds L with A = L L^T.
bool CholeskyFactor(std::vector<double>* a, size_t n);

// Solve L z = b (forward) then L^T x = z (backward) given the lower factor.
std::vector<double> CholeskySolve(const std::vector<double>& chol, size_t n,
                                  std::vector<double> b);

}  // namespace optim
}  // namespace hvd
