#include "gaussian_process.h"

#include <cmath>

namespace hvd {
namespace optim {

bool CholeskyFactor(std::vector<double>* a, size_t n) {
  std::vector<double>& m = *a;
  for (size_t j = 0; j < n; ++j) {
    double diag = m[j * n + j];
    for (size_t k = 0; k < j; ++k) diag -= m[j * n + k] * m[j * n + k];
    if (diag <= 0.0) return false;
    diag = std::sqrt(diag);
    m[j * n + j] = diag;
    for (size_t i = j + 1; i < n; ++i) {
      double v = m[i * n + j];
      for (size_t k = 0; k < j; ++k) v -= m[i * n + k] * m[j * n + k];
      m[i * n + j] = v / diag;
    }
    // zero the strict upper triangle so the factor is unambiguous
    for (size_t k = j + 1; k < n; ++k) m[j * n + k] = 0.0;
  }
  return true;
}

std::vector<double> CholeskySolve(const std::vector<double>& chol, size_t n,
                                  std::vector<double> b) {
  // forward: L z = b
  for (size_t i = 0; i < n; ++i) {
    double v = b[i];
    for (size_t k = 0; k < i; ++k) v -= chol[i * n + k] * b[k];
    b[i] = v / chol[i * n + i];
  }
  // backward: L^T x = z
  for (size_t ii = n; ii-- > 0;) {
    double v = b[ii];
    for (size_t k = ii + 1; k < n; ++k) v -= chol[k * n + ii] * b[k];
    b[ii] = v / chol[ii * n + ii];
  }
  return b;
}

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double sq = 0.0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    sq += d * d;
  }
  return signal_variance_ *
         std::exp(-sq / (2.0 * length_scale_ * length_scale_));
}

bool GaussianProcess::Fit(const std::vector<std::vector<double>>& x,
                          const std::vector<double>& y) {
  size_t n = x.size();
  std::vector<double> k(n * n);
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double v = Kernel(x[i], x[j]);
      if (i == j) v += noise_variance_;
      k[i * n + j] = v;
      k[j * n + i] = v;
    }
  }
  if (!CholeskyFactor(&k, n)) return false;
  x_ = x;
  chol_ = std::move(k);
  alpha_ = CholeskySolve(chol_, n, y);
  return true;
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mean,
                              double* variance) const {
  size_t n = x_.size();
  if (n == 0) {
    *mean = 0.0;
    *variance = signal_variance_;
    return;
  }
  std::vector<double> ks(n);
  for (size_t i = 0; i < n; ++i) ks[i] = Kernel(x_[i], x);
  double m = 0.0;
  for (size_t i = 0; i < n; ++i) m += ks[i] * alpha_[i];
  *mean = m;
  // var = k(x,x) - ks^T (K + nI)^-1 ks, via v = L^-1 ks, var = kxx - v.v
  std::vector<double> v = ks;
  for (size_t i = 0; i < n; ++i) {
    double t = v[i];
    for (size_t k = 0; k < i; ++k) t -= chol_[i * n + k] * v[k];
    v[i] = t / chol_[i * n + i];
  }
  double reduction = 0.0;
  for (size_t i = 0; i < n; ++i) reduction += v[i] * v[i];
  double var = Kernel(x, x) - reduction;
  *variance = var > 0.0 ? var : 0.0;
}

}  // namespace optim
}  // namespace hvd
