#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

namespace hvd {

namespace {
// Search space: log2(fusion MB) in [0, 8] (1 MB .. 256 MB), cycle in
// [1, 25] ms (reference tunes the same two knobs,
// parameter_manager.cc joint BayesianParameter).
const double kFusionLogLow = 0.0, kFusionLogHigh = 8.0;
const double kCycleLow = 1.0, kCycleHigh = 25.0;

int64_t FusionBytesFromLog2Mb(double log2_mb) {
  return static_cast<int64_t>(std::llround(std::pow(2.0, log2_mb))) * 1024 *
         1024;
}
}  // namespace

ParameterManager::ParameterManager(const Options& opts)
    : opts_(opts),
      discard_left_(opts.warmup_samples),
      best_fusion_log2_mb_(
          std::log2(std::max<double>(1.0, static_cast<double>(
                                              opts.fusion_threshold_bytes) /
                                              (1024.0 * 1024.0)))),
      best_cycle_ms_(opts.cycle_time_ms),
      best_cat_{opts.hierarchical_allreduce, opts.hierarchical_allgather,
                opts.cache_enabled, opts.compression,
                opts.ring_segment_bytes, opts.ring_stripes, opts.schedule},
      fusion_bytes_(opts.fusion_threshold_bytes),
      cycle_ms_(opts.cycle_time_ms),
      hier_allreduce_(opts.hierarchical_allreduce),
      hier_allgather_(opts.hierarchical_allgather),
      cache_enabled_(opts.cache_enabled),
      compression_(opts.compression),
      ring_segment_bytes_(opts.ring_segment_bytes),
      ring_stripes_(opts.ring_stripes),
      schedule_(opts.schedule),
      tuning_(opts.active),
      best_score_(0.0) {
  if (!opts.active) return;
  // Categorical walk (reference tries its CategoricalParameters
  // sequentially; same set here: hierarchy on/off, cache on/off, and —
  // when a compressor is configured — wire compression on/off).
  const bool comp = opts.compression;
  const int64_t seg = opts.ring_segment_bytes;
  const int str = opts.ring_stripes;
  const int sch = opts.schedule;
  walk_ = {
      {false, false, true, comp, seg, str, sch},
      {true, false, true, comp, seg, str, sch},
      {false, true, true, comp, seg, str, sch},
      {true, true, true, comp, seg, str, sch},
      {false, false, false, comp, seg, str, sch},
  };
  if (opts.compression_available) {
    // one probe of the opposite compression state at the default
    // schedule configuration — enough for the score to decide whether
    // the quantize overhead pays for the wire savings on this job
    walk_.push_back({false, false, true, !comp, seg, str, sch});
  }
  if (opts.schedule_tunable) {
    // collective-schedule probes for the tcp plane, tuned jointly with
    // segment/stripe/compression: explicitly measure the flat ring (1)
    // and the two-level hierarchical schedule (2) so the score decides
    // whether the topology-aware plan pays on this job (indices into
    // the SCHEDULES tuple shared with ops/tcp_dataplane.py; rhd/star
    // are latency-regime choices the auto resolver owns per tensor
    // size, so probing them against a bytes/sec score would be noise)
    if (sch != 1) walk_.push_back({false, false, true, comp, seg, str, 1});
    if (sch != 2) walk_.push_back({false, false, true, comp, seg, str, 2});
  }
  if (opts.ring_tunable) {
    // ring transfer-engine probes around the configured values at the
    // default schedule configuration: halve/double the pipeline
    // segment, double the stripe count.  Clamped to sane spans —
    // smaller segments trade per-frame overhead for overlap, more
    // stripes trade connections for per-stream throughput, and the
    // score decides what pays on this job's links.
    if (seg > 0) {
      // a probe whose clamp lands back on the configured value would
      // duplicate an existing walk entry (the seed-dedup pass below
      // only removes matches of the SEED categorical) and burn a full
      // probe window re-measuring the same point
      const int64_t seg_lo = std::max<int64_t>(seg / 2, 1 << 16);
      const int64_t seg_hi = std::min<int64_t>(seg * 2, 1 << 26);
      if (seg_lo != seg)
        walk_.push_back({false, false, true, comp, seg_lo, str, sch});
      if (seg_hi != seg)
        walk_.push_back({false, false, true, comp, seg_hi, str, sch});
    }
    const int str_hi = std::min(str * 2, 8);
    if (str_hi != str)
      walk_.push_back({false, false, true, comp, seg, str_hi, sch});
  }
  // The walk starts at the CONFIGURED categorical so the first tuning
  // samples — and everything published before the walk advances —
  // respect the operator's explicit hierarchical/cache choices instead
  // of silently flipping them off (the reference seeds its parameter
  // manager from the configured values before tuning).
  const Categorical seed{opts.hierarchical_allreduce,
                         opts.hierarchical_allgather, opts.cache_enabled,
                         opts.compression, seg, str, sch};
  auto same = [&seed](const Categorical& c) {
    return c.hier_allreduce == seed.hier_allreduce &&
           c.hier_allgather == seed.hier_allgather &&
           c.cache_enabled == seed.cache_enabled &&
           c.compression == seed.compression &&
           c.ring_segment_bytes == seed.ring_segment_bytes &&
           c.ring_stripes == seed.ring_stripes &&
           c.schedule == seed.schedule;
  };
  walk_.erase(std::remove_if(walk_.begin(), walk_.end(), same), walk_.end());
  walk_.insert(walk_.begin(), seed);
  if (!opts.log_path.empty()) {
    log_ = std::fopen(opts.log_path.c_str(), "w");
    if (log_) {
      std::fprintf(log_,
                   "score_bytes_per_sec,fusion_threshold_mb,cycle_time_ms,"
                   "hierarchical_allreduce,hierarchical_allgather,"
                   "cache_enabled,compression,ring_segment_bytes,"
                   "ring_stripes,schedule\n");
    }
  }
  bayes_ = std::make_unique<optim::BayesianOptimizer>(
      std::vector<double>{kFusionLogLow, kCycleLow},
      std::vector<double>{kFusionLogHigh, kCycleHigh},
      opts.gaussian_process_noise);
  ApplyPoint(bayes_->Suggest());
}

ParameterManager::~ParameterManager() {
  if (log_) std::fclose(log_);
}

void ParameterManager::Record(int64_t bytes) {
  if (!tuning_.load()) return;
  window_bytes_ += bytes;
}

void ParameterManager::ApplyPoint(const std::vector<double>& point) {
  current_point_ = point;
  const Categorical& cat = walk_[walk_index_];
  fusion_bytes_.store(FusionBytesFromLog2Mb(point[0]));
  cycle_ms_.store(point[1]);
  hier_allreduce_.store(cat.hier_allreduce);
  hier_allgather_.store(cat.hier_allgather);
  cache_enabled_.store(cat.cache_enabled);
  compression_.store(cat.compression);
  ring_segment_bytes_.store(cat.ring_segment_bytes);
  ring_stripes_.store(cat.ring_stripes);
  schedule_.store(cat.schedule);
  discard_left_ = opts_.warmup_samples;
  window_scores_.clear();
  window_bytes_ = 0;
  window_start_ = -1.0;
}

void ParameterManager::ApplyBest() {
  fusion_bytes_.store(FusionBytesFromLog2Mb(best_fusion_log2_mb_));
  cycle_ms_.store(best_cycle_ms_);
  hier_allreduce_.store(best_cat_.hier_allreduce);
  hier_allgather_.store(best_cat_.hier_allgather);
  cache_enabled_.store(best_cat_.cache_enabled);
  compression_.store(best_cat_.compression);
  ring_segment_bytes_.store(best_cat_.ring_segment_bytes);
  ring_stripes_.store(best_cat_.ring_stripes);
  schedule_.store(best_cat_.schedule);
  tuning_.store(false);
  if (log_) {
    std::fflush(log_);
  }
}

void ParameterManager::NextCategorical() {
  ++walk_index_;
  if (walk_index_ >= walk_.size()) {
    ApplyBest();
    return;
  }
  bayes_ = std::make_unique<optim::BayesianOptimizer>(
      std::vector<double>{kFusionLogLow, kCycleLow},
      std::vector<double>{kFusionLogHigh, kCycleHigh},
      opts_.gaussian_process_noise);
  ApplyPoint(bayes_->Suggest());
}

void ParameterManager::LogRow(double score) {
  if (!log_) return;
  std::fprintf(log_, "%.1f,%.2f,%.2f,%d,%d,%d,%d,%lld,%d,%d\n", score,
               static_cast<double>(fusion_bytes_.load()) / (1024.0 * 1024.0),
               cycle_ms_.load(), hier_allreduce_.load() ? 1 : 0,
               hier_allgather_.load() ? 1 : 0, cache_enabled_.load() ? 1 : 0,
               compression_.load() ? 1 : 0,
               static_cast<long long>(ring_segment_bytes_.load()),
               ring_stripes_.load(), schedule_.load());
}

bool ParameterManager::Update(double now_seconds) {
  if (!tuning_.load()) return false;
  if (window_start_ < 0.0) {
    window_start_ = now_seconds;
    window_bytes_ = 0;
    return false;
  }
  double elapsed = now_seconds - window_start_;
  if (elapsed <= 0.0) return false;
  double score = static_cast<double>(window_bytes_) / elapsed;
  window_start_ = now_seconds;
  window_bytes_ = 0;

  if (discard_left_ > 0) {
    --discard_left_;
    return false;
  }
  window_scores_.push_back(score);
  if (window_scores_.size() < static_cast<size_t>(opts_.steady_state_samples))
    return false;

  // Median of the windows = the observation for the current point.
  std::sort(window_scores_.begin(), window_scores_.end());
  double observed = window_scores_[window_scores_.size() / 2];
  window_scores_.clear();
  LogRow(observed);

  if (observed > best_score_.load()) {
    best_score_.store(observed);
    best_fusion_log2_mb_ = current_point_[0];
    best_cycle_ms_ = current_point_[1];
    best_cat_ = walk_[walk_index_];
  }

  bayes_->AddSample(current_point_, observed);
  if (bayes_->num_samples() >=
      static_cast<size_t>(opts_.bayes_opt_max_samples)) {
    NextCategorical();
  } else {
    ApplyPoint(bayes_->Suggest());
  }
  return true;
}

}  // namespace hvd
