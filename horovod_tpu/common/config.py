"""Runtime configuration, resolved from env vars at ``hvd.init()`` time.

Reference: knob parsing in ``horovod/common/operations.cc:404-500`` and
``horovod/common/utils/env_parser.cc``.
"""

import dataclasses

from horovod_tpu.utils import env as env_util


@dataclasses.dataclass
class Config:
    fusion_threshold_bytes: int = env_util.DEFAULT_FUSION_THRESHOLD
    cycle_time_ms: float = env_util.DEFAULT_CYCLE_TIME_MS
    cache_capacity: int = env_util.DEFAULT_CACHE_CAPACITY
    timeline_path: str | None = None
    timeline_mark_cycles: bool = False
    stall_check_disable: bool = False
    stall_warning_seconds: float = env_util.DEFAULT_STALL_WARNING_SECONDS
    stall_shutdown_seconds: float = 0.0
    controller: str = "native"
    autotune: bool = False
    autotune_log: str | None = None
    autotune_warmup_samples: int = 3
    autotune_steady_state_samples: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # Opt-in separately from hierarchical_allreduce: hierarchical Adasum
    # CHANGES the reduction result (adasum of per-group averages, the
    # reference's NCCL+MPI Adasum), it is not a schedule-only switch.
    adasum_hierarchical: bool = False
    # Default on-the-wire allreduce compression ("none" | "bf16" |
    # "fp16" | "int8") for requests that don't pass one explicitly;
    # autotune may toggle it between the configured value and "none".
    compression: str = "none"
    # TCP-ring transfer engine (docs/tuning.md): pipeline segment size
    # in bytes (0 = unsegmented) and dedicated bulk connections per
    # peer.  Both join the autotune walk in tcp mode.
    ring_segment_bytes: int = env_util.DEFAULT_RING_SEGMENT_BYTES
    ring_stripes: int = env_util.DEFAULT_RING_STRIPES
    # Collective schedule for the TCP data plane (docs/tuning.md):
    # "auto" lets the coordinator pick per tensor size and topology,
    # the rest force one plan (flat_ring | hierarchical | rhd | star).
    # Joins the autotune walk in tcp mode.
    schedule: str = "auto"
    # Fault-tolerant runtime knobs (docs/fault_tolerance.md): bound on
    # abort propagation, heartbeat period, missed-heartbeat window
    # (0 disables liveness tracking), and the deterministic fault spec.
    abort_timeout_seconds: float = env_util.DEFAULT_ABORT_TIMEOUT_SECONDS
    heartbeat_interval_seconds: float = \
        env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS
    liveness_timeout_seconds: float = \
        env_util.DEFAULT_LIVENESS_TIMEOUT_SECONDS
    fault_spec: str | None = None
    # Degraded-network tolerance (docs/fault_tolerance.md): RTT EWMA
    # smoothing, the k x median straggler verdict (k = factor, m =
    # windows), and whether a confirmed straggler is proposed for
    # drain-style exclusion under elastic.
    rtt_alpha: float = env_util.DEFAULT_RTT_ALPHA
    # Self-healing transport (docs/fault_tolerance.md "connection blips
    # vs dead peers"): the reconnect window a broken session may heal
    # inside (0 = off, the pre-session escalate-immediately behavior)
    # and the bound on the sender-side replay buffer of unacked frames.
    reconnect_budget_seconds: float = \
        env_util.DEFAULT_RECONNECT_BUDGET_SECONDS
    replay_buffer_bytes: int = env_util.DEFAULT_REPLAY_BUFFER_BYTES
    straggler_factor: float = env_util.DEFAULT_STRAGGLER_FACTOR
    straggler_windows: int = env_util.DEFAULT_STRAGGLER_WINDOWS
    straggler_exclude: bool = False
    # Elastic membership (docs/elastic.md): survive rank loss by
    # reconfiguring instead of raising; bounds on the reconfiguration
    # window and on how small/large membership may become.
    elastic: bool = False
    reconfig_timeout_seconds: float = \
        env_util.DEFAULT_RECONFIG_TIMEOUT_SECONDS
    min_ranks: int = env_util.DEFAULT_MIN_RANKS
    max_ranks: int = env_util.DEFAULT_MAX_RANKS
    # Coordinator fail-over (docs/elastic.md#coordinator-fail-over):
    # survivors of a rank-0 loss race a CAS election at the rendezvous
    # server and re-form under a new coordinator instead of dying.
    coord_failover: bool = False
    election_timeout_seconds: float = \
        env_util.DEFAULT_ELECTION_TIMEOUT_SECONDS
    # ZeRO-sharded weight update + executor selection (docs/sharding.md):
    # ``zero`` turns on optimizer-state sharding in the high-level
    # training wrappers; ``zero_min_size`` keeps tiny models on the
    # replicated path; ``executor`` picks the XLA data plane ("psum" =
    # flat hvd-axis mesh, "mesh" = NamedSharding dp-axis executor).
    zero: bool = False
    zero_min_size: int = env_util.DEFAULT_ZERO_MIN_SIZE
    executor: str = "psum"
    # Process groups (docs/groups.md): cap on live sub-communicators per
    # job — each owns negotiation state, caches and (tcp) a ring plane,
    # so an unbounded registry is a leak.
    group_max: int = env_util.DEFAULT_GROUP_MAX
    # Preemption-aware drain + durable checkpointing
    # (docs/checkpoint.md): ``drain`` converts a worker SIGTERM (the
    # preemption notice) into a planned departure; ``ckpt_dir`` enables
    # the background sharded checkpoint writer, snapshotting every
    # ``ckpt_interval_steps`` committed steps and keeping ``ckpt_keep``
    # complete checkpoints.
    drain: bool = True
    ckpt_dir: str | None = None
    ckpt_interval_steps: int = env_util.DEFAULT_CKPT_INTERVAL_STEPS
    ckpt_keep: int = env_util.DEFAULT_CKPT_KEEP

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            fusion_threshold_bytes=env_util.get_int(
                env_util.HVD_FUSION_THRESHOLD,
                env_util.DEFAULT_FUSION_THRESHOLD),
            cycle_time_ms=env_util.get_float(
                env_util.HVD_CYCLE_TIME, env_util.DEFAULT_CYCLE_TIME_MS),
            cache_capacity=env_util.get_int(
                env_util.HVD_CACHE_CAPACITY, env_util.DEFAULT_CACHE_CAPACITY),
            timeline_path=env_util.get_str(env_util.HVD_TIMELINE),
            timeline_mark_cycles=env_util.get_bool(
                env_util.HVD_TIMELINE_MARK_CYCLES),
            stall_check_disable=env_util.get_bool(
                env_util.HVD_STALL_CHECK_DISABLE),
            stall_warning_seconds=env_util.get_float(
                env_util.HVD_STALL_CHECK_TIME_SECONDS,
                env_util.DEFAULT_STALL_WARNING_SECONDS),
            stall_shutdown_seconds=env_util.get_float(
                env_util.HVD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            controller=env_util.get_str(env_util.HVD_CONTROLLER, "native"),
            autotune=env_util.get_bool(env_util.HVD_AUTOTUNE),
            autotune_log=env_util.get_str(env_util.HVD_AUTOTUNE_LOG),
            autotune_warmup_samples=env_util.get_int(
                env_util.HVD_AUTOTUNE_WARMUP_SAMPLES, 3),
            autotune_steady_state_samples=env_util.get_int(
                env_util.HVD_AUTOTUNE_STEADY_STATE_SAMPLES, 10),
            autotune_bayes_opt_max_samples=env_util.get_int(
                env_util.HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20),
            autotune_gaussian_process_noise=env_util.get_float(
                env_util.HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8),
            hierarchical_allreduce=env_util.get_bool(
                env_util.HVD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=env_util.get_bool(
                env_util.HVD_HIERARCHICAL_ALLGATHER),
            adasum_hierarchical=env_util.get_bool(
                env_util.HVD_ADASUM_HIERARCHICAL),
            compression=_validated_compression(env_util.get_str(
                env_util.HVD_TPU_COMPRESSION, "none")),
            ring_segment_bytes=_validated_nonneg(
                env_util.HVD_TPU_RING_SEGMENT_BYTES,
                env_util.DEFAULT_RING_SEGMENT_BYTES),
            ring_stripes=max(1, env_util.get_int(
                env_util.HVD_TPU_RING_STRIPES,
                env_util.DEFAULT_RING_STRIPES)),
            schedule=_validated_schedule(env_util.get_str(
                env_util.HVD_TPU_SCHEDULE, "auto")),
            abort_timeout_seconds=env_util.get_float(
                env_util.HVD_TPU_ABORT_TIMEOUT,
                env_util.DEFAULT_ABORT_TIMEOUT_SECONDS),
            heartbeat_interval_seconds=env_util.get_float(
                env_util.HVD_TPU_HEARTBEAT_INTERVAL,
                env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS),
            liveness_timeout_seconds=env_util.get_float(
                env_util.HVD_TPU_LIVENESS_TIMEOUT,
                env_util.DEFAULT_LIVENESS_TIMEOUT_SECONDS),
            fault_spec=_validated_fault_spec(env_util.get_str(
                env_util.HVD_TPU_FAULT_SPEC)),
            rtt_alpha=env_util.get_float(
                env_util.HVD_TPU_RTT_ALPHA,
                env_util.DEFAULT_RTT_ALPHA),
            reconnect_budget_seconds=env_util.get_float(
                env_util.HVD_TPU_RECONNECT_BUDGET,
                env_util.DEFAULT_RECONNECT_BUDGET_SECONDS),
            replay_buffer_bytes=_validated_nonneg(
                env_util.HVD_TPU_REPLAY_BUFFER_BYTES,
                env_util.DEFAULT_REPLAY_BUFFER_BYTES),
            straggler_factor=env_util.get_float(
                env_util.HVD_TPU_STRAGGLER_FACTOR,
                env_util.DEFAULT_STRAGGLER_FACTOR),
            straggler_windows=max(1, env_util.get_int(
                env_util.HVD_TPU_STRAGGLER_WINDOWS,
                env_util.DEFAULT_STRAGGLER_WINDOWS)),
            straggler_exclude=env_util.get_bool(
                env_util.HVD_TPU_STRAGGLER_EXCLUDE),
            elastic=env_util.get_bool(env_util.HVD_TPU_ELASTIC),
            reconfig_timeout_seconds=env_util.get_float(
                env_util.HVD_TPU_RECONFIG_TIMEOUT,
                env_util.DEFAULT_RECONFIG_TIMEOUT_SECONDS),
            min_ranks=max(1, env_util.get_int(
                env_util.HVD_TPU_MIN_RANKS,
                env_util.DEFAULT_MIN_RANKS)),
            max_ranks=_validated_nonneg(
                env_util.HVD_TPU_MAX_RANKS,
                env_util.DEFAULT_MAX_RANKS),
            coord_failover=env_util.get_bool(
                env_util.HVD_TPU_COORD_FAILOVER),
            election_timeout_seconds=env_util.get_float(
                env_util.HVD_TPU_ELECTION_TIMEOUT,
                env_util.DEFAULT_ELECTION_TIMEOUT_SECONDS),
            zero=env_util.get_bool(env_util.HVD_TPU_ZERO),
            zero_min_size=_validated_nonneg(
                env_util.HVD_TPU_ZERO_MIN_SIZE,
                env_util.DEFAULT_ZERO_MIN_SIZE),
            executor=_validated_executor(env_util.get_str(
                env_util.HVD_TPU_EXECUTOR, "psum")),
            group_max=max(1, env_util.get_int(
                env_util.HVD_TPU_GROUP_MAX,
                env_util.DEFAULT_GROUP_MAX)),
            drain=env_util.get_bool(env_util.HVD_TPU_DRAIN, True),
            ckpt_dir=env_util.get_str(env_util.HVD_TPU_CKPT_DIR),
            ckpt_interval_steps=max(1, env_util.get_int(
                env_util.HVD_TPU_CKPT_INTERVAL,
                env_util.DEFAULT_CKPT_INTERVAL_STEPS)),
            ckpt_keep=_validated_nonneg(
                env_util.HVD_TPU_CKPT_KEEP,
                env_util.DEFAULT_CKPT_KEEP),
        )


def effective_heartbeat_interval(config) -> float:
    """The heartbeat period both controllers actually run: the
    configured interval clamped to at most a quarter of the abort
    deadline (so abort propagation meets it); 0 only when the interval
    AND the abort timeout are both disabled."""
    interval = config.heartbeat_interval_seconds
    if config.abort_timeout_seconds > 0:
        interval = min(interval or 1e9,
                       config.abort_timeout_seconds / 4.0)
    return interval


def _validated_nonneg(name, default):
    """Negative byte counts would silently disable segmentation in a
    surprising way; fail at init() like the other validated knobs."""
    value = env_util.get_int(name, default)
    if value < 0:
        raise ValueError(f"{name} must be >= 0, got {value}")
    return value


def _validated_fault_spec(text):
    """Same fail-at-init rule as compression: a fault spec with a typo
    would otherwise silently never fire and the chaos run would prove
    nothing."""
    if text:
        from horovod_tpu.common.faults import parse_fault_spec

        parse_fault_spec(text)
    return text


def _validated_executor(name: str) -> str:
    """Same fail-at-init rule: an HVD_TPU_EXECUTOR typo must not
    silently run the default data plane."""
    if name not in ("psum", "mesh"):
        raise ValueError(
            f"HVD_TPU_EXECUTOR must be 'psum' or 'mesh', got {name!r}")
    return name


def _validated_schedule(name: str) -> str:
    """Same fail-at-init rule: an HVD_TPU_SCHEDULE typo must not
    silently fall back to the auto resolver."""
    from horovod_tpu.ops.tcp_dataplane import SCHEDULES

    if name not in SCHEDULES:
        raise ValueError(
            f"HVD_TPU_SCHEDULE must be one of {SCHEDULES}, got {name!r}")
    return name


def _validated_compression(name: str) -> str:
    """Fail at init() with a clear message rather than at the first
    allreduce when HVD_TPU_COMPRESSION holds a typo."""
    from horovod_tpu.common.compression import resolve_compression

    return resolve_compression(name)
