"""Process/device topology: the rank model.

The reference derives rank / local_rank / cross_rank from MPI communicator
splits (``horovod/common/mpi/mpi_context.cc:147-156``).  On TPU the natural
analog is the pod-slice coordinate system:

- ``rank``        — global logical worker id (one worker per chip)
- ``local_rank``  — chip index within this host (reference: shared-memory comm)
- ``cross_rank``  — host index (reference: cross communicator)

Two operating modes:

- **device-rank** (single-controller SPMD, the TPU-native default): one Python
  process drives every addressable device; each device is one logical rank.
  Eager collectives are issued from per-rank threads (see
  ``horovod_tpu.common.basics.run_parallel``) and executed as XLA collectives
  over the mesh.
- **process-rank**: one process per worker, launched by ``hvdrun`` which wires
  the ``HVD_RANK``/``HVD_SIZE``/... env contract exactly like the reference
  launcher does (``horovod/run/gloo_run.py:152-157``).
"""

import dataclasses
import os

from horovod_tpu.utils import env as env_util


@dataclasses.dataclass(frozen=True)
class Topology:
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    mode: str  # "device" | "process"

    @property
    def is_homogeneous(self) -> bool:
        return self.size == self.local_size * self.cross_size


def _mpi_placed() -> "Topology | None":
    """Fallback contract for mpirun/jsrun placement (``hvdrun
    --launcher mpirun``): the per-rank variables come from the MPI
    runtime (OpenMPI's OMPI_COMM_WORLD_* or the PMI set) because a
    single mpirun command line cannot export per-rank values.

    Gated on the delegation contract (rendezvous address exported by
    ``hvdrun --launcher mpirun/jsrun``): a script launched under plain
    mpirun/srun WITHOUT hvdrun keeps the default device-rank mode
    instead of being hijacked into process mode it can't complete."""
    if env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR) is None:
        return None
    rank = os.environ.get("OMPI_COMM_WORLD_RANK",
                          os.environ.get("PMI_RANK"))
    size = os.environ.get("OMPI_COMM_WORLD_SIZE",
                          os.environ.get("PMI_SIZE"))
    if rank is None or size is None:
        return None
    rank, size = int(rank), int(size)
    placed = _from_host_slots(rank, size)
    if placed is not None:
        return placed
    local_rank = int(os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK",
                                    os.environ.get("MPI_LOCALRANKID", 0)))
    local_size = int(os.environ.get("OMPI_COMM_WORLD_LOCAL_SIZE",
                                    os.environ.get("MPI_LOCALNRANKS", 1)))
    # uniform-slots + BLOCK placement assumption for the derived cross
    # axis (mpirun's default --map-by core/slot fills hosts in rank
    # blocks; --map-by node round-robins ranks and breaks this
    # derivation).  The delegation drivers export HVD_HOST_SLOTS (the
    # exact rank-block layout, handled above) precisely so non-uniform
    # allocations — e.g. jsrun's trimmed last host — never reach this
    # fallback; it remains for scripts run under bare mpirun.
    cross_size = max(size // max(local_size, 1), 1)
    return Topology(rank, size, local_rank, local_size,
                    cross_rank=rank // max(local_size, 1),
                    cross_size=cross_size, mode="process")


def _from_host_slots(rank, size) -> "Topology | None":
    """Exact per-rank placement from the ``HVD_HOST_SLOTS`` layout the
    mpirun/jsrun delegation drivers export (``run/runner.py``,
    ``run/js_run.py``): ``"h1:n1,h2:n2"``, host-major in rank-block
    order — the order both the jsrun rankfile and ``mpirun -H
    --map-by slot`` place ranks in.  Correct even when hosts carry
    unequal slot counts, where the MPI-local-vars derivation above
    would give ranks on the short host a different cross_size."""
    spec = env_util.get_str(env_util.HVD_HOST_SLOTS)
    if not spec:
        return None
    counts = []
    for part in spec.split(","):
        host, _, n = part.rpartition(":")
        if not host or not n.isdigit():
            return None
        counts.append(int(n))
    if sum(counts) != size:
        return None  # stale/foreign layout: fall back to MPI vars
    base = 0
    for cross_rank, n in enumerate(counts):
        if rank < base + n:
            return Topology(rank, size,
                            local_rank=rank - base, local_size=n,
                            cross_rank=cross_rank,
                            cross_size=len(counts), mode="process")
        base += n
    return None


def from_env() -> "Topology | None":
    """Build topology from the hvdrun env contract, if present; fall
    back to MPI-runtime placement variables (mpirun/jsrun delegation)."""
    if env_util.get_str(env_util.HVD_RANK) is None:
        return _mpi_placed()
    rank = env_util.get_int(env_util.HVD_RANK, 0)
    size = env_util.get_int(env_util.HVD_SIZE, 1)
    local_rank = env_util.get_int(env_util.HVD_LOCAL_RANK, rank)
    local_size = env_util.get_int(env_util.HVD_LOCAL_SIZE, size)
    cross_rank = env_util.get_int(env_util.HVD_CROSS_RANK, 0)
    cross_size = env_util.get_int(env_util.HVD_CROSS_SIZE, 1)
    return Topology(rank, size, local_rank, local_size, cross_rank, cross_size,
                    mode="process")


def from_devices(devices, process_index: int, process_count: int,
                 this_rank: int = 0) -> Topology:
    """Device-rank topology: every addressable device is a logical rank.

    ``local_*`` is the within-process device axis; ``cross_*`` the process
    (host) axis — mirroring the reference's LOCAL (shared-memory) and CROSS
    communicators on pod-slice coordinates.
    """
    local_size = len(devices)
    size = local_size * process_count
    local_rank = this_rank % local_size
    return Topology(
        rank=process_index * local_size + local_rank,
        size=size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=process_index,
        cross_size=process_count,
        mode="device",
    )
