"""Per-peer round-trip-time EWMAs for adaptive deadlines
(docs/fault_tolerance.md "degraded networks").

Fixed liveness windows misfire under degradation: a slow-but-alive
peer (congested NIC, throttled link) misses a fixed deadline and gets
aborted as dead — the exact failure mode the MLPerf TPU-pod work calls
the first-order production problem at scale.  The fix is measurement:
every worker samples the RTT of its own control-plane round trips
(heartbeats) and its ring chunk sends ("ring acks"), folds them into
per-key EWMAs, and reports the worst to the coordinator with each
heartbeat; the coordinator widens that rank's liveness window by an
RTT-proportional slack, so slow and dead become distinguishable.

One process-wide tracker (:func:`tracker`) is shared by the heartbeat
loop and the ring data plane so a degradation on either path widens the
reported figure.
"""

import threading

from horovod_tpu.utils import env as env_util

# keys of the process-wide tracker
COORD_KEY = "coordinator"


class RttTracker:
    """Thread-safe per-key EWMA of duration samples (seconds).

    ``alpha`` is the EWMA smoothing factor (HVD_TPU_RTT_ALPHA): the
    weight of the newest sample.  Higher alpha adapts faster to a link
    that just degraded; lower alpha resists one-off spikes."""

    def __init__(self, alpha=None):
        if alpha is None:
            alpha = env_util.get_float(env_util.HVD_TPU_RTT_ALPHA,
                                       env_util.DEFAULT_RTT_ALPHA)
        self.alpha = min(max(float(alpha), 0.01), 1.0)
        self._ewma = {}                 # key -> seconds; guarded by self._lock
        self._lock = threading.Lock()

    def sample(self, key, seconds):
        if seconds < 0:
            return
        with self._lock:
            prev = self._ewma.get(key)
            self._ewma[key] = (seconds if prev is None
                               else prev + self.alpha * (seconds - prev))

    def get(self, key, default=0.0):
        with self._lock:
            return self._ewma.get(key, default)

    def worst(self) -> float:
        """The largest EWMA across keys — the figure a worker reports:
        its slowest observed link bounds how late its own beats and
        chunk sends may legitimately run."""
        with self._lock:
            return max(self._ewma.values(), default=0.0)

    def snapshot(self):
        with self._lock:
            return dict(self._ewma)

    def clear(self):
        with self._lock:
            self._ewma.clear()


def median(values):
    """Median of a value sequence (0.0 when empty) — the straggler
    baseline: a rank is only a straggler relative to its peers, never
    in absolute terms (the whole pod may be slow on purpose)."""
    vals = sorted(values)
    if not vals:
        return 0.0
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2.0


_tracker = None
_tracker_lock = threading.Lock()


def tracker() -> RttTracker:
    """The process-wide tracker shared by the heartbeat loop and the
    ring data plane (lazy: alpha resolves from the env on first use)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = RttTracker()
        return _tracker


def reset():
    """Drop all samples AND the cached alpha (tests; elastic reinit
    keeps samples on purpose — the links did not change)."""
    global _tracker
    with _tracker_lock:
        _tracker = None
