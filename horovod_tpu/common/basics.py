"""Process model and global state: ``init`` / ``rank`` / ``size`` / ...

The reference implements this as ctypes calls into the C core
(``horovod/common/basics.py:22`` HorovodBasics over ``operations.cc:663-797``).
Here the state is Python-owned; the native core (when built) plugs in as the
controller implementation underneath.

Two operating modes (see ``horovod_tpu/common/topology.py``):

- **device-rank** (default): every addressable JAX device is a logical rank.
  Per-rank user code runs on threads — ``run_parallel(fn)`` mirrors the
  reference's test pattern of executing the same rank-parameterized function
  on every rank.
- **process-rank**: ``hvdrun`` wired the ``HVD_RANK``/... env contract; one
  process per worker.
"""

import contextlib
import threading

from horovod_tpu.common import topology as topology_mod
from horovod_tpu.common.config import Config
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger
from horovod_tpu.utils.timeline import Timeline

_state = None
_state_lock = threading.Lock()
_tls = threading.local()


class _GlobalState:
    def __init__(self, topology, devices, config, executor, controller,
                 timeline):
        self.topology = topology
        self.devices = devices
        self.config = config
        self.executor = executor
        self.controller = controller
        self.timeline = timeline
        # Elastic membership (docs/elastic.md): ``worker_id`` is this
        # process's STABLE identity — the launcher-assigned initial rank,
        # never rewritten by reconfiguration (fault-injection determinism
        # and log attribution key off it).  ``rank`` is merely this
        # worker id's current position in the membership list.
        self.worker_id = topology.rank if topology.mode == "process" else 0
        self.epoch = 0


def _make_executor(config, devices):
    """Build the XLA data plane ``config.executor`` selects: ``"psum"``
    is the flat hvd-axis :class:`XlaExecutor`; ``"mesh"`` the
    NamedSharding :class:`MeshExecutor` over the ``parallel.mesh``
    dp-axis vocabulary (docs/sharding.md)."""
    if config.executor == "mesh":
        from horovod_tpu.sharding.mesh_executor import MeshExecutor
        executor = MeshExecutor(devices)
    else:
        from horovod_tpu.ops.xla_executor import XlaExecutor
        executor = XlaExecutor(devices)
    executor.hierarchical_allreduce = config.hierarchical_allreduce
    executor.hierarchical_allgather = config.hierarchical_allgather
    executor.adasum_hierarchical = config.adasum_hierarchical
    return executor


def init(comm=None, controller=None):
    """Initialize horovod_tpu.

    ``comm`` is accepted for API parity with the reference (an MPI
    communicator there); passing a list of jax devices restricts the rank set
    to those devices.
    """
    global _state
    with _state_lock:
        if _state is not None:
            return
        import jax  # deferred so env vars set before init still apply

        config = Config.from_env()
        if controller:
            config.controller = controller

        # deterministic fault injection (docs/fault_tolerance.md): arm
        # the process-wide injector before any controller/transport code
        # runs, keyed by this process's launcher rank
        from horovod_tpu.common import faults
        faults.configure(config.fault_spec,
                         rank=env_util.get_int(env_util.HVD_RANK, 0))

        env_topology = topology_mod.from_env()
        explicit = (controller or
                    env_util.get_str(env_util.HVD_CONTROLLER))
        use_global_mesh = (
            env_topology is not None and env_topology.size > 1
            and (env_util.get_bool(env_util.HVD_GLOBAL_MESH)
                 or explicit == "gmesh"))
        if use_global_mesh:
            # pod mode (hvdrun --tpu / --global-mesh): every process joins
            # one jax.distributed runtime; each chip is a logical rank;
            # the data plane is compiled XLA collectives over the GLOBAL
            # mesh (reference: gloo_context.cc:56-73 full-mesh rendezvous,
            # replaced by the jax coordinator + GSPMD).
            from horovod_tpu.common import distributed as dist_mod
            dist_mod.initialize_jax_distributed(
                env_topology.rank, env_topology.size)
            local = list(jax.local_devices())
            devices = sorted(
                jax.devices(),
                key=lambda d: (getattr(d, "process_index", 0), d.id))
            if len(devices) != len(local) * env_topology.size:
                raise RuntimeError(
                    f"heterogeneous device counts: {len(devices)} global "
                    f"devices across {env_topology.size} processes with "
                    f"{len(local)} local — global-mesh mode requires the "
                    f"same chip count on every host")
            topology = topology_mod.from_devices(
                local, env_topology.rank, env_topology.size)
            config.controller = "gmesh"
        elif env_topology is not None and env_topology.size > 1:
            # process-rank mode: collectives go through the TCP controller
            # (the reference's Gloo configuration).  The native/python
            # controllers coordinate a single process's device ranks and
            # cannot span processes — an explicit request for them here is
            # a configuration error, not something to override silently.
            if explicit and explicit != "tcp":
                raise RuntimeError(
                    f"HVD_CONTROLLER={explicit} cannot coordinate "
                    f"{env_topology.size} processes; multi-process jobs "
                    f"use the tcp controller (the in-process controllers "
                    f"only coordinate device ranks within one process)")
            topology = env_topology
            devices = jax.local_devices()
            config.controller = "tcp"
        elif isinstance(comm, (list, tuple)) and comm:
            devices = list(comm)
            topology = topology_mod.from_devices(devices, 0, 1)
        else:
            devices = jax.local_devices()
            topology = topology_mod.from_devices(
                devices, jax.process_index(), jax.process_count())

        executor = _make_executor(config, devices)

        timeline = None
        impl = None
        if config.controller == "gmesh":
            from horovod_tpu.ops.global_controller import \
                GlobalMeshController
            # per-process timeline file; rank-0 aggregation via the
            # launcher-side merge (utils/timeline.py)
            path = config.timeline_path
            if path:
                path = f"{path}.rank{topology.cross_rank}"
            timeline = Timeline(path, config.timeline_mark_cycles)
            impl = GlobalMeshController(topology, executor, timeline,
                                        config)
        elif config.controller == "tcp":
            from horovod_tpu.ops.tcp_controller import TcpController
            # per-rank trace file; rank 0 merges all into the base path
            # at shutdown (reference: timeline.cc rank-0 aggregation)
            path = config.timeline_path
            if path:
                path = f"{path}.rank{topology.rank}"
            timeline = Timeline(path, config.timeline_mark_cycles)
            impl = TcpController(topology, executor, timeline, config)
        elif config.controller == "native":
            try:
                from horovod_tpu.ops.native_controller import NativeController
                impl = NativeController(topology, executor, None, config)
                # the native core writes the timeline itself
                timeline = Timeline(None)
            except (ImportError, OSError) as exc:
                get_logger().debug(
                    "native core unavailable (%s); falling back to the "
                    "python controller", exc)
        if impl is None:
            timeline = Timeline(config.timeline_path,
                                config.timeline_mark_cycles)
            if topology.size > len(devices):
                raise RuntimeError(
                    f"topology spans {topology.size} ranks but only "
                    f"{len(devices)} devices are addressable in this "
                    f"process; multi-process collectives require the tcp "
                    f"controller (launch with hvdrun)")
            from horovod_tpu.ops.python_controller import PythonController
            impl = PythonController(topology, executor, timeline, config)
        impl.start()

        _state = _GlobalState(topology, devices, config, executor, impl,
                              timeline)
        # a fresh world must not inherit the previous job's process
        # groups (docs/groups.md): the registry belongs to ONE init
        from horovod_tpu import groups as groups_mod
        groups_mod.reset()
        _maybe_install_drain(config)


def _maybe_install_drain(config):
    """Arm the SIGTERM→graceful-drain handler (docs/checkpoint.md) when
    the runtime can actually honor it: multi-process tcp jobs with
    ``HVD_TPU_DRAIN`` on.  Elsewhere SIGTERM keeps its default (kill)
    disposition — a single process has nobody to announce departure to,
    and the in-process controllers have no coordinator."""
    if not (config.drain and config.controller == "tcp"
            and _state is not None and _state.topology.mode == "process"
            and _state.topology.size > 1):
        return
    from horovod_tpu.common import drain as drain_mod
    # resolved at signal time: reconfiguration replaces the controller
    drain_mod.install(
        lambda: _state.controller if _state is not None else None)


def _drained_teardown():
    """Quietly dismantle this process's runtime after a granted drain:
    the rank has already left the membership, so there are no job-end
    barriers to run — close transports, flush the timeline, drop the
    global state so atexit paths see an uninitialized runtime."""
    global _state
    with _state_lock:
        if _state is None:
            return
        try:
            _state.controller.close_for_reconfig()
        except Exception:  # noqa: BLE001 — leaving a world that has
            # already reconfigured past us
            get_logger().debug("drain teardown error", exc_info=True)
        try:
            _state.timeline.close()
        except Exception:  # noqa: BLE001 — best-effort flush
            get_logger().debug("drain timeline close error",
                               exc_info=True)
        _state = None


def shutdown():
    global _state
    with _state_lock:
        if _state is None:
            return
        _state.controller.shutdown()
        _state.timeline.close()
        _state = None
    from horovod_tpu import groups as groups_mod
    groups_mod.reset()


def worker_id() -> int:
    """This process's stable elastic identity (the launcher-assigned
    initial rank; unchanged by reconfiguration)."""
    return _get_state().worker_id


def members() -> list:
    """Current worker-id list in rank order: position r holds the
    stable worker id serving rank r at this membership epoch (identity
    before any elastic reconfiguration).  Process groups record THESE
    ids, so their rank-specs survive renumbering (docs/groups.md)."""
    state = _get_state()
    m = getattr(state.controller, "_members", None)
    return list(m) if m is not None else list(range(state.topology.size))


def _elastic_reinit(epoch, members):
    """Move this surviving process to a new membership epoch
    (docs/elastic.md): tear down the current-generation controller (no
    job-end barriers — the job is not ending), re-key rank/size from
    this worker's position in the new membership, and gang-start a
    fresh TcpController under the epoch's rendezvous scopes — which
    rebuilds the ring topology and stripe connections from scratch."""
    global _state
    import dataclasses

    with _state_lock:
        state = _get_state()
        wid = state.worker_id
        if wid not in members:
            raise ValueError(
                f"worker {wid} is not part of membership {members}")
        if epoch <= state.epoch:
            return  # stale directive: this process already moved on
        try:
            state.controller.close_for_reconfig()
        except Exception:  # noqa: BLE001 — tearing down a dead world
            get_logger().debug("reconfig teardown error", exc_info=True)
        new_rank = members.index(wid)
        new_size = len(members)
        # the global and local axes are re-keyed densely; the cross axis
        # keeps its launch value (single-host elastic — see docs)
        topology = dataclasses.replace(
            state.topology, rank=new_rank, size=new_size,
            local_rank=new_rank, local_size=new_size)
        from horovod_tpu.ops.tcp_controller import TcpController
        impl = TcpController(topology, state.executor, state.timeline,
                             state.config, epoch=epoch,
                             members=list(members))
        impl.start()
        state.topology = topology
        state.controller = impl
        state.epoch = epoch
        # re-form EVERY process group for the new membership
        # (docs/groups.md): a group is a pure function of (spec,
        # members) — grids re-plan over the survivors, explicit rank
        # lists with a dead worker turn typed-unsatisfiable
        from horovod_tpu import groups as groups_mod
        groups_mod.reform(list(members))
        get_logger().warning(
            "elastic: worker %d re-formed at epoch %d as rank %d/%d",
            wid, epoch, new_rank, new_size)


def _elastic_join_init(epoch, members):
    """Initialize a late-joining worker directly at an admitted
    membership epoch (it never belonged to epoch 0; a plain ``init()``
    would gang-start against the dead world's rendezvous scope)."""
    global _state
    with _state_lock:
        if _state is not None:
            raise RuntimeError(
                "horovod_tpu is already initialized; joiners call "
                "hvd.elastic.wait_for_membership() INSTEAD of hvd.init()")
        import jax

        config = Config.from_env()
        config.controller = "tcp"
        from horovod_tpu.common import faults
        wid = env_util.get_int(env_util.HVD_RANK, 0)
        faults.configure(config.fault_spec, rank=wid)
        new_rank = members.index(wid)
        topology = topology_mod.Topology(
            rank=new_rank, size=len(members),
            local_rank=new_rank, local_size=len(members),
            cross_rank=0, cross_size=1, mode="process")
        devices = jax.local_devices()
        executor = _make_executor(config, devices)
        path = config.timeline_path
        if path:
            path = f"{path}.rank{wid}"
        timeline = Timeline(path, config.timeline_mark_cycles)
        from horovod_tpu.ops.tcp_controller import TcpController
        impl = TcpController(topology, executor, timeline, config,
                             epoch=epoch, members=list(members))
        impl.start()
        _state = _GlobalState(topology, devices, config, executor, impl,
                              timeline)
        _state.worker_id = wid
        _state.epoch = epoch
        # same init-boundary rule as init(): a joiner's fresh world must
        # not inherit a previous job's process groups (docs/groups.md)
        from horovod_tpu import groups as groups_mod
        groups_mod.reset()
        _maybe_install_drain(config)
        get_logger().warning(
            "elastic: worker %d joined at epoch %d as rank %d/%d",
            wid, epoch, new_rank, len(members))


def is_initialized() -> bool:
    return _state is not None


def abort(reason="aborted by user"):
    """Broadcast a coordinated abort for the in-flight collective round
    (docs/fault_tolerance.md).

    Every rank — including ranks currently blocked inside a collective —
    purges its in-flight ring state and raises
    :class:`horovod_tpu.HvdAbortedError` (naming this rank as the
    origin) within ``HVD_TPU_ABORT_TIMEOUT``.  Use it when this rank
    detects an unrecoverable condition (corrupt batch, failed health
    check) and the whole job must unwind symmetrically instead of
    leaving peers hanging in a half-finished round.
    """
    state = _get_state()
    do_abort = getattr(state.controller, "abort", None)
    if do_abort is None:
        raise NotImplementedError(
            f"controller {state.config.controller!r} does not support "
            f"coordinated abort")
    do_abort(rank(), reason)


def _get_state() -> _GlobalState:
    if _state is None:
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init() first")
    return _state


# ----------------------------------------------------------- rank model -----
@contextlib.contextmanager
def rank_context(local_rank: int):
    """Bind the calling thread to a logical rank (device-rank mode)."""
    previous = getattr(_tls, "local_rank", None)
    _tls.local_rank = local_rank
    try:
        yield
    finally:
        _tls.local_rank = previous


def _current_local_rank() -> int:
    return getattr(_tls, "local_rank", None) or 0


def rank() -> int:
    state = _get_state()
    topo = state.topology
    if topo.mode == "process":
        return topo.rank
    return topo.cross_rank * topo.local_size + _current_local_rank()


def size() -> int:
    return _get_state().topology.size


def local_rank() -> int:
    state = _get_state()
    if state.topology.mode == "process":
        return state.topology.local_rank
    return _current_local_rank()


def local_size() -> int:
    return _get_state().topology.local_size


def cross_rank() -> int:
    return _get_state().topology.cross_rank


def cross_size() -> int:
    return _get_state().topology.cross_size


def mesh():
    """The 1-D jax Mesh over all logical ranks (axis name ``"hvd"``)."""
    return _get_state().executor.mesh


def local_device():
    """The jax device backing this logical rank's compute.

    Process-rank (tcp) jobs use this to run jitted steps on their own
    accelerator while gradients ride the eager collectives — the
    reference's one-GPU-per-process pattern (VERDICT r1 #7: process mode
    must use the chips)."""
    state = _get_state()
    devices = state.executor.devices
    # the within-host index, NOT the global rank: with non-block rank
    # placement rank() % len(devices) can double-book one chip and
    # leave another idle
    return devices[local_rank() % len(devices)]


def run_parallel(fn, num_ranks=None):
    """Run ``fn`` once per logical rank on separate threads and return the
    per-rank results.  ``fn`` may take zero args or the rank as one arg.

    This is the device-rank analog of the reference's "same script on every
    rank" execution model (SURVEY §4): inside ``fn``, ``hvd.rank()`` etc.
    reflect the calling thread's rank.
    """
    import inspect

    state = _get_state()
    n = num_ranks or state.topology.local_size
    results = [None] * n
    errors = [None] * n
    wants_rank = len(inspect.signature(fn).parameters) >= 1

    def worker(r):
        with rank_context(r):
            try:
                results[r] = fn(r) if wants_rank else fn()
            except BaseException as exc:  # noqa: BLE001 — reraised below
                errors[r] = exc

    threads = [threading.Thread(target=worker, args=(r,), daemon=True,
                                name=f"hvd-rank-{r}")
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for exc in errors:
        if exc is not None:
            raise exc
    return results


# ------------------------------------------------------ capability probes ---
def mpi_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def mpi_enabled() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def xla_enabled() -> bool:
    return True


def ccl_built() -> bool:
    """oneCCL backend probe (reference: ``basics.py`` ``ccl_built``) —
    always False: the five comm backends collapse into the XLA plane."""
    return False


def ddl_built() -> bool:
    """IBM DDL backend probe (reference parity) — always False."""
    return False


def mpi_threads_supported() -> bool:
    """Reference: whether MPI was initialized with THREAD_MULTIPLE.
    There is no MPI data plane here (mpirun only launches workers), so
    this is always False; raises if called before ``init`` like the
    reference does."""
    _get_state()  # raises when not initialized (reference contract)
    return False


def is_homogeneous() -> bool:
    """True when every host runs the same number of ranks (reference:
    ``controller.cc`` ``is_homogeneous_``, exposed on the basics
    surface)."""
    return _get_state().topology.is_homogeneous
