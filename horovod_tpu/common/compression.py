"""Gradient compression for collective traffic.

Mirrors the reference's compression surface (``horovod/torch/compression.py:45``,
``horovod/tensorflow/compression.py``) but TPU-first: the half-precision
compressor targets **bfloat16**, the MXU-native dtype, instead of fp16 (fp16's
narrow exponent needs loss scaling; bf16 keeps fp32's range so compression is
a pure cast that XLA fuses into the collective).
"""

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) for decompression."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default: no compression."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class BF16Compressor(Compressor):
    """Cast floating tensors to bfloat16 before the collective."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(Compressor):
    """fp16 compressor for parity with the reference API surface."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return tensor.astype(jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class Compression:
    """Optional gradient compression algorithm used during allreduce."""

    none = NoneCompressor
    bf16 = BF16Compressor
    fp16 = FP16Compressor
