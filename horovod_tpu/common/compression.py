"""Gradient compression for collective traffic.

Mirrors the reference's compression surface (``horovod/torch/compression.py:45``,
``horovod/tensorflow/compression.py``) but TPU-first: the half-precision
compressor targets **bfloat16**, the MXU-native dtype, instead of fp16 (fp16's
narrow exponent needs loss scaling; bf16 keeps fp32's range so compression is
a pure cast that XLA fuses into the collective).

Beyond the cast-style compressors, :class:`Int8Compressor` implements
block-scaled int8 quantization (EQuARX, arXiv:2506.17615): each 256-element
block carries one fp32 scale (max-abs / 127), values travel as int8 and the
reduction accumulates in fp32 — ~4x fewer bytes on the wire at a bounded
per-block error of ``scale/2 = max|x|/254`` per contribution.  Because each
rank quantizes against its OWN block scales, the int8 wire format cannot ride
a plain ``psum``; the quantized collective helpers below decompose the
allreduce into quantized reduce-scatter (``all_to_all`` of int8 blocks +
fp32 accumulate) and quantized allgather (requantize the reduced chunk,
``all_gather`` int8 + scales, dequantize).  ``ops/xla_executor.py`` compiles
the same decomposition into the fused eager plane and
``ops/tcp_dataplane.py`` mirrors it over the TCP ring.
"""

import jax
import jax.numpy as jnp

# Quantization granularity: one fp32 scale per this many elements.  256
# keeps the scale overhead at ~1.6% of the int8 payload while staying
# fine-grained enough that one outlier only coarsens its own block
# (EQuARX uses the same order of magnitude).  Defined jax-free in
# ops_enum so the numpy TCP codecs share the exact same wire format.
from horovod_tpu.common.ops_enum import INT8_BLOCK  # noqa: E402,F401


# --------------------------------------------------------- block quantization
def quantize_int8_blocks(x, block=INT8_BLOCK):
    """Quantize ``x`` (float, last dim divisible by ``block``) to
    (int8 values, fp32 per-block scales with shape ``x.shape[:-1] +
    (x.shape[-1] // block,)``).  Zero blocks get scale 1.0 so the
    round trip is exact (0 -> 0) and never divides by zero."""
    shape = x.shape
    xb = x.astype(jnp.float32).reshape(
        shape[:-1] + (shape[-1] // block, block))
    maxabs = jnp.max(jnp.abs(xb), axis=-1)
    scale = jnp.where(maxabs > 0, maxabs / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q.reshape(shape), scale


def dequantize_int8_blocks(q, scale, block=INT8_BLOCK):
    """Inverse of :func:`quantize_int8_blocks`; returns fp32."""
    shape = q.shape
    qb = q.astype(jnp.float32).reshape(
        shape[:-1] + (shape[-1] // block, block))
    return (qb * scale[..., None]).reshape(shape)


# ------------------------------------------------- quantized SPMD collectives
# All three run INSIDE a shard_map / pmap region where ``axis_name`` is
# bound.  Bytes on the wire: int8 payload + fp32 scales (1/block of the
# element count), so each leg moves ~27% of the fp32 bytes.
def quantized_reduce_scatter(x2d, axis_name, block=INT8_BLOCK):
    """``x2d``: ``[n, chunk]`` float with ``chunk % block == 0`` and ``n``
    the size of ``axis_name``.  Quantizes each destination chunk once at
    the sender, exchanges int8 + scales via ``all_to_all``, and
    accumulates this rank's chunk from all contributions in fp32 —
    returns the reduced ``[chunk]`` fp32 chunk this rank owns."""
    q, s = quantize_int8_blocks(x2d, block)
    qx = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    sx = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    return jnp.sum(dequantize_int8_blocks(qx, sx, block), axis=0)


def quantized_all_gather(chunk, axis_name, block=INT8_BLOCK):
    """Requantize the (already reduced) ``[chunk]`` fp32 chunk and
    all-gather int8 + scales; returns the full fp32 ``[n * chunk]``
    vector, identical on every rank."""
    q, s = quantize_int8_blocks(chunk, block)
    qg = jax.lax.all_gather(q, axis_name, tiled=True)
    sg = jax.lax.all_gather(s, axis_name, tiled=True)
    return dequantize_int8_blocks(qg, sg, block)


def quantized_allreduce(flat, axis_name, block=INT8_BLOCK):
    """Block-scaled int8 allreduce of a flat float vector over
    ``axis_name``: quantized reduce-scatter + fp32 accumulate +
    quantized allgather.  Returns the fp32 sum; each element passes
    through exactly two quantizations (its contribution and the reduced
    result), so the error is bounded by ``(n + 1) * blockmax / 254``."""
    n = jax.lax.psum(1, axis_name)  # concrete inside shard_map
    size = flat.shape[0]
    chunk = -(-size // (n * block)) * block
    x = jnp.pad(flat.astype(jnp.float32), (0, n * chunk - size))
    red = quantized_reduce_scatter(x.reshape(n, chunk), axis_name, block)
    return quantized_all_gather(red, axis_name, block)[:size]


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) for decompression."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default: no compression."""

    name = "none"

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class BF16Compressor(Compressor):
    """Cast floating tensors to bfloat16 before the collective."""

    name = "bf16"

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(Compressor):
    """fp16 compressor for parity with the reference API surface."""

    name = "fp16"

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return tensor.astype(jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class Int8Compressor(Compressor):
    """Block-scaled int8 quantization (block 256, fp32 scales).

    Per-rank block scales cannot ride a plain ``psum`` (summing int8
    values quantized against different scales is meaningless), so
    axis-aware callers — ``allreduce_gradients``, the fused XLA
    executor, the TCP ring — detect ``block_quantized`` and run the
    quantized collective decomposition above.  The standalone
    ``compress``/``decompress`` pair used by axis-free call sites (the
    GSPMD path, Adasum's pytree reduce) simulates the quantize ->
    dequantize round trip locally: numerics match the quantized wire,
    bytes do not shrink (XLA owns the wire there).

    Non-float tensors and tensors smaller than one block pass through
    exactly.
    """

    name = "int8"
    block_quantized = True
    block = INT8_BLOCK

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if (not jnp.issubdtype(dtype, jnp.floating)
                or tensor.size < INT8_BLOCK):
            return tensor, None
        flat = tensor.reshape(-1).astype(jnp.float32)
        pad = (-flat.size) % INT8_BLOCK
        q, s = quantize_int8_blocks(jnp.pad(flat, (0, pad)))
        sim = dequantize_int8_blocks(q, s)[:flat.size]
        return sim.astype(dtype).reshape(tensor.shape), None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class Compression:
    """Optional gradient compression algorithm used during allreduce."""

    none = NoneCompressor
    bf16 = BF16Compressor
    fp16 = FP16Compressor
    int8 = Int8Compressor


# Canonical names travel the wire (controller messages, bucket keys,
# HVD_TPU_COMPRESSION); classes stay the Python API surface.
COMPRESSION_NAMES = {
    "none": NoneCompressor,
    "bf16": BF16Compressor,
    "fp16": FP16Compressor,
    "int8": Int8Compressor,
}


def resolve_compression(value, default="none") -> str:
    """Normalize a user-facing compression argument — ``None`` (use the
    configured default), a canonical name string, a ``Compressor``
    subclass or instance — to its canonical name."""
    if value is None:
        value = default
    if isinstance(value, str):
        name = value.lower()
        if name not in COMPRESSION_NAMES:
            raise ValueError(
                f"unknown compression {value!r}; expected one of "
                f"{sorted(COMPRESSION_NAMES)}")
        return name
    name = getattr(value, "name", None)
    if isinstance(name, str) and name in COMPRESSION_NAMES:
        return name
    raise ValueError(
        f"unknown compression {value!r}; expected one of "
        f"{sorted(COMPRESSION_NAMES)} or a Compression class")


def compressor_for(name):
    """Canonical name -> Compressor class."""
    return COMPRESSION_NAMES[resolve_compression(name)]
