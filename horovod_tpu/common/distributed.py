"""Multi-host JAX runtime bootstrap.

The reference forms its full process mesh at init from launcher env
(``horovod/common/gloo/gloo_context.cc:56-73``: HTTP-store rendezvous →
``connectFullMesh``).  The JAX analog is ``jax.distributed.initialize``:
process 0 hosts the coordinator, every process connects, and
``jax.devices()`` then spans the whole job — the global mesh the SPMD
data plane compiles against.

The coordinator address is published through the launcher's rendezvous
KV store (same channel the TCP controller uses), so the env contract
stays exactly the launcher's: ``HVD_RANK``/``HVD_SIZE`` +
``HVD_RENDEZVOUS_{ADDR,PORT}``.  ``HVD_COORDINATOR_ADDR`` overrides for
externally-managed jobs.
"""

import os
import socket

from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

JAXDIST_SCOPE = "jaxdist"
JAXDIST_KEY = "coordinator"


def _reserve_port() -> "tuple[socket.socket, int]":
    """Bind a free port and KEEP the socket open; the caller closes it
    immediately before handing the port to jax — shrinking the
    grab-the-port race window from publish-to-initialize down to
    microseconds (SO_REUSEADDR lets jax rebind while the probe socket is
    in TIME_WAIT-free close)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("", 0))
    return s, s.getsockname()[1]


def _my_address() -> str:
    """The address other processes use to reach this process's
    coordinator (process 0 only)."""
    iface = env_util.get_str(env_util.HVD_IFACE)
    if iface:
        from horovod_tpu.run.service import network
        ip = network.local_interfaces().get(iface)
        if ip:
            return ip
    rendezvous = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR, "")
    if rendezvous in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def initialize_jax_distributed(process_id: int, num_processes: int) -> None:
    """Connect this process to the job-wide JAX runtime (idempotent)."""
    import jax

    if num_processes <= 1:
        return
    try:
        from jax._src import distributed as _dist
        if getattr(_dist.global_state, "client", None) is not None:
            return  # already initialized (e.g. by the user)
    except ImportError:  # pragma: no cover — private module moved
        pass

    # CPU multi-process collectives need an explicit cross-process
    # implementation; harmless for TPU jobs (per-platform setting).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover — older jax
        pass

    coordinator = env_util.get_str(env_util.HVD_COORDINATOR_ADDR)
    reserved = None
    if not coordinator:
        addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
        port = env_util.get_str(env_util.HVD_RENDEZVOUS_PORT)
        if addr is None:
            raise RuntimeError(
                "global-mesh mode needs HVD_COORDINATOR_ADDR or the "
                "hvdrun rendezvous env contract to agree on the jax "
                "coordinator address")
        from horovod_tpu.run import http_client
        if process_id == 0:
            reserved, cport = _reserve_port()
            coordinator = f"{_my_address()}:{cport}"
            http_client.put(addr, int(port), JAXDIST_SCOPE, JAXDIST_KEY,
                            coordinator.encode())
        else:
            coordinator = http_client.get(
                addr, int(port), JAXDIST_SCOPE, JAXDIST_KEY,
                timeout=env_util.get_float(
                    env_util.HVD_START_TIMEOUT, 120.0)).decode()

    get_logger().debug(
        "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
        coordinator, num_processes, process_id)
    if reserved is not None:
        reserved.close()
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
