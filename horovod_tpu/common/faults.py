"""Deterministic fault injection for the collective runtime.

``HVD_TPU_FAULT_SPEC`` holds a comma-separated list of fault specs; each
spec triggers one failure at an exact step of an instrumented point, so
tests and chaos runs (``bin/hvd-chaos``) can reproduce a failure mode
bit-for-bit instead of waiting for it to happen in production:

    HVD_TPU_FAULT_SPEC="rank1:allreduce:2:crash,*:connect:1:refuse"

Grammar (one spec)::

    <target>:<point>:<step>:<action>

    target  rank<N> — only rank N trips the fault; * — any rank
    point   an instrumented site name.  Shipping points:
              allreduce / broadcast / allgather / alltoall / adasum
                  (controller submit path, before negotiation)
              ring      (ring data plane, after the coordinator's go-ahead
                         — i.e. mid-collective)
              send / recv   (ring chunk transport)
              connect   (any control/data-plane TCP connection attempt)
    step    1-based hit count of that point in this process: the fault
            fires on exactly the step-th call
    action  crash   — hard-exit the process (os._exit(1)): a dead rank
            drop    — silently skip the operation: a silent packet/worker
            refuse  — raise ConnectionRefusedError: a transport blip
            preempt — SIGTERM to self: the TPU preemption notice; the
                      operation itself proceeds, and the drain handler
                      (docs/checkpoint.md) decides what happens next

Counters are per-process and per-point.  The module is inert (one dict
lookup per check) when no spec is configured.
"""

import os
import signal
import sys
import threading

_ACTIONS = ("crash", "drop", "refuse", "preempt")


class FaultSpec:
    __slots__ = ("rank", "point", "step", "action")

    def __init__(self, rank, point, step, action):
        self.rank = rank        # int, or None for "*"
        self.point = point
        self.step = step
        self.action = action

    def __repr__(self):
        target = "*" if self.rank is None else f"rank{self.rank}"
        return f"{target}:{self.point}:{self.step}:{self.action}"


def parse_fault_spec(text):
    """Parse a spec string into FaultSpec objects; raises ValueError with
    the offending fragment so a typo fails the job at init, not at the
    (never-reached) injection point."""
    specs = []
    for part in (p.strip() for p in (text or "").split(",")):
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 4:
            raise ValueError(
                f"fault spec {part!r}: expected "
                f"<target>:<point>:<step>:<action>")
        target, point, step_s, action = fields
        if target == "*":
            rank = None
        elif target.startswith("rank"):
            try:
                rank = int(target[4:])
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: bad target {target!r}") from None
        else:
            raise ValueError(
                f"fault spec {part!r}: target must be rank<N> or *")
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(
                f"fault spec {part!r}: step must be an integer") from None
        if step < 1:
            raise ValueError(f"fault spec {part!r}: step is 1-based")
        if action not in _ACTIONS:
            raise ValueError(
                f"fault spec {part!r}: action must be one of {_ACTIONS}")
        if not point:
            raise ValueError(f"fault spec {part!r}: empty point")
        specs.append(FaultSpec(rank, point, step, action))
    return specs


class FaultInjector:
    """Counts hits per point and returns the matching action, if any."""

    def __init__(self, specs, rank=0):
        self._specs = list(specs)
        self._rank = rank
        self._counts = {}
        self._lock = threading.Lock()

    def fire(self, point):
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
        for spec in self._specs:
            if (spec.point == point and spec.step == n
                    and spec.rank in (None, self._rank)):
                return spec.action
        return None


_injector = None
_configured = False
_config_lock = threading.Lock()


def configure(spec_text, rank=0):
    """Install the process-wide injector (``hvd.init()`` calls this with
    the resolved config + rank; tests call it directly)."""
    global _injector, _configured
    with _config_lock:
        specs = parse_fault_spec(spec_text) if spec_text else []
        _injector = FaultInjector(specs, rank=rank) if specs else None
        _configured = True


def _auto_configure():
    """Fallback for points hit before ``hvd.init()`` (e.g. a connect
    during rendezvous): read the env contract directly.  Only WORKER
    processes (HVD_RANK present) arm the injector — the launcher/driver
    shares the spec env var but must neither trip rank-0 faults itself
    nor advance step counters the workers' determinism depends on."""
    from horovod_tpu.utils import env as env_util

    rank = env_util.get_str(env_util.HVD_RANK)
    if rank is None:
        configure(None)
    else:
        configure(env_util.get_str(env_util.HVD_TPU_FAULT_SPEC),
                  rank=env_util.get_int(env_util.HVD_RANK, 0))


def check(point) -> bool:
    """Trip any fault armed for this hit of ``point``.

    Returns True when the caller must DROP the operation; raises
    ConnectionRefusedError for ``refuse``; ``crash`` never returns.
    """
    if not _configured:
        _auto_configure()
    injector = _injector
    if injector is None:
        return False
    action = injector.fire(point)
    if action is None:
        return False
    if action == "drop":
        print(f"[hvd-fault] dropping {point} (injected)",
              file=sys.stderr, flush=True)
        return True
    if action == "refuse":
        raise ConnectionRefusedError(
            f"injected connection refusal at {point} (HVD_TPU_FAULT_SPEC)")
    if action == "preempt":
        # Deliver the preemption notice the way the platform would:
        # asynchronously, to this process, while the operation keeps
        # going.  With drain enabled the installed handler turns this
        # into a planned departure; without it, default disposition
        # kills the process (same observable as the real thing).
        print(f"[hvd-fault] preempting at {point} (injected SIGTERM)",
              file=sys.stderr, flush=True)
        os.kill(os.getpid(), signal.SIGTERM)
        return False
    # crash: bypass every handler — this models a rank dying mid-step
    print(f"[hvd-fault] crashing at {point} (injected)",
          file=sys.stderr, flush=True)
    sys.stderr.flush()
    os._exit(1)
