"""Deterministic fault injection for the collective runtime.

``HVD_TPU_FAULT_SPEC`` holds a comma-separated list of fault specs; each
spec triggers one failure at an exact step of an instrumented point, so
tests and chaos runs (``bin/hvd-chaos``) can reproduce a failure mode
bit-for-bit instead of waiting for it to happen in production:

    HVD_TPU_FAULT_SPEC="rank1:allreduce:2:crash,*:connect:1:refuse"

Grammar (one spec)::

    <target>:<point>:<step>:<action>[:<param>[:<duration_s>]]

    target  rank<N> — only rank N trips the fault; * — any rank
    point   an instrumented site name.  Shipping points:
              allreduce / broadcast / allgather / alltoall / adasum
                  (controller submit path, before negotiation)
              ring      (ring data plane, after the coordinator's go-ahead
                         — i.e. mid-collective)
              send / recv   (ring chunk transport)
              connect   (any control/data-plane TCP connection attempt)
              link      (framing layer: one hit per client-side frame
                         write — control, bulk-stripe, and mailbox paths)
    step    1-based hit count of that point in this process: the fault
            fires on exactly the step-th call
    action  crash   — hard-exit the process (os._exit(1)): a dead rank
            drop    — silently skip the operation: a silent packet/worker
            refuse  — raise ConnectionRefusedError: a transport blip
            preempt — SIGTERM to self: the TPU preemption notice; the
                      operation itself proceeds, and the drain handler
                      (docs/checkpoint.md) decides what happens next

Degraded-network actions (docs/fault_tolerance.md "degraded networks"):
unlike the binary actions above these do not fire once — they ARM at the
step-th hit of their point and then degrade every client-side frame
write for ``duration_s`` seconds (omitted: the rest of the run):

    delay:<ms>        add a fixed sleep before every frame write
    jitter:<ms>       add a uniform [0, ms) sleep before every write
    throttle:<MBps>   pace writes to at most MBps megabytes/second
    flaky:<p>         drop each write with probability p (the transport
                      raises BEFORE any bytes leave, so the ordinary
                      idempotent-send retry machinery absorbs it)
    partition:<lo-hi> cut every link that crosses the rank-range
                      boundary [lo, hi] (a simulated host group): writes
                      and connects between an in-group and an out-group
                      rank fail as if the hosts were partitioned

    HVD_TPU_FAULT_SPEC="rank1:link:1:delay:200:30,*:allreduce:3:flaky:0.2"

Mid-stream actions (docs/fault_tolerance.md "connection blips vs dead
peers"): unlike ``flaky`` — which re-rolls the loss BEFORE any byte
leaves the socket — these break the connection AFTER bytes are on the
wire, exercising the session layer's reconnect + replay path:

    reset:<p>         with probability p per frame write, write a
                      partial frame prefix, hard-close the socket and
                      raise ConnectionResetError (a genuine mid-stream
                      RST; arms like a degradation, optional duration)
    blip:<ms>         one-shot: the armed write hard-closes the link to
                      its peer and every write/connect toward that peer
                      is refused for the ms window, then accepted again
                      (a link flap; never re-arms)

Both accept ``*`` in the step field — armed from the first hit — in
addition to the 1-based step the other actions require:

    HVD_TPU_FAULT_SPEC="rank2:link:*:reset:0.3,rank1:link:5:blip:3000"

Degradations are deterministic under the existing seed contract: the
flaky/jitter/reset RNG is seeded from the spec text and the rank, so
the same spec on the same rank rolls the same sequence.

Counters are per-process and per-point.  The module is inert (one dict
lookup per check, one attribute read per frame write) when no spec is
configured.
"""

import math
import os
import random
import signal
import sys
import threading
import time
import zlib

_ACTIONS = ("crash", "drop", "refuse", "preempt")
# parameterized, duration-scoped degradations (arm-and-stay, not
# fire-once); applied at the framing layer via link()
_DEGRADE_ACTIONS = ("delay", "jitter", "throttle", "flaky", "partition")
# mid-stream link breaks: armed like degradations, but they sever the
# connection AFTER bytes hit the wire so the session layer's
# reconnect + replay path is what absorbs them
_MIDSTREAM_ACTIONS = ("reset", "blip")


class FaultSpec:
    __slots__ = ("rank", "point", "step", "action", "param", "duration")

    def __init__(self, rank, point, step, action, param=None,
                 duration=None):
        self.rank = rank        # int, or None for "*"
        self.point = point
        self.step = step
        self.action = action
        self.param = param      # float, or (lo, hi) for partition
        self.duration = duration  # seconds the degradation stays armed

    def __repr__(self):
        target = "*" if self.rank is None else f"rank{self.rank}"
        step = "*" if self.step is None else self.step
        base = f"{target}:{self.point}:{step}:{self.action}"
        if self.action in _DEGRADE_ACTIONS + _MIDSTREAM_ACTIONS:
            if self.action == "partition":
                base += f":{self.param[0]}-{self.param[1]}"
            else:
                base += f":{self.param:g}"
            if self.duration is not None:
                base += f":{self.duration:g}"
        return base


def _parse_degrade_param(part, action, text):
    if action == "partition":
        lo, sep, hi = text.partition("-")
        try:
            lo_i, hi_i = int(lo), int(hi)
        except ValueError:
            raise ValueError(
                f"fault spec {part!r}: partition wants <lo>-<hi> rank "
                f"range, got {text!r}") from None
        if not sep or lo_i < 0 or hi_i < lo_i:
            raise ValueError(
                f"fault spec {part!r}: partition wants <lo>-<hi> with "
                f"0 <= lo <= hi")
        return (lo_i, hi_i)
    try:
        value = float(text)
    except ValueError:
        raise ValueError(
            f"fault spec {part!r}: {action} wants a numeric parameter, "
            f"got {text!r}") from None
    # float() happily parses "nan"/"inf", and nan slides through every
    # one-sided range check below (nan < 0 is False) — a nan delay
    # would reach time.sleep() and crash the transport write path
    if not math.isfinite(value):
        raise ValueError(
            f"fault spec {part!r}: {action} parameter must be finite, "
            f"got {text!r}")
    if action == "flaky":
        if not 0.0 <= value <= 1.0:
            raise ValueError(
                f"fault spec {part!r}: flaky probability must be in "
                f"[0, 1], got {value:g}")
    elif action == "throttle":
        if value <= 0:
            raise ValueError(
                f"fault spec {part!r}: throttle rate must be > 0 MBps")
    elif value < 0:
        raise ValueError(
            f"fault spec {part!r}: {action} must be >= 0 ms")
    return value


def parse_fault_spec(text):
    """Parse a spec string into FaultSpec objects; raises ValueError with
    the offending fragment so a typo fails the job at init, not at the
    (never-reached) injection point."""
    specs = []
    for part in (p.strip() for p in (text or "").split(",")):
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 4:
            raise ValueError(
                f"fault spec {part!r}: expected "
                f"<target>:<point>:<step>:<action>[:<param>"
                f"[:<duration_s>]]")
        target, point, step_s, action = fields[:4]
        if target == "*":
            rank = None
        elif target.startswith("rank"):
            try:
                rank = int(target[4:])
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: bad target {target!r}") from None
        else:
            raise ValueError(
                f"fault spec {part!r}: target must be rank<N> or *")
        if step_s == "*":
            # "armed from the first hit" only makes sense for the
            # mid-stream breaks — every other action fires exactly once
            if action not in _MIDSTREAM_ACTIONS:
                raise ValueError(
                    f"fault spec {part!r}: step * is only valid for "
                    f"{'/'.join(_MIDSTREAM_ACTIONS)}")
            step = None
        else:
            try:
                step = int(step_s)
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: step must be an integer "
                    f"or *") from None
            if step < 1:
                raise ValueError(f"fault spec {part!r}: step is 1-based")
        if not point:
            raise ValueError(f"fault spec {part!r}: empty point")
        param = duration = None
        if action in _DEGRADE_ACTIONS:
            if len(fields) not in (5, 6):
                raise ValueError(
                    f"fault spec {part!r}: {action} wants "
                    f"<target>:<point>:<step>:{action}:<param>"
                    f"[:<duration_s>]")
            param = _parse_degrade_param(part, action, fields[4])
            if len(fields) == 6:
                try:
                    duration = float(fields[5])
                except ValueError:
                    raise ValueError(
                        f"fault spec {part!r}: duration must be "
                        f"seconds") from None
                if not math.isfinite(duration) or duration <= 0:
                    raise ValueError(
                        f"fault spec {part!r}: duration must be > 0")
        elif action == "reset":
            if len(fields) not in (5, 6):
                raise ValueError(
                    f"fault spec {part!r}: reset wants "
                    f"<target>:<point>:<step>:reset:<p>[:<duration_s>]")
            try:
                param = float(fields[4])
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: reset wants a probability, "
                    f"got {fields[4]!r}") from None
            if not math.isfinite(param) or not 0.0 <= param <= 1.0:
                raise ValueError(
                    f"fault spec {part!r}: reset probability must be in "
                    f"[0, 1], got {param:g}")
            if len(fields) == 6:
                try:
                    duration = float(fields[5])
                except ValueError:
                    raise ValueError(
                        f"fault spec {part!r}: duration must be "
                        f"seconds") from None
                if not math.isfinite(duration) or duration <= 0:
                    raise ValueError(
                        f"fault spec {part!r}: duration must be > 0")
        elif action == "blip":
            if len(fields) != 5:
                raise ValueError(
                    f"fault spec {part!r}: blip wants "
                    f"<target>:<point>:<step>:blip:<window_ms>")
            try:
                param = float(fields[4])
            except ValueError:
                raise ValueError(
                    f"fault spec {part!r}: blip wants a window in ms, "
                    f"got {fields[4]!r}") from None
            if not math.isfinite(param) or param < 0:
                raise ValueError(
                    f"fault spec {part!r}: blip window must be >= 0 ms")
        elif action in _ACTIONS:
            if len(fields) != 4:
                raise ValueError(
                    f"fault spec {part!r}: {action} takes no parameter")
        else:
            raise ValueError(
                f"fault spec {part!r}: action must be one of "
                f"{_ACTIONS + _DEGRADE_ACTIONS + _MIDSTREAM_ACTIONS}")
        specs.append(FaultSpec(rank, point, step, action, param=param,
                               duration=duration))
    return specs


class LinkState:
    """Per-frame-write verdict aggregated over the armed degradations.

    ``delay_s`` is the resolved sleep for THIS write (fixed delays plus
    the jitter roll); ``throttle_bps`` is the tightest armed pacing rate
    in bytes/second (0: unthrottled); ``drop`` is the flaky roll for
    this write; ``partitioned`` means the (rank, peer) link crosses an
    armed partition boundary and the write must fail outright; ``reset``
    means the transport must sever the connection MID-FRAME (partial
    prefix on the wire, hard close, ConnectionResetError) so the
    session layer's reconnect + replay path absorbs it."""

    __slots__ = ("delay_s", "throttle_bps", "drop", "partitioned",
                 "reset")

    def __init__(self, delay_s=0.0, throttle_bps=0.0, drop=False,
                 partitioned=False, reset=False):
        self.delay_s = delay_s
        self.throttle_bps = throttle_bps
        self.drop = drop
        self.partitioned = partitioned
        self.reset = reset

    def __bool__(self):
        return bool(self.delay_s or self.throttle_bps or self.drop
                    or self.partitioned or self.reset)


class FaultInjector:
    """Counts hits per point and returns the matching action, if any.

    Degradation specs never return an action from :meth:`fire` — the
    step-th hit of their point ARMS them (stamping the activation time)
    and :meth:`link` aggregates whatever is currently active."""

    def __init__(self, specs, rank=0, seed_text=""):
        self._specs = list(specs)
        self._rank = rank
        self._counts = {}
        # spec -> monotonic arm time; guarded by self._lock
        self._armed = {}
        self._lock = threading.Lock()
        self._degrade = [s for s in self._specs
                         if s.action in _DEGRADE_ACTIONS
                         + _MIDSTREAM_ACTIONS
                         and s.rank in (None, rank)]
        # peer -> monotonic end of an open blip window (the link toward
        # that peer refuses writes AND reconnects until then); guarded
        # by self._lock
        self._blips = {}
        # step `*` mid-stream specs are armed from process start — no
        # counted hit has to happen first
        for spec in self._degrade:
            if spec.step is None:
                self._armed[spec] = time.monotonic()
        # hits of "link" only matter when a spec watches that point —
        # keeps the per-frame-write hot path to one attribute read when
        # faults are armed for other points only
        self.link_live = bool(self._degrade) or any(
            s.point == "link" for s in self._specs)
        # deterministic under the seed contract: same spec text + rank
        # -> same flaky/jitter roll sequence; guarded by self._lock
        self._rng = random.Random(
            zlib.crc32(seed_text.encode()) ^ (rank * 0x9E3779B1))

    def fire(self, point):
        now = time.monotonic()
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            for spec in self._degrade:
                if (spec.point == point and spec.step == n
                        and spec not in self._armed):
                    self._armed[spec] = now
        for spec in self._specs:
            if (spec.point == point and spec.step == n
                    and spec.rank in (None, self._rank)
                    and spec.action in _ACTIONS):
                return spec.action
        return None

    def _active_locked(self, now):  # holds: self._lock
        for spec, armed_at in self._armed.items():
            if spec.duration is None or now - armed_at <= spec.duration:
                yield spec

    def link(self, peer=None):
        """One client-side frame write toward ``peer`` (None: unknown).
        Counts a hit of the "link" point (which may arm link-stepped
        specs or trip a binary action) and returns the aggregated
        LinkState, or None when nothing is active."""
        action = self.fire("link")
        if not self._degrade:
            return _binary_link_state(action)
        delay = jitter = 0.0
        throttle = 0.0
        flaky = 0.0
        reset_p = 0.0
        partitioned = reset = False
        now = time.monotonic()
        with self._lock:
            tripped = []
            for spec in self._active_locked(now):
                if spec.action == "delay":
                    delay = max(delay, spec.param / 1000.0)
                elif spec.action == "jitter":
                    jitter = max(jitter, spec.param / 1000.0)
                elif spec.action == "throttle":
                    bps = spec.param * 1e6
                    throttle = bps if throttle == 0 \
                        else min(throttle, bps)
                elif spec.action == "flaky":
                    flaky = max(flaky, spec.param)
                elif spec.action == "reset":
                    reset_p = max(reset_p, spec.param)
                elif spec.action == "blip" and peer is not None:
                    # one-shot: THIS write severs the link toward its
                    # peer and opens the refuse window; never re-arms
                    self._blips[peer] = now + spec.param / 1000.0
                    tripped.append(spec)
                    reset = True
                elif spec.action == "partition" and peer is not None:
                    lo, hi = spec.param
                    if (lo <= self._rank <= hi) != (lo <= peer <= hi):
                        partitioned = True
            for spec in tripped:
                del self._armed[spec]
            if peer is not None and peer in self._blips:
                if now < self._blips[peer]:
                    reset = True
                else:
                    del self._blips[peer]
            if jitter > 0:
                delay += self._rng.uniform(0.0, jitter)
            drop = flaky > 0 and self._rng.random() < flaky
            if reset_p > 0 and self._rng.random() < reset_p:
                reset = True
        state = LinkState(delay_s=delay, throttle_bps=throttle,
                          drop=drop, partitioned=partitioned,
                          reset=reset)
        if action is not None:
            state.drop = state.drop or action == "drop"
            _trip_binary(action, "link")
        return state if state else None

    def blip_blocked(self, peer):
        """True while an open blip window covers the link toward
        ``peer`` — reconnect attempts inside the window must be refused
        (the flap is still down), so the session layer's backoff loop
        is what rides it out."""
        if peer is None or not self._blips:
            return False
        now = time.monotonic()
        with self._lock:
            until = self._blips.get(peer)
            if until is None:
                return False
            if now < until:
                return True
            del self._blips[peer]
            return False


def _binary_link_state(action):
    if action is None:
        return None
    if action == "drop":
        return LinkState(drop=True)
    _trip_binary(action, "link")
    return None


_injector = None
_configured = False
_config_lock = threading.Lock()


def configure(spec_text, rank=0):
    """Install the process-wide injector (``hvd.init()`` calls this with
    the resolved config + rank; tests call it directly)."""
    global _injector, _configured
    with _config_lock:
        specs = parse_fault_spec(spec_text) if spec_text else []
        _injector = (FaultInjector(specs, rank=rank,
                                   seed_text=spec_text or "")
                     if specs else None)
        _configured = True


def _auto_configure():
    """Fallback for points hit before ``hvd.init()`` (e.g. a connect
    during rendezvous): read the env contract directly.  Only WORKER
    processes (HVD_RANK present) arm the injector — the launcher/driver
    shares the spec env var but must neither trip rank-0 faults itself
    nor advance step counters the workers' determinism depends on."""
    from horovod_tpu.utils import env as env_util

    rank = env_util.get_str(env_util.HVD_RANK)
    if rank is None:
        configure(None)
    else:
        configure(env_util.get_str(env_util.HVD_TPU_FAULT_SPEC),
                  rank=env_util.get_int(env_util.HVD_RANK, 0))


def _trip_binary(action, point):
    """Apply a fired binary action; shared by check() and link()."""
    if action == "refuse":
        raise ConnectionRefusedError(
            f"injected connection refusal at {point} (HVD_TPU_FAULT_SPEC)")
    if action == "preempt":
        # Deliver the preemption notice the way the platform would:
        # asynchronously, to this process, while the operation keeps
        # going.  With drain enabled the installed handler turns this
        # into a planned departure; without it, default disposition
        # kills the process (same observable as the real thing).
        print(f"[hvd-fault] preempting at {point} (injected SIGTERM)",
              file=sys.stderr, flush=True)
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if action == "crash":
        # crash: bypass every handler — this models a rank dying mid-step
        print(f"[hvd-fault] crashing at {point} (injected)",
              file=sys.stderr, flush=True)
        sys.stderr.flush()
        os._exit(1)


def check(point, peer=None) -> bool:
    """Trip any fault armed for this hit of ``point``.

    Returns True when the caller must DROP the operation; raises
    ConnectionRefusedError for ``refuse``; ``crash`` never returns.
    ``peer`` scopes per-link faults: a ``connect`` toward a peer whose
    blip window is still open is refused (the flap is still down).
    """
    if not _configured:
        _auto_configure()
    injector = _injector
    if injector is None:
        return False
    if point == "connect" and injector.blip_blocked(peer):
        raise ConnectionRefusedError(
            f"injected link blip toward peer {peer}: connection "
            f"refused (HVD_TPU_FAULT_SPEC)")
    action = injector.fire(point)
    if action is None:
        return False
    if action == "drop":
        print(f"[hvd-fault] dropping {point} (injected)",
              file=sys.stderr, flush=True)
        return True
    _trip_binary(action, point)
    return False


def link(peer=None):
    """Degraded-network verdict for one client-side frame write toward
    ``peer`` (a rank, or None when the peer's rank is unknown).  Returns
    a :class:`LinkState` to apply, or None on the (fast) healthy path.

    The transport applies it BEFORE any bytes leave the socket: delay/
    jitter/throttle sleep, flaky raises so the idempotent-send retry
    absorbs it, partition fails the write like an unreachable host."""
    if not _configured:
        _auto_configure()
    injector = _injector
    if injector is None or not injector.link_live:
        return None
    return injector.link(peer)
