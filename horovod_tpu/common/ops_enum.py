"""Reduction-op enum shared by every binding.

Mirrors the reference's ``ReduceOp`` surface (``horovod/torch/mpi_ops.py:60``:
Average / Sum / Adasum) plus the internal request types
(``horovod/common/message.h:47``).
"""

import enum

# Block-scaled int8 wire format: one fp32 scale per this many elements.
# Lives here (jax-free) because BOTH data planes must agree on it — the
# compiled XLA programs (common/compression.py quantizers) and the
# numpy TCP ring codecs (ops/tcp_dataplane.py).
INT8_BLOCK = 256


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM


class RequestType(enum.IntEnum):
    """What a rank asks the coordinator for (reference: message.h RequestType)."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    REDUCE_SCATTER = 6


class ResponseType(enum.IntEnum):
    """What the coordinator tells ranks to run (reference: message.h ResponseType)."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    ERROR = 6
    REDUCE_SCATTER = 7


def reduce_scatter_split_sizes(dim0, num_ranks):
    """First-dimension block sizes for REDUCE_SCATTER, np.array_split
    style: the first ``dim0 % num_ranks`` ranks get one extra row.  Both
    data planes and every controller must agree on this partition, so it
    lives here (jax- and numpy-free)."""
    base, extra = divmod(int(dim0), int(num_ranks))
    return [base + 1 if r < extra else base for r in range(num_ranks)]


def is_float_dtype(dt) -> bool:
    """Float detection covering ml_dtypes extension types (bfloat16,
    float8_*) whose numpy kind is not 'f' — shared by the TCP star and
    ring data planes and the torch binding."""
    import numpy as np

    dt = np.dtype(dt)
    return np.issubdtype(dt, np.floating) or "float" in dt.name
