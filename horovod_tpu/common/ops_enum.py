"""Reduction-op enum shared by every binding.

Mirrors the reference's ``ReduceOp`` surface (``horovod/torch/mpi_ops.py:60``:
Average / Sum / Adasum) plus the internal request types
(``horovod/common/message.h:47``).
"""

import enum

# Block-scaled int8 wire format: one fp32 scale per this many elements.
# Lives here (jax-free) because BOTH data planes must agree on it — the
# compiled XLA programs (common/compression.py quantizers) and the
# numpy TCP ring codecs (ops/tcp_dataplane.py).
INT8_BLOCK = 256


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM


class RequestType(enum.IntEnum):
    """What a rank asks the coordinator for (reference: message.h RequestType)."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5


class ResponseType(enum.IntEnum):
    """What the coordinator tells ranks to run (reference: message.h ResponseType)."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    ERROR = 6


def is_float_dtype(dt) -> bool:
    """Float detection covering ml_dtypes extension types (bfloat16,
    float8_*) whose numpy kind is not 'f' — shared by the TCP star and
    ring data planes and the torch binding."""
    import numpy as np

    dt = np.dtype(dt)
    return np.issubdtype(dt, np.floating) or "float" in dt.name
