"""Shared fusion-bucket planning (reference: ``controller.cc:640``
FuseResponses — greedy run of compatible allreduces up to the threshold).

One implementation used by the in-process controllers (bucketing
GroupEntries) and the gmesh coordinator (bucketing metadata), so the
bucket-compatibility rules cannot drift between single-host and pod
modes."""


def plan_buckets(items, *, key_fn, nbytes_fn, threshold):
    """Greedy in-order bucketing.

    Yields lists of consecutive ``items`` sharing ``key_fn(item)`` whose
    cumulative ``nbytes_fn(item)`` stays within ``threshold``.  A new
    key or a full bucket starts the next one (an oversize single item
    still gets its own bucket)."""
    bucket, bucket_key, bucket_bytes = [], None, 0
    for item in items:
        key = key_fn(item)
        nbytes = nbytes_fn(item)
        if bucket and (key != bucket_key
                       or bucket_bytes + nbytes > threshold):
            yield bucket
            bucket, bucket_bytes = [], 0
        bucket.append(item)
        bucket_key = key
        bucket_bytes += nbytes
    if bucket:
        yield bucket
