"""Graceful drain: turn SIGTERM (the TPU preemption notice) into a
planned departure instead of a crash.

Protocol (docs/checkpoint.md): the handler marks the process draining
and a notifier thread announces departure to the rank-0 coordinator
(``controller.request_drain()`` → ``DrainMsg``).  The coordinator
excludes the rank from liveness blame, plans an elastic
reconfiguration WITHOUT this rank, waits for the next collective
boundary, and publishes the drain-marked directive.  Survivors re-form
via the ordinary elastic path; the draining rank sees the directive at
its next collective, tears down, and leaves with
:class:`~horovod_tpu.common.handles.HvdDrainedError` — exit 0, zero
``HvdAbortedError`` anywhere.

When the coordinator refuses the drain (rank 0 itself, elastic off,
survivors would drop below min_ranks) the preemption is not
survivable: the process exits 143 (SIGTERM's conventional code), which
the launcher attributes exactly like the real preemption death it
models.
"""

import os
import signal
import sys
import threading

from horovod_tpu.common import busy

_requested = threading.Event()
_installed_lock = threading.Lock()
_installed = False


def requested() -> bool:
    """True once this process has received its preemption notice."""
    return _requested.is_set()


def reset():
    """Test hook: forget a previous drain request / installation."""
    global _installed
    _requested.clear()
    with _installed_lock:
        _installed = False


def _notify(get_controller):
    # Slow-by-design window: announcing + waiting for the coordinator's
    # boundary ack can take seconds; don't let it read as death.
    with busy.window():
        controller = get_controller()
        ok = False
        if controller is not None:
            try:
                ok = controller.request_drain()
            except Exception as exc:  # noqa: BLE001 — a dead
                # coordinator while we're being preempted: nothing to
                # drain into, fall through to the unsurvivable path
                print(f"[hvd-drain] drain announce failed: {exc}",
                      file=sys.stderr, flush=True)
    if ok:
        print("[hvd-drain] departure announced; leaving at the next "
              "collective boundary", file=sys.stderr, flush=True)
        return
    print("[hvd-drain] drain refused/impossible; exiting as preempted",
          file=sys.stderr, flush=True)
    os._exit(143)


def install(get_controller) -> bool:
    """Install the drain SIGTERM handler (``hvd.init()`` calls this when
    ``config.drain`` and the controller supports ``request_drain``).

    ``get_controller`` is a zero-arg callable resolved at SIGNAL time —
    an elastic reconfiguration replaces the controller object, and the
    drain must talk to the current one.  Returns False when the handler
    could not be installed (non-main thread)."""
    global _installed
    with _installed_lock:
        if _installed:
            return True

        def _handler(signum, frame):
            if _requested.is_set():
                return  # duplicate notice: drain already in flight
            _requested.set()
            t = threading.Thread(target=_notify, args=(get_controller,),
                                 name="hvd-drain", daemon=True)
            # lifecycle: fire-and-forget by design — it either returns
            # after a successful announce or ends the process itself
            t.start()

        try:
            signal.signal(signal.SIGTERM, _handler)
        except ValueError:
            # not the main thread (embedded init): no drain handling,
            # SIGTERM keeps its previous disposition
            return False
        _installed = True
        return True
