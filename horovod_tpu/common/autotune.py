"""Python face of the native autotuner (reference:
``horovod/common/parameter_manager.{h,cc}``,
``horovod/common/optim/bayesian_optimization.cc``,
``horovod/common/optim/gaussian_process.cc``).

The math and the tuning walk live in C++ (``csrc/hvd/parameter_manager.cc``,
``csrc/hvd/optim/``); these thin ctypes wrappers exist for tests (numpy
oracle comparisons) and for embedding the tuner in pure-Python controllers.
"""

import ctypes

import numpy as np


_lib_handle = None


def _lib():
    global _lib_handle
    if _lib_handle is None:
        from horovod_tpu.ops.native_controller import _load_lib
        _lib_handle = _load_lib()
    return _lib_handle


def _as_dbl(arr):
    a = np.ascontiguousarray(arr, dtype=np.float64)
    return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class GaussianProcess:
    """GP regression with an RBF kernel (native implementation).

    k(a, b) = signal_variance * exp(-||a-b||^2 / (2 length_scale^2)),
    observation noise ``noise_variance`` added on the diagonal.
    """

    def __init__(self, length_scale=1.0, signal_variance=1.0,
                 noise_variance=1e-6):
        self._lib = _lib()
        self._h = self._lib.hvd_gp_create(length_scale, signal_variance,
                                          noise_variance)

    def fit(self, x, y):
        x = np.asarray(x, dtype=np.float64)
        if x.ndim == 1:
            # n one-dimensional samples — np.atleast_2d would instead
            # produce ONE n-dimensional sample (1, n) and silently fit
            # a garbage model against a length-mismatched y
            x = x[:, None]
        y = np.asarray(y, dtype=np.float64)
        if len(y) != x.shape[0]:
            raise ValueError(
                f"x has {x.shape[0]} samples but y has {len(y)}")
        xa, xp = _as_dbl(x)
        ya, yp = _as_dbl(y)
        rc = self._lib.hvd_gp_fit(self._h, xp, yp, x.shape[0], x.shape[1])
        if rc != 0:
            raise ValueError("GP fit failed: kernel matrix not SPD")
        return self

    def predict(self, x):
        """Posterior (mean, variance) at a single point."""
        xa, xp = _as_dbl(np.asarray(x, dtype=np.float64).ravel())
        mean = ctypes.c_double()
        var = ctypes.c_double()
        self._lib.hvd_gp_predict(self._h, xp, xa.size, ctypes.byref(mean),
                                 ctypes.byref(var))
        return mean.value, var.value

    def __del__(self):
        try:
            self._lib.hvd_gp_destroy(self._h)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def expected_improvement(mean, stddev, best, xi=0.01):
    """EI for maximization (native implementation)."""
    return float(_lib().hvd_expected_improvement(mean, stddev, best, xi))


class BayesianOptimizer:
    """GP + expected-improvement search over a box (native)."""

    def __init__(self, low, high, gp_noise=1e-4, num_candidates=256):
        self._lib = _lib()
        low = np.asarray(low, dtype=np.float64)
        high = np.asarray(high, dtype=np.float64)
        self._dim = low.size
        la, lp = _as_dbl(low)
        ha, hp = _as_dbl(high)
        self._h = self._lib.hvd_bo_create(lp, hp, self._dim, gp_noise,
                                          num_candidates)

    def add_sample(self, x, y):
        xa, xp = _as_dbl(np.asarray(x, dtype=np.float64).ravel())
        self._lib.hvd_bo_add_sample(self._h, xp, self._dim, float(y))

    def suggest(self):
        out = np.zeros(self._dim, dtype=np.float64)
        oa, op = _as_dbl(out)
        self._lib.hvd_bo_suggest(self._h, op, self._dim)
        return oa.copy()

    @property
    def best_y(self):
        return float(self._lib.hvd_bo_best_y(self._h))

    def __del__(self):
        try:
            self._lib.hvd_bo_destroy(self._h)
        except Exception:  # noqa: BLE001
            pass


class ParameterManager:
    """Virtual-clock ParameterManager handle (native).  The embedded core
    drives its own instance off the background loop; this standalone handle
    is for tests and for pure-Python controllers."""

    def __init__(self, warmup_samples=3, steady_state_samples=10,
                 bayes_opt_max_samples=20, gp_noise=0.8, log_path=None,
                 fusion_threshold_bytes=64 * 1024 * 1024, cycle_time_ms=1.0,
                 hierarchical_allreduce=False, hierarchical_allgather=False,
                 cache_enabled=True, compression=False,
                 compression_available=False,
                 ring_segment_bytes=1 << 20, ring_stripes=2,
                 ring_tunable=False, schedule=0, schedule_tunable=False):
        self._lib = _lib()
        self._h = self._lib.hvd_pm_create(
            warmup_samples, steady_state_samples, bayes_opt_max_samples,
            gp_noise, log_path.encode() if log_path else None,
            fusion_threshold_bytes, cycle_time_ms,
            1 if hierarchical_allreduce else 0,
            1 if hierarchical_allgather else 0,
            1 if cache_enabled else 0,
            1 if compression else 0,
            1 if compression_available else 0,
            int(ring_segment_bytes), int(ring_stripes),
            1 if ring_tunable else 0, int(schedule),
            1 if schedule_tunable else 0)

    def record(self, nbytes):
        self._lib.hvd_pm_record(self._h, int(nbytes))

    def update(self, now_seconds):
        return bool(self._lib.hvd_pm_update(self._h, float(now_seconds)))

    @property
    def fusion_threshold_bytes(self):
        return int(self._lib.hvd_pm_fusion_bytes(self._h))

    @property
    def cycle_time_ms(self):
        return float(self._lib.hvd_pm_cycle_ms(self._h))

    @property
    def hierarchical_allreduce(self):
        return bool(self._lib.hvd_pm_hierarchical_allreduce(self._h))

    @property
    def hierarchical_allgather(self):
        return bool(self._lib.hvd_pm_hierarchical_allgather(self._h))

    @property
    def cache_enabled(self):
        return bool(self._lib.hvd_pm_cache_enabled(self._h))

    @property
    def compression_enabled(self):
        return bool(self._lib.hvd_pm_compression_enabled(self._h))

    @property
    def ring_segment_bytes(self):
        return int(self._lib.hvd_pm_ring_segment_bytes(self._h))

    @property
    def ring_stripes(self):
        return int(self._lib.hvd_pm_ring_stripes(self._h))

    @property
    def schedule(self):
        """Tuned collective schedule as the index into the canonical
        name tuple (``ops/tcp_dataplane.py`` ``SCHEDULES``)."""
        return int(self._lib.hvd_pm_schedule(self._h))

    @property
    def tuning(self):
        return bool(self._lib.hvd_pm_tuning(self._h))

    @property
    def best_score(self):
        return float(self._lib.hvd_pm_best_score(self._h))

    def __del__(self):
        try:
            self._lib.hvd_pm_destroy(self._h)
        except Exception:  # noqa: BLE001
            pass
