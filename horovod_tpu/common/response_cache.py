"""Shared steady-state signature cache (reference:
``horovod/common/response_cache.cc`` — LRU of validated tensor
signatures; a name whose every rank resubmits the signature of the last
validated round skips re-validation).

One implementation shared by the python and tcp controllers so
HIT/MISS/eviction semantics cannot drift between them.  Signatures are
opaque hashables: the python controller uses parameter tuples, the tcp
controller wire-digest bytes.  The gmesh controller deliberately has no
signature cache: its coordinator round-trip is already a long-polled
append-only log (O(names) metadata, amortized across cycles) and its
steady-state fast path is the executor's compiled-program cache.
"""

from collections import OrderedDict


class SignatureCache:
    """name -> last validated signature, LRU-bounded.

    States map onto the reference's MISS/HIT/INVALID:
    - ``check`` True  == HIT (skip validation),
    - ``check`` False == MISS (validate, then ``store``),
    - ``evict``       == INVALID (stalled or signature changed).
    """

    def __init__(self, capacity=1024):
        self._entries = OrderedDict()
        self._capacity = capacity
        self.hits = 0
        self.enabled = True   # autotune's cache_enabled knob lands here

    def check(self, name, sigs) -> bool:
        """True iff every rank's signature agrees and matches the cached
        one.  ``sigs`` is the set (or iterable) of per-rank signatures;
        ``None`` (signature unavailable) never matches."""
        if not self.enabled:
            return False
        sigs = set(sigs)
        if len(sigs) != 1 or None in sigs:
            return False
        cached = self._entries.get(name)
        if cached is not None and cached == next(iter(sigs)):
            self._entries.move_to_end(name)
            self.hits += 1
            return True
        return False

    def store(self, name, sigs):
        """Record a validated round's signature; only when all ranks
        agreed (a mixed set means validation rejected or per-rank shapes
        legitimately differ, e.g. variable-dim0 allgather)."""
        if not self.enabled:
            return
        sigs = set(sigs)
        if len(sigs) != 1 or None in sigs:
            return
        self._entries[name] = next(iter(sigs))
        self._entries.move_to_end(name)
        while len(self._entries) > self._capacity:
            self._entries.popitem(last=False)

    def evict(self, name):
        self._entries.pop(name, None)

    def clear(self):
        """Drop every cached signature.  Called on coordinated abort: a
        signature validated before the abort must never short-circuit
        validation for a post-reconfiguration membership (same tensor
        name, different world)."""
        self._entries.clear()

    def __len__(self):
        return len(self._entries)
