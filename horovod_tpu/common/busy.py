"""Busy windows: tell the liveness tracker "slow, not dead".

A rank inside a checkpoint shard write or a drain teardown can stall
its heartbeat loop behind disk I/O for longer than the liveness window;
without this, a clean drain or a routine snapshot converts into a
coordinated abort (docs/checkpoint.md).  The heartbeat loop stamps
every ``HeartbeatMsg`` with :func:`active`, and the coordinator doubles
the liveness deadline for ranks whose last heartbeat was busy-flagged.

Cheap and lock-light: a counter under a lock, nested windows allowed.
"""

import contextlib
import threading

_lock = threading.Lock()
_depth = 0


@contextlib.contextmanager
def window():
    """Mark this process busy (slow I/O expected) for the duration."""
    global _depth
    with _lock:
        _depth += 1
    try:
        yield
    finally:
        with _lock:
            _depth -= 1


def active() -> bool:
    with _lock:
        return _depth > 0
