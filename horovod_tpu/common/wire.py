"""Binary wire codec, the Python half of ``csrc/hvd/message.{h,cc}``.

Little-endian, length-prefixed strings; layout must match the C++
Writer/Reader exactly (the reference uses FlatBuffers for the same role:
``horovod/common/wire/message.fbs``).
"""

import struct

import numpy as np

# numpy dtype name -> hvd::DataType code
DTYPE_CODES = {
    "float32": 0,
    "float64": 1,
    "bfloat16": 2,
    "float16": 3,
    "int8": 4,
    "int16": 5,
    "int32": 6,
    "int64": 7,
    "uint8": 8,
    "bool": 9,
}


def dtype_code(dtype) -> int:
    try:
        name = np.dtype(dtype).name
    except TypeError:
        name = str(dtype)
    return DTYPE_CODES[name]


def encode_request(req_id, rank, req_type, op, dtype, root_rank, prescale,
                   postscale, name, shape, splits):
    name_bytes = name.encode()
    parts = [
        struct.pack("<QiBBBidd", req_id, rank, int(req_type), int(op),
                    dtype_code(dtype) if dtype is not None else 0,
                    root_rank, prescale, postscale),
        struct.pack("<I", len(name_bytes)),
        name_bytes,
        struct.pack("<I", len(shape)),
        struct.pack(f"<{len(shape)}q", *shape) if shape else b"",
        struct.pack("<I", len(splits or [])),
        struct.pack(f"<{len(splits)}q", *splits) if splits else b"",
    ]
    return b"".join(parts)


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.off = 0

    def take(self, fmt):
        vals = struct.unpack_from("<" + fmt, self.buf, self.off)
        self.off += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def string(self):
        n = self.take("I")
        s = self.buf[self.off:self.off + n].decode()
        self.off += n
        return s


def decode_batch(buf):
    """Decode a ResponseBatch -> (batch_id, shutdown, responses).

    Each response is a dict with type/op/dtype/prescale/postscale/error and
    entries of (name, [(rank, req_id)...], joined_ranks, root_rank).
    """
    r = _Reader(buf)
    batch_id = r.take("Q")
    shutdown = bool(r.take("B"))
    responses = []
    for _ in range(r.take("I")):
        resp_type = r.take("B")
        op = r.take("B")
        dtype = r.take("B")
        prescale = r.take("d")
        postscale = r.take("d")
        error = r.string()
        entries = []
        for _ in range(r.take("I")):
            name = r.string()
            parts = []
            for _ in range(r.take("I")):
                rank = r.take("i")
                req_id = r.take("Q")
                parts.append((rank, req_id))
            joined = [r.take("i") for _ in range(r.take("I"))]
            root_rank = r.take("i")
            entries.append((name, parts, joined, root_rank))
        responses.append({
            "type": resp_type, "op": op, "dtype": dtype,
            "prescale": prescale, "postscale": postscale,
            "error": error, "entries": entries,
        })
    return batch_id, shutdown, responses
