"""Async-op handles.

The reference exposes integer handles managed by a poll/wait map
(``horovod/torch/handle_manager.{h,cc}``).  Core operations here return
:class:`Handle` objects; the torch binding wraps them in integers for drop-in
API fidelity.
"""

import json
import threading


class HvdError(RuntimeError):
    """Raised when a collective fails (reference: Response::ERROR path)."""


class HvdAbortedError(HvdError):
    """Raised on EVERY rank when the collective runtime performs a
    coordinated abort — a rank crashed, went silent past the liveness
    window, hit an unrecoverable transport error, or the stall inspector
    promoted a stalled tensor into a shutdown.  Symmetric by design: all
    survivors raise this one typed error (naming the origin rank) within
    ``HVD_TPU_ABORT_TIMEOUT`` instead of hanging or failing each with a
    different exception and leaked ring state."""

    def __init__(self, origin_rank, reason):
        super().__init__(
            f"collective runtime aborted (origin rank {origin_rank}): "
            f"{reason}")
        self.origin_rank = origin_rank
        self.reason = reason


class HvdReconfigureError(HvdAbortedError):
    """An abort carrying an elastic membership directive (the coordinator
    decided the job can survive the failure).  Subclasses
    :class:`HvdAbortedError` so every existing ``except HvdAbortedError``
    site — and the non-elastic contract — is untouched; ``hvd.elastic.run``
    catches this subtype, reconfigures, and retries the step instead of
    letting the job die."""

    def __init__(self, origin_rank, reason, *, epoch, members, dead,
                 cause="", drain=False):
        super().__init__(origin_rank, reason)
        self.epoch = epoch          # new membership epoch to move to
        self.members = list(members)  # stable worker ids, new-rank order
        self.dead = list(dead)      # worker ids removed this epoch
        self.cause = cause          # the original (pre-rewrite) reason
        self.drain = drain          # planned departure, not a failure


class HvdDrainedError(HvdError):
    """Raised on the DRAINING rank only, after it helped the survivors
    reconfigure past it: this worker received a preemption notice
    (SIGTERM), announced departure, and left at a collective boundary.
    Deliberately NOT a subclass of :class:`HvdAbortedError` — a drain is
    a success path, and the zero-``HvdAbortedError`` guarantee of the
    drain protocol (docs/checkpoint.md) would be meaningless if the
    drained rank itself raised one.  ``hvd.elastic.run`` catches it and
    returns; bare workers can treat it as "stop training, exit 0"."""

    def __init__(self, worker_id):
        super().__init__(
            f"worker {worker_id} drained after preemption notice")
        self.worker_id = worker_id


# Elastic reconfiguration directives ride the existing abort fan-out
# (peer pushes, heartbeat replies, negotiation responses) as a marked
# reason string, so no wire message gains a new field for delivery.
RECONFIG_MARKER = "__hvd_elastic_reconfig__:"


def encode_reconfig_reason(epoch, members, dead, cause, drain=False):
    """Serialize a membership directive into an abort ``reason``.

    ``drain=True`` marks a PLANNED departure: delivery skips the rank-0
    peer fan-out (the directive reaches every rank at its next
    collective / heartbeat anyway) and the departing worker leaves with
    :class:`HvdDrainedError` instead of an abort."""
    payload = {"epoch": epoch, "members": list(members),
               "dead": list(dead), "cause": str(cause)}
    if drain:
        payload["drain"] = True
    return RECONFIG_MARKER + json.dumps(payload)


def is_drain_reason(reason) -> bool:
    """True when ``reason`` is a drain-marked membership directive."""
    if not (isinstance(reason, str)
            and reason.startswith(RECONFIG_MARKER)):
        return False
    try:
        return bool(json.loads(
            reason[len(RECONFIG_MARKER):]).get("drain"))
    except (ValueError, AttributeError):
        return False


def make_abort_error(origin_rank, reason):
    """Build the right typed error for a learned ``(origin, reason)``
    abort: a plain :class:`HvdAbortedError`, or the
    :class:`HvdReconfigureError` subtype when the reason carries an
    elastic membership directive."""
    if isinstance(reason, str) and reason.startswith(RECONFIG_MARKER):
        try:
            d = json.loads(reason[len(RECONFIG_MARKER):])
            return HvdReconfigureError(
                origin_rank, reason, epoch=d["epoch"],
                members=d["members"], dead=d.get("dead", ()),
                cause=d.get("cause", ""),
                drain=bool(d.get("drain", False)))
        except (ValueError, KeyError, TypeError):
            pass  # malformed directive degrades to a plain abort
    return HvdAbortedError(origin_rank, reason)


class Handle:
    """Completion handle for one rank's view of one collective."""

    __slots__ = ("_event", "_result", "_error", "name")

    def __init__(self, name=""):
        self._event = threading.Event()
        self._result = None
        self._error = None
        self.name = name

    def set_result(self, result):
        # first completion wins: an abort broadcast and the op's own
        # failure path may both reach the same handle
        if self._event.is_set():
            return
        self._result = result
        self._event.set()

    def set_error(self, message):
        if self._event.is_set():
            return
        self._error = (message if isinstance(message, HvdError)
                       else HvdError(message))
        self._event.set()

    def poll(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"collective '{self.name}' did not complete within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class HandleManager:
    """Integer-handle indirection used by the torch binding.

    Mirrors ``horovod/torch/handle_manager.cc:47`` (AllocateHandle /
    MarkDone via the underlying Handle / PollHandle / WaitForCompletion).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._handles = {}

    def allocate(self, handle: Handle) -> int:
        with self._lock:
            idx = self._next
            self._next += 1
            self._handles[idx] = handle
        return idx

    def get(self, idx: int) -> Handle:
        with self._lock:
            if idx not in self._handles:
                raise ValueError(f"unknown handle {idx}")
            return self._handles[idx]

    def poll(self, idx: int) -> bool:
        return self.get(idx).poll()

    def wait(self, idx: int, timeout=None):
        handle = self.get(idx)
        try:
            result = handle.wait(timeout)
        except TimeoutError:
            raise  # handle stays registered: the collective may still
            # complete, and a retry must be able to collect the result
        except Exception:
            with self._lock:  # terminal (HvdError): drop the entry
                self._handles.pop(idx, None)
            raise
        with self._lock:
            self._handles.pop(idx, None)
        return result
