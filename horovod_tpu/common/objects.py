"""Pickled-object collectives over the eager byte plane (reference:
``horovod/torch/__init__.py:608`` broadcast_object — pickle to a byte
tensor, broadcast the length then the payload)."""

import pickle

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.ops import eager


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object from ``root_rank``.

    Two eager broadcasts: an int64 length, then the uint8 payload —
    every rank must call this collectively (same contract as the
    reference's torch/TF flavors, which this single implementation
    backs)."""
    name = name or "bcast_object"
    if basics.rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        length = np.array([payload.size], dtype=np.int64)
    else:
        payload = None
        length = np.zeros((1,), dtype=np.int64)
    length = np.asarray(eager.synchronize(eager.broadcast_async(
        length, root_rank, name=f"{name}.len")))
    if payload is None:
        payload = np.zeros((int(length[0]),), dtype=np.uint8)
    out = np.asarray(eager.synchronize(eager.broadcast_async(
        payload, root_rank, name=f"{name}.data")))
    return pickle.loads(out.tobytes())
