"""Pickled-object collectives over the eager byte plane (reference:
``horovod/torch/__init__.py:608`` broadcast_object — pickle to a byte
tensor, broadcast the length then the payload)."""

import pickle

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.ops import eager


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object from ``root_rank``.

    Two eager broadcasts: the payload length as two int32 halves
    (int64 would narrow under jax_enable_x64=False), then the uint8
    payload —
    every rank must call this collectively (same contract as the
    reference's torch/TF flavors, which this single implementation
    backs)."""
    name = name or "bcast_object"
    # The length rides the eager plane, where jax_enable_x64=False
    # silently narrows int64 to int32 — a >= 2 GiB payload would wrap.
    # Split it into two non-negative int32 halves instead (31 bits each,
    # 62-bit range), which survive any narrowing.
    if basics.rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        length = np.array([payload.size & 0x7FFFFFFF,
                           payload.size >> 31], dtype=np.int32)
    else:
        payload = None
        length = np.zeros((2,), dtype=np.int32)
    length = np.asarray(eager.synchronize(eager.broadcast_async(
        length, root_rank, name=f"{name}.len")))
    if payload is None:
        size = (int(length[1]) << 31) | int(length[0])
        payload = np.zeros((size,), dtype=np.uint8)
    out = np.asarray(eager.synchronize(eager.broadcast_async(
        payload, root_rank, name=f"{name}.data")))
    # wire-safe: the bytes traveled through the collective plane, whose
    # frames are HMAC-verified before ANY deserialization — an
    # unauthenticated peer cannot place data here
    return pickle.loads(out.tobytes())
