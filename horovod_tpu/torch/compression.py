"""Gradient compression for the torch binding (reference:
``horovod/torch/compression.py:45``) — bf16-first on TPU."""

import torch


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class BF16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point and tensor.dtype != torch.bfloat16:
            return tensor.to(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point and tensor.dtype != torch.float16:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class Compression:
    none = NoneCompressor
    bf16 = BF16Compressor
    fp16 = FP16Compressor
