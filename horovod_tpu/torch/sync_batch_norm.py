"""Synchronized BatchNorm over all ranks (reference:
``horovod/torch/sync_batch_norm.py:35`` — allgather of per-rank
mean/var/count in forward, allreduced gradient statistics in backward).
"""

import torch
import torch.nn.functional as F
from torch.autograd.function import Function

from horovod_tpu.common import basics
from horovod_tpu.torch import mpi_ops


class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
    """Applies synchronized batch normalization: statistics are computed over
    the global batch across every rank."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def forward(self, input):
        self._check_input_dim(input)
        if not self.training or basics.size() == 1:
            return F.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, self.training, self.momentum, self.eps)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, self.momentum)


class _SyncBatchNormFn(Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum):
        reduce_dims = [0] + list(range(2, input.dim()))
        count = torch.tensor(
            [input.numel() // input.size(1)], dtype=torch.float32)
        mean = input.mean(dim=reduce_dims)
        # biased variance for normalization
        var = input.var(dim=reduce_dims, unbiased=False)

        # gather [count, mean..., var...] from every rank in one op
        packed = torch.cat([count, mean, var]).unsqueeze(0)
        gathered = mpi_ops.allgather(packed, name="sync_batch_norm.stats")
        counts = gathered[:, 0]
        means = gathered[:, 1:1 + mean.numel()]
        vars_ = gathered[:, 1 + mean.numel():]

        total = counts.sum()
        global_mean = (means * counts.unsqueeze(1)).sum(0) / total
        # law of total variance
        global_var = ((vars_ + (means - global_mean) ** 2)
                      * counts.unsqueeze(1)).sum(0) / total
        invstd = torch.rsqrt(global_var + eps)

        if running_mean is not None:
            unbiased = global_var * (total / (total - 1))
            running_mean.mul_(1 - momentum).add_(global_mean * momentum)
            running_var.mul_(1 - momentum).add_(unbiased * momentum)

        shape = [1, -1] + [1] * (input.dim() - 2)
        xhat = (input - global_mean.reshape(shape)) * invstd.reshape(shape)
        out = xhat * weight.reshape(shape) + bias.reshape(shape)

        ctx.save_for_backward(input, weight, global_mean, invstd, total)
        return out

    @staticmethod
    def backward(ctx, grad_output):
        input, weight, global_mean, invstd, total = ctx.saved_tensors
        reduce_dims = [0] + list(range(2, input.dim()))
        shape = [1, -1] + [1] * (input.dim() - 2)

        xmu = input - global_mean.reshape(shape)
        sum_dy = grad_output.sum(dim=reduce_dims)
        sum_dy_xmu = (grad_output * xmu).sum(dim=reduce_dims)

        # per-channel global sums across ranks
        packed = torch.cat([sum_dy, sum_dy_xmu]).unsqueeze(0)
        reduced = mpi_ops.allreduce(packed, op=mpi_ops.Sum,
                                    name="sync_batch_norm.grad_stats")[0]
        g_sum_dy = reduced[:sum_dy.numel()]
        g_sum_dy_xmu = reduced[sum_dy.numel():]

        w_invstd = (weight * invstd).reshape(shape)
        grad_input = w_invstd * (
            grad_output - (g_sum_dy.reshape(shape)
                           + xmu * (invstd ** 2).reshape(shape)
                           * g_sum_dy_xmu.reshape(shape)) / total)

        grad_weight = sum_dy_xmu * invstd
        grad_bias = sum_dy
        return grad_input, grad_weight, grad_bias, None, None, None, None
