"""Synchronized BatchNorm over all ranks (reference:
``horovod/torch/sync_batch_norm.py:35`` — allgather of per-rank
mean/var/count in forward, allreduced gradient statistics in backward).
"""

import torch
import torch.nn.functional as F
from torch.autograd.function import Function

from horovod_tpu.common import basics
from horovod_tpu.torch import mpi_ops


class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
    """Applies synchronized batch normalization: statistics are computed over
    the global batch across every rank."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def forward(self, input):
        self._check_input_dim(input)
        # exponential_average_factor semantics as in _BatchNorm:
        # momentum=None means a cumulative moving average driven by
        # num_batches_tracked — on EVERY training path, so single-rank
        # and distributed runs of the same module behave identically
        eaf = 0.0 if self.momentum is None else self.momentum
        if self.training and self.track_running_stats \
                and self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)
            if self.momentum is None:
                eaf = 1.0 / float(self.num_batches_tracked)
        if not self.training or basics.size() == 1:
            return F.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, self.training, eaf, self.eps)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, eaf)


class _SyncBatchNormFn(Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum):
        reduce_dims = [0] + list(range(2, input.dim()))
        # statistics in float32 regardless of activation dtype
        inp32 = input.float()
        count = torch.tensor(
            [input.numel() // input.size(1)], dtype=torch.float32)
        mean = inp32.mean(dim=reduce_dims)
        # biased variance for normalization
        var = inp32.var(dim=reduce_dims, unbiased=False)

        # gather [count, mean..., var...] from every rank in one op
        packed = torch.cat([count, mean, var]).unsqueeze(0)
        gathered = mpi_ops.allgather(packed, name="sync_batch_norm.stats")
        counts = gathered[:, 0]
        means = gathered[:, 1:1 + mean.numel()]
        vars_ = gathered[:, 1 + mean.numel():]

        total = counts.sum()
        global_mean = (means * counts.unsqueeze(1)).sum(0) / total
        # law of total variance
        global_var = ((vars_ + (means - global_mean) ** 2)
                      * counts.unsqueeze(1)).sum(0) / total
        invstd = torch.rsqrt(global_var + eps)

        if running_mean is not None:
            unbiased = global_var * (total / (total - 1))
            running_mean.mul_(1 - momentum).add_(global_mean * momentum)
            running_var.mul_(1 - momentum).add_(unbiased * momentum)

        shape = [1, -1] + [1] * (input.dim() - 2)
        xhat = (inp32 - global_mean.reshape(shape)) * invstd.reshape(shape)
        if weight is not None:
            out = xhat * weight.float().reshape(shape) \
                + bias.float().reshape(shape)
        else:  # affine=False
            out = xhat

        ctx.save_for_backward(input, weight, global_mean, invstd, total)
        # activations keep the input dtype (bf16 stays bf16 distributed
        # and single-rank alike); stats stayed fp32 above
        return out.to(input.dtype)

    @staticmethod
    def backward(ctx, grad_output):
        input, weight, global_mean, invstd, total = ctx.saved_tensors
        reduce_dims = [0] + list(range(2, input.dim()))
        shape = [1, -1] + [1] * (input.dim() - 2)

        grad32 = grad_output.float()
        xmu = input.float() - global_mean.reshape(shape)
        sum_dy = grad32.sum(dim=reduce_dims)
        sum_dy_xmu = (grad32 * xmu).sum(dim=reduce_dims)

        # per-channel global sums across ranks
        packed = torch.cat([sum_dy, sum_dy_xmu]).unsqueeze(0)
        reduced = mpi_ops.allreduce(packed, op=mpi_ops.Sum,
                                    name="sync_batch_norm.grad_stats")[0]
        g_sum_dy = reduced[:sum_dy.numel()]
        g_sum_dy_xmu = reduced[sum_dy.numel():]

        scale = (weight.float() * invstd if weight is not None
                 else invstd).reshape(shape)
        grad_input = scale * (
            grad32 - (g_sum_dy.reshape(shape)
                      + xmu * (invstd ** 2).reshape(shape)
                      * g_sum_dy_xmu.reshape(shape)) / total)
        grad_input = grad_input.to(grad_output.dtype)

        if weight is not None:
            grad_weight = (sum_dy_xmu * invstd).to(weight.dtype)
            grad_bias = sum_dy.to(weight.dtype)
        else:
            grad_weight = None
            grad_bias = None
        return grad_input, grad_weight, grad_bias, None, None, None, None
