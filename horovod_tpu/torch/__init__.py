"""PyTorch binding: drop-in ``hvd.*`` surface for torch users.

Mirrors the reference torch API (``horovod/torch/__init__.py``,
``horovod/torch/mpi_ops.py``): sync/async/in-place collective variants with
integer handles, ``DistributedOptimizer`` with per-parameter gradient hooks,
``broadcast_parameters`` / ``broadcast_optimizer_state``, ``join``,
compression and ``SyncBatchNorm``.  Tensors bridge torch<->JAX via numpy
(zero-copy on the torch CPU side); the collectives execute on the XLA data
plane like every other binding.
"""

from horovod_tpu.common.basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mpi_built,
    gloo_built,
    nccl_built,
    xla_built,
    mpi_enabled,
    gloo_enabled,
    xla_enabled,
    ccl_built,
    ddl_built,
    mpi_threads_supported,
    is_homogeneous,
)
from horovod_tpu.common.ops_enum import Average, Sum, Adasum  # noqa: F401
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    allreduce,
    allreduce_async,
    allreduce_,
    allreduce_async_,
    allgather,
    allgather_async,
    broadcast,
    broadcast_async,
    broadcast_,
    broadcast_async_,
    alltoall,
    alltoall_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_allreduce_,
    grouped_allreduce_async_,
    synchronize,
    poll,
    join,
)
from horovod_tpu.torch.optimizer import (  # noqa: F401
    DistributedOptimizer,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_object,
)
from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm  # noqa: F401
