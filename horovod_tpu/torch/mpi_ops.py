"""Torch collective ops with integer handles.

Mirrors ``horovod/torch/mpi_ops.py``: every op has sync / async / in-place
variants; async ops return integer handles resolved by ``synchronize`` /
``poll`` through a HandleManager (reference: torch/handle_manager.cc).
torch<->XLA staging goes through numpy; torch CPU tensors share memory with
their numpy views, so the copies are torch-side only where semantically
required (in-place variants).
"""

import time

import numpy as np
import torch

import jax
import jax.numpy as jnp

from horovod_tpu.common.handles import HandleManager
from horovod_tpu.common.ops_enum import Adasum, Average, ReduceOp, Sum  # noqa: F401 — re-exported
from horovod_tpu.ops import eager

_handle_manager = HandleManager()

# torch bool/bfloat16 need explicit numpy bridging
_TORCH_NUMPY_FIXUPS = {
    torch.bfloat16: torch.float32,
}


_WARNED_NARROW = set()

# dtypes staged ZERO-COPY via DLPack (reference: the C++ adapters enqueue
# framework tensors without copies, torch/adapter_v2.h:42).  64-bit dtypes
# stay on the numpy path so the narrow-to-32-bit conversion is explicit;
# bf16/bool keep their bridges.
_DLPACK_DTYPES = frozenset({
    torch.float32, torch.float16, torch.int32, torch.int16, torch.int8,
    torch.uint8,
})


def _to_jax(tensor: torch.Tensor):
    src = tensor.detach()
    fixup = _TORCH_NUMPY_FIXUPS.get(src.dtype)
    if fixup is not None:
        arr = jnp.asarray(src.to(fixup).numpy()).astype(
            str(src.dtype).replace("torch.", ""))
    elif src.dtype in _DLPACK_DTYPES:
        # zero-copy on the common-dtype path: the jax array aliases the
        # torch storage (CPU->CPU DLPack import).  Same contract as the
        # reference's adapters: do not mutate the tensor before
        # synchronize — the data plane reads it when the cycle runs.
        try:
            arr = jax.dlpack.from_dlpack(src.contiguous())
        except Exception:  # noqa: BLE001 — backend without dlpack import
            arr = jnp.asarray(src.contiguous().numpy())
    else:
        if src.dtype in (torch.int64, torch.float64) \
                and not jax.config.jax_enable_x64 \
                and src.dtype not in _WARNED_NARROW:
            _WARNED_NARROW.add(src.dtype)
            from horovod_tpu.utils.logging import get_logger
            get_logger().warning(
                "%s tensors narrow to 32-bit on the XLA device plane "
                "(jax_enable_x64 off); values beyond 32-bit range lose "
                "precision. Process mode (hvdrun) keeps 64-bit exact.",
                src.dtype)
        arr = jnp.asarray(src.contiguous().numpy())
    return arr


def _to_eager(tensor: torch.Tensor):
    """Torch tensor -> whatever the active data plane wants: numpy in
    tcp mode (keeps 64-bit dtypes EXACT on the numpy wire; converting
    through jax first would narrow them), jax arrays otherwise."""
    from horovod_tpu.common import basics

    state = basics._get_state()
    if state.config.controller == "tcp":
        src = tensor.detach()
        if src.dtype in _TORCH_NUMPY_FIXUPS:  # bf16: numpy can't hold it
            return _to_jax(tensor)
        return src.contiguous().numpy()
    return _to_jax(tensor)


def _to_torch(arr, like: torch.Tensor = None):
    np_arr = np.asarray(arr)
    if np_arr.dtype.name == "bfloat16":
        out = torch.from_numpy(
            np.array(arr.astype(jnp.float32), copy=True)).to(torch.bfloat16)
    else:
        # copy: jax exposes read-only buffers; torch tensors must be writable
        out = torch.from_numpy(np.array(np_arr, copy=True))
    if like is not None and out.dtype != like.dtype:
        out = out.to(like.dtype)
    return out


class _TorchHandle:
    __slots__ = ("core_handle", "finalize")

    def __init__(self, core_handle, finalize):
        self.core_handle = core_handle
        self.finalize = finalize

    def poll(self):
        return self.core_handle.poll()

    def wait(self, timeout=None):
        result = self.core_handle.wait(timeout)
        return self.finalize(result)


def _register(core_handle, finalize) -> int:
    return _handle_manager.allocate(_TorchHandle(core_handle, finalize))


class _GroupHandle:
    """Handle protocol over a grouped submission's member int handles
    (reference contract: ONE handle per group; ``synchronize`` on it
    returns the list of results).  ``wait`` drains EVERY member before
    re-raising the first error, so a partial failure cannot leak the
    surviving members' HandleManager entries; it lives in the normal
    ``_handle_manager`` id space, whose pop-on-terminal-error then
    cleans up the group entry itself."""

    def __init__(self, members):
        self._members = list(members)
        self._done = {}   # member index -> result (survives a timeout)
        self.name = "grouped"

    def poll(self) -> bool:
        return all(i in self._done or _handle_manager.poll(h)
                   for i, h in enumerate(self._members))

    def wait(self, timeout=None):
        # The timeout is a deadline over the WHOLE group, not a per-member
        # allowance — otherwise a group of n members could block for up
        # to n * timeout.  An expired deadline still calls each remaining
        # member with wait(0): already-completed members drain for free,
        # only a genuinely pending one raises.
        deadline = None if timeout is None else time.monotonic() + timeout
        results = []
        first_error = None
        for i, h in enumerate(self._members):
            memo = self._done.get(i)
            if memo is not None:
                # resolved on a previous (timed-out) wait: its manager
                # entry is already popped — replay the memoized outcome
                # (result OR terminal error) so a retry stays correct
                kind, val = memo
                if kind == "err":
                    if first_error is None:
                        first_error = val
                    results.append(None)
                else:
                    results.append(val)
                continue
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                result = _handle_manager.wait(h, remaining)
            except TimeoutError:
                # re-raise the TIMEOUT even when a member already failed
                # terminally: a terminal raise here would make the
                # manager pop the group entry while a member is still
                # pending (stranding its handle forever).  The pending
                # member stays registered; resolved members — results
                # AND terminal errors — are memoized, so a retry drains
                # the rest and then surfaces the real error.
                raise
            except Exception as exc:  # noqa: BLE001 — drain, then raise
                if first_error is None:
                    first_error = exc
                self._done[i] = ("err", exc)
                results.append(None)
                continue
            self._done[i] = ("ok", result)
            results.append(result)
        if first_error is not None:
            raise first_error
        return results


def synchronize(handle: int):
    """Block until the async op completes and return the torch result —
    a list of results for a group handle (reference:
    mpi_ops.synchronize)."""
    return _handle_manager.wait(handle)


def poll(handle: int) -> bool:
    return _handle_manager.poll(handle)


def join() -> int:
    return eager.join()


# -------------------------------------------------------------- allreduce ---
def _allreduce_async_impl(tensor, name, op, prescale_factor,
                          postscale_factor, compression, output_tensor):
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    core_handle = eager.allreduce_async(
        _to_eager(compressed), name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor)

    def finalize(result):
        out = compression.decompress(_to_torch(result, like=tensor), ctx)
        if output_tensor is not None:
            output_tensor.copy_(out.reshape(output_tensor.shape))
            return output_tensor
        return out

    return _register(core_handle, finalize)


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    compression=None) -> int:
    op = eager._resolve_op(op, average)
    return _allreduce_async_impl(tensor, name, op, prescale_factor,
                                 postscale_factor, compression, None)


def allreduce(tensor, average=None, name=None, compression=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(allreduce_async(
        tensor, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        compression=compression))


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0) -> int:
    """In-place variant: the result is copied back into ``tensor``."""
    op = eager._resolve_op(op, average)
    return _allreduce_async_impl(tensor, name, op, prescale_factor,
                                 postscale_factor, None, tensor)


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(allreduce_async_(
        tensor, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor))


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor=1.0,
                            postscale_factor=1.0) -> int:
    """ONE group handle for the burst (reference contract:
    ``torch/mpi_ops.py grouped_allreduce_async`` — ``synchronize`` on
    it returns the list of reduced tensors); the per-tensor
    submissions share a base name so the controller fuses compatible
    runs."""
    op = eager._resolve_op(op, average)
    base = name or eager._auto_name("torch_grouped")
    return _handle_manager.allocate(_GroupHandle([
        _allreduce_async_impl(t, f"{base}.{i}", op, prescale_factor,
                              postscale_factor, None, None)
        for i, t in enumerate(tensors)]))


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor))


def grouped_allreduce_async_(tensors, average=None, name=None, op=None,
                             prescale_factor=1.0,
                             postscale_factor=1.0) -> int:
    """In-place grouped variant: results copy back into ``tensors``."""
    op = eager._resolve_op(op, average)
    base = name or eager._auto_name("torch_grouped")
    return _handle_manager.allocate(_GroupHandle([
        _allreduce_async_impl(t, f"{base}.{i}", op, prescale_factor,
                              postscale_factor, None, t)
        for i, t in enumerate(tensors)]))


def grouped_allreduce_(tensors, average=None, name=None, op=None,
                       prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(grouped_allreduce_async_(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor))


# -------------------------------------------------------------- allgather ---
def allgather_async(tensor, name=None) -> int:
    core_handle = eager.allgather_async(_to_eager(tensor), name=name)
    return _register(core_handle,
                     lambda result: _to_torch(result, like=tensor))


def allgather(tensor, name=None):
    return synchronize(allgather_async(tensor, name=name))


# -------------------------------------------------------------- broadcast ---
def broadcast_async(tensor, root_rank, name=None) -> int:
    core_handle = eager.broadcast_async(_to_eager(tensor), root_rank,
                                        name=name)
    return _register(core_handle,
                     lambda result: _to_torch(result, like=tensor))


def broadcast(tensor, root_rank, name=None):
    return synchronize(broadcast_async(tensor, root_rank, name=name))


def broadcast_async_(tensor, root_rank, name=None) -> int:
    core_handle = eager.broadcast_async(_to_eager(tensor), root_rank,
                                        name=name)

    def finalize(result):
        tensor.copy_(_to_torch(result, like=tensor).reshape(tensor.shape))
        return tensor

    return _register(core_handle, finalize)


def broadcast_(tensor, root_rank, name=None):
    return synchronize(broadcast_async_(tensor, root_rank, name=name))


# --------------------------------------------------------------- alltoall ---
def alltoall_async(tensor, splits=None, name=None) -> int:
    splits_was_tensor = splits is not None and torch.is_tensor(splits)
    if splits_was_tensor:
        splits = splits.tolist()
    core_handle = eager.alltoall_async(_to_eager(tensor), splits=splits,
                                       name=name)

    def finalize(result):
        out, recv_splits = result
        out = _to_torch(out, like=tensor)
        if splits_was_tensor:
            # reference parity: tensor splits in -> received splits out,
            # so variable-split callers can partition by source rank
            return out, torch.tensor(recv_splits, dtype=torch.int32)
        return out

    return _register(core_handle, finalize)


def alltoall(tensor, splits=None, name=None):
    return synchronize(alltoall_async(tensor, splits=splits, name=name))
