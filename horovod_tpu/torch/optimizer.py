"""DistributedOptimizer and parameter/state broadcast for torch.

Mirrors ``horovod/torch/__init__.py``: the wrapper dynamically subclasses the
user's optimizer class, registers per-parameter gradient hooks that launch
asynchronous allreduces as gradients become ready (overlapping communication
with the rest of backward), and ``step`` synchronizes before applying
updates.  ``backward_passes_per_step`` delays the allreduce for local
gradient accumulation.  The Adasum variant reduces post-step parameter
deltas instead of gradients (reference: ``_DistributedAdasumOptimizer``,
torch/__init__.py:225).
"""

import contextlib

import torch

from horovod_tpu.common.ops_enum import Adasum, Average, ReduceOp
from horovod_tpu.torch import mpi_ops
from horovod_tpu.torch.compression import Compression


class _DistributedOptimizerMixin:
    def _hvd_init(self, named_parameters, compression,
                  backward_passes_per_step, op, prescale_factor,
                  postscale_factor):
        self._compression = compression
        self._op = op
        self._backward_passes_per_step = backward_passes_per_step
        self._prescale_factor = prescale_factor
        self._postscale_factor = postscale_factor
        self._handles = {}
        self._grad_accs = []
        self._requires_update = []
        self._should_synchronize = True

        if named_parameters is not None:
            named_parameters = list(named_parameters)
            names = [k for k, _ in named_parameters]
            dups = {n for n in names if names.count(n) > 1}
            if dups:
                # duplicate names would silently pair the wrong tensors
                # across ranks (reference: torch/__init__.py:84-90)
                raise ValueError(
                    f"named_parameters contains duplicate names: "
                    f"{sorted(dups)}")
            self._parameter_names = {v: k for k, v in named_parameters}
        else:
            # one global index: per-group enumeration would collide
            # names across groups and pair unrelated gradients
            self._parameter_names = {
                v: f"allreduce.noname.{i}"
                for i, v in enumerate(
                    p for group in self.param_groups
                    for p in group["params"])
            }
        self._allreduce_delay = {}
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._requires_update.append(p)
                    self._allreduce_delay[p] = backward_passes_per_step
                    p.register_post_accumulate_grad_hook(self._make_hook())

    def _make_hook(self):
        def hook(p):
            if p not in self._allreduce_delay:
                return
            if self._allreduce_delay[p] <= 0:
                # reference: torch/__init__.py asserts here — silently
                # continuing would overwrite the accumulated gradient
                # with a stale allreduced one at step()
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before step() or "
                    "synchronize(); increase backward_passes_per_step "
                    "or call synchronize() between backward passes")
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                self._handles[p] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        if name is None:
            # unique per parameter: a shared fallback would pair
            # unrelated tensors across ranks
            name = f"unnamed.{id(p)}"
        if p.grad is None:
            # a parameter whose hook never fired on this rank still
            # participates with zeros — ranks where it DID fire would
            # hang otherwise — and the averaged gradient must land in
            # p.grad so the optimizer applies the SAME update everywhere
            p.grad = torch.zeros_like(p)
        return mpi_ops._allreduce_async_impl(
            p.grad, f"allreduce.{name}", self._op, self._prescale_factor,
            self._postscale_factor, self._compression, p.grad)

    def synchronize(self):
        """Wait for all outstanding gradient allreduces (reference:
        torch/__init__.py:165).  Parameters whose hooks did not fire on
        this rank (data-dependent branches, frozen-at-runtime paths)
        are submitted NOW with their current (or zero) gradient — every
        rank must contribute to every negotiated tensor or the ranks
        where the hook did fire would hang (reference: the missing_p
        loop in synchronize)."""
        for p in self._requires_update:
            if p not in self._handles:
                # reference missing_p loop: no delay condition — every
                # rank must contribute to every negotiated tensor, even
                # mid-accumulation (calling synchronize mid-window is
                # the caller's choice; skipping would hang other ranks)
                self._handles[p] = self._allreduce_grad_async(p)
        for p, handle in self._handles.items():
            mpi_ops.synchronize(handle)
            self._allreduce_delay[p] = self._backward_passes_per_step
        self._handles.clear()

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Use after an explicit ``synchronize()`` (e.g. to clip the
        averaged gradients) so ``step()`` doesn't wait a second time
        (reference: torch/__init__.py:185-202)::

            optimizer.synchronize()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            with optimizer.skip_synchronize():
                optimizer.step()
        """
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            self.synchronize()
        # skip past the mixin in the MRO to the wrapped optimizer's step
        return super(_DistributedOptimizerMixin, self).step(closure)


class _DistributedAdasumOptimizerMixin(_DistributedOptimizerMixin):
    """Adasum optimizer: apply the local update, then Adasum-reduce the
    parameter DELTAS so the combined step is scale-invariant."""

    def _hvd_init(self, *args, **kwargs):
        super()._hvd_init(*args, **kwargs)
        # gradients are NOT reduced; deltas are
        self._allreduce_delay = {}

    def _make_hook(self):
        def hook(p):
            pass
        return hook

    def step(self, closure=None):
        starting = {
            p: p.detach().clone()
            for group in self.param_groups for p in group["params"]
            if p.grad is not None
        }
        loss = super(_DistributedOptimizerMixin, self).step(closure)
        handles = []
        for i, (p, start) in enumerate(starting.items()):
            delta = p.detach() - start
            name = self._parameter_names.get(p, f"delta.{i}")
            handles.append((p, start,
                            mpi_ops.allreduce_async(
                                delta, name=f"adasum.{name}", op=Adasum)))
        for p, start, handle in handles:
            reduced = mpi_ops.synchronize(handle)
            with torch.no_grad():
                p.copy_(start + reduced.reshape(p.shape))
        return loss

    def synchronize(self):
        pass


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average,
                         prescale_factor=1.0, postscale_factor=1.0):
    """Wrap a torch optimizer so gradient exchange is transparent
    (reference: horovod/torch/__init__.py:433 DistributedOptimizer)."""
    op = ReduceOp(op)
    mixin = (_DistributedAdasumOptimizerMixin if op == Adasum
             else _DistributedOptimizerMixin)
    cls = type(optimizer.__class__.__name__, (mixin, optimizer.__class__),
               {})
    optimizer.__class__ = cls
    optimizer._hvd_init(named_parameters, compression,
                        backward_passes_per_step, op, prescale_factor,
                        postscale_factor)
    return optimizer


def broadcast_parameters(params, root_rank=0):
    """Broadcast parameters from root to all ranks, in place (reference:
    torch/__init__.py:452).  Accepts a ``state_dict()`` or an iterable of
    ``(name, tensor)``."""
    if isinstance(params, dict):
        params = sorted(params.items())
    handles = []
    for name, p in params:
        if p is None or not torch.is_tensor(p):
            continue
        handles.append(mpi_ops.broadcast_async_(p, root_rank,
                                                name=f"broadcast.{name}"))
    for handle in handles:
        mpi_ops.synchronize(handle)


def _broadcast_scalar(scalar, root_rank, name):
    """Type- and value-preserving scalar broadcast.  The scalar rides as
    its 8 raw little-endian bytes in a uint8 tensor: the XLA bridge
    downcasts int64/float64 (jax_enable_x64 is off), so any 64-bit wide
    representation would silently truncate step counters > 2**31 or lose
    float64 precision — bytes survive exactly."""
    import struct

    if isinstance(scalar, bool):
        fmt, conv = "<q", lambda v: bool(v)
        payload = struct.pack(fmt, int(scalar))
    elif isinstance(scalar, int):
        fmt, conv = "<q", int
        payload = struct.pack(fmt, scalar)
    else:
        fmt, conv = "<d", float
        payload = struct.pack(fmt, float(scalar))
    wrapped = torch.tensor(list(payload), dtype=torch.uint8)
    out = mpi_ops.broadcast(wrapped, root_rank, name=name)
    return conv(struct.unpack(fmt, bytes(out.tolist()))[0])


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast optimizer state from root (reference:
    torch/__init__.py:484).  Tensor state entries broadcast directly;
    scalar entries (step counters, lr, ...) ride type-preserving 0-d
    broadcasts.

    Ranks with EMPTY state (torch creates it lazily on the first step)
    materialize it with a zero-gradient step first — otherwise a root
    resuming from a checkpoint would submit broadcasts fresh workers
    never answer, hanging the job (reference: the dummy-step dance at
    torch/__init__.py:490-516)."""
    if not optimizer.state_dict().get("state"):
        saved_grads, backups = [], []
        for group in optimizer.param_groups:
            for p in group["params"]:
                saved_grads.append((p, p.grad))
                backups.append((p, p.detach().clone()))
                p.grad = torch.zeros_like(p)
        if isinstance(optimizer, _DistributedOptimizerMixin):
            # the RAW step: only ranks with empty state run this dummy,
            # so the wrapped step's synchronize() would hang waiting for
            # ranks that skipped it (reference calls super().step() too)
            super(_DistributedOptimizerMixin, optimizer).step()
        else:
            optimizer.step()
        with torch.no_grad():
            for p, backup in backups:
                p.copy_(backup)  # undo weight-decay drift etc.
        for p, grad in saved_grads:
            p.grad = grad

    state_dict = optimizer.state_dict()

    scalars = {}
    handles = []
    for pid, state in state_dict.get("state", {}).items():
        for key, value in state.items():
            name = f"opt_state.{pid}.{key}"
            if torch.is_tensor(value) and value.ndim > 0:
                handles.append(
                    mpi_ops.broadcast_async_(value, root_rank, name=name))
            else:
                scalar = value.item() if torch.is_tensor(value) else value
                restored = _broadcast_scalar(scalar, root_rank, name)
                scalars[(pid, key)] = (value, restored)

    for handle in handles:
        mpi_ops.synchronize(handle)

    for (pid, key), (orig, restored) in scalars.items():
        if torch.is_tensor(orig):
            state_dict["state"][pid][key] = torch.tensor(
                restored, dtype=orig.dtype)
        else:
            state_dict["state"][pid][key] = restored

    for gi, group in enumerate(state_dict.get("param_groups", [])):
        for key, value in group.items():
            if isinstance(value, (int, float)):
                group[key] = _broadcast_scalar(
                    value, root_rank, name=f"opt_group.{gi}.{key}")

    optimizer.load_state_dict(state_dict)


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object from ``root_rank``
    (reference: ``torch/__init__.py:608``)."""
    from horovod_tpu.common.objects import broadcast_object as _bo

    return _bo(obj, root_rank=root_rank, name=name or "torch_bcast_object")
