"""MXNet binding (reference: ``horovod/mxnet/__init__.py`` +
``mpi_ops.py``): DistributedOptimizer (allreduce inside ``update``),
gluon DistributedTrainer (``__init__.py:87``), ``broadcast_parameters``
(``:120``), and the eager collective surface — routed through the same
controller + data plane as the torch/TF bindings instead of
``MXEnginePushAsync`` C shims (``mxnet/mpi_ops.cc:135``).

Per-symbol import guard: imports cleanly without MXNet (which is EOL
upstream and absent from this image; the binding activates when MXNet
is installed).  Executed end-to-end by ``tests/test_mxnet.py`` against
``tests/_mxnet_shim`` — a stand-in reproducing exactly the
NDArray/optimizer/gluon surface this module touches.
"""

try:
    import mxnet as _mx
    _MX_ERROR = None
except ImportError as _exc:  # pragma: no cover — mxnet absent in image
    _mx = None
    _MX_ERROR = _exc

import numpy as _np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common.ops_enum import (  # noqa: F401
    Adasum, Average, Sum)
from horovod_tpu.ops import eager as _eager

init = _basics.init
shutdown = _basics.shutdown
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
mpi_built = _basics.mpi_built
gloo_built = _basics.gloo_built
nccl_built = _basics.nccl_built
ccl_built = _basics.ccl_built
ddl_built = _basics.ddl_built
mpi_threads_supported = _basics.mpi_threads_supported
is_homogeneous = _basics.is_homogeneous


def _require_mx():
    if _mx is None:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.mxnet requires MXNet, which is not installed in "
            "this environment. Use horovod_tpu.torch or the JAX-native "
            "API instead.") from _MX_ERROR


def _to_mx(result, like):
    arr = _mx.nd.array(_np.asarray(result), dtype=like.dtype)
    return arr.as_in_context(like.context)


# --------------------------------------------------------------- collectives
def allreduce(tensor, average=True, name=None, prescale_factor=1.0,
              postscale_factor=1.0):
    _require_mx()
    out = _eager.allreduce(tensor.asnumpy(), average=average, name=name,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
    return _to_mx(out, tensor)


def allreduce_(tensor, average=True, name=None, priority=0):
    """In-place allreduce (reference: ``mpi_ops.py`` allreduce_);
    priority accepted for API parity (the controller orders by
    negotiation, not engine priority)."""
    _require_mx()
    del priority
    out = _eager.allreduce(tensor.asnumpy(), average=average, name=name)
    # assign the numpy result directly: a throwaway NDArray (plus a
    # device copy under real MXNet) would double the hot-path copies
    tensor[:] = _np.asarray(out)
    return tensor


def allgather(tensor, name=None):
    _require_mx()
    return _to_mx(_eager.allgather(tensor.asnumpy(), name=name), tensor)


def broadcast(tensor, root_rank, name=None):
    _require_mx()
    return _to_mx(
        _eager.broadcast(tensor.asnumpy(), root_rank, name=name), tensor)


def broadcast_(tensor, root_rank, name=None):
    _require_mx()
    out = _eager.broadcast(tensor.asnumpy(), root_rank, name=name)
    tensor[:] = _np.asarray(out)
    return tensor


def alltoall(tensor, splits=None, name=None):
    _require_mx()
    return _to_mx(
        _eager.alltoall(tensor.asnumpy(), splits=splits, name=name),
        tensor)


# ----------------------------------------------------------------- optimizer
def DistributedOptimizer(optimizer):
    """Wrap an ``mx.optimizer.Optimizer``: gradients are summed across
    ranks inside ``update``/``update_multi_precision`` and
    ``rescale_grad`` is divided by size, which is equivalent to — and
    cheaper than — averaging in the allreduce (reference:
    ``mxnet/__init__.py:40-85``)."""
    _require_mx()
    if getattr(optimizer, "_hvd_wrapped", False):
        # double wrapping would allreduce twice AND divide rescale_grad
        # twice — hard error, not silent wrong step sizes
        raise ValueError(
            "optimizer is already a DistributedOptimizer; wrapping "
            "twice would double-allreduce gradients")

    class _Distributed(_mx.optimizer.Optimizer):
        _hvd_wrapped = True

        def __init__(self, opt):
            self._optimizer = opt
            self._optimizer.rescale_grad /= size()

        def __getattr__(self, item):
            return getattr(self.__dict__["_optimizer"], item)

        def create_state_multi_precision(self, index, weight):
            return self._optimizer.create_state_multi_precision(
                index, weight)

        def _do_allreduce(self, index, grad):
            if size() == 1:
                return
            if isinstance(index, (tuple, list)):
                for i, idx in enumerate(index):
                    allreduce_(grad[i], average=False, name=str(idx),
                               priority=-i)
            else:
                allreduce_(grad, average=False, name=str(index))

        def update(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            self._optimizer.update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            self._optimizer.update_multi_precision(index, weight, grad,
                                                   state)

        def set_learning_rate(self, lr):
            self._optimizer.set_learning_rate(lr)

        def set_lr_mult(self, args_lr_mult):
            self._optimizer.set_lr_mult(args_lr_mult)

        def set_wd_mult(self, args_wd_mult):
            self._optimizer.set_wd_mult(args_wd_mult)

    return _Distributed(optimizer)


if _mx is not None:
    class DistributedTrainer(_mx.gluon.Trainer):
        """Gluon trainer whose ``_allreduce_grads`` exchanges gradients
        (reference: ``mxnet/__init__.py:87``); the scale trick matches
        the reference: gradients are summed, the update rescales by
        1/size."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     **kwargs):
            if getattr(optimizer, "_hvd_wrapped", False):
                # double-wrapping would sum gradients twice AND apply the
                # 1/size rescale twice — hard error, not silent corruption
                raise ValueError(
                    "DistributedTrainer wraps a plain optimizer; do not "
                    "pass a DistributedOptimizer")
            # kvstore=None is REQUIRED (reference: mxnet/__init__.py:87
            # passes it explicitly): gluon's default 'device' kvstore
            # would route updates through a store _allreduce_grads never
            # feeds, silently applying stale gradients
            kwargs.setdefault("kvstore", None)
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params, **kwargs)
            self._scale /= size()

        def _allreduce_grads(self):
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for grad in param.list_grad():
                        allreduce_(grad, average=False,
                                   name=str(i), priority=-i)
else:  # pragma: no cover
    def DistributedTrainer(*_args, **_kwargs):
        _require_mx()


def broadcast_parameters(params, root_rank=0):
    """Broadcast a gluon ``ParameterDict`` / param dict from root
    (reference: ``mxnet/__init__.py:120``)."""
    _require_mx()
    if hasattr(params, "items"):
        tensors = []
        names = []
        for name, param in sorted(params.items()):
            if isinstance(param, _mx.nd.NDArray):
                tensors.append(param)   # plain name -> NDArray dict
                names.append(name)
                continue
            try:
                tensors.append(param.data())
                names.append(name)
            except _mx.gluon.parameter.DeferredInitializationError:
                # shape-deferred parameter: hook its initialization so
                # the broadcast happens the moment data exists
                # (reference: mxnet/__init__.py:120 wraps _init_impl —
                # silently skipping would leave each rank its own
                # random init after the first forward)
                _hook_deferred_broadcast(param, name, root_rank)
    else:
        raise ValueError(f"invalid params of type {type(params)}")
    for name, tensor in zip(names, tensors):
        broadcast_(tensor, root_rank, name=f"param.{name}")


def _hook_deferred_broadcast(param, name, root_rank):
    """Wrap ``param._init_impl`` so a deferred parameter broadcasts
    right after gluon initializes it (reference: the post-init
    broadcast wrapper in ``mxnet/__init__.py:120``)."""
    original = param._init_impl

    def wrapped(*args, **kwargs):
        result = original(*args, **kwargs)
        param._init_impl = original   # fire once
        broadcast_(param.data(), root_rank, name=f"param.{name}")
        return result

    param._init_impl = wrapped
