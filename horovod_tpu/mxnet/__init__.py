"""MXNet binding gate (reference: ``horovod/mxnet/__init__.py``).

MXNet is not present in this image (and is EOL upstream); the binding
surface (DistributedOptimizer update-hook, DistributedTrainer,
broadcast_parameters) is covered by the torch and JAX bindings.
"""

try:
    import mxnet  # noqa: F401
except ImportError as exc:  # pragma: no cover
    raise ImportError(
        "horovod_tpu.mxnet requires MXNet, which is not installed in this "
        "environment. Use horovod_tpu.torch or the JAX-native API instead."
    ) from exc
