"""ResNet v1.5 family (flax), the framework's CNN benchmark vehicle.

The reference benchmarks ResNet-50/101 through tf_cnn_benchmarks and its
synthetic benchmark examples (``examples/pytorch_synthetic_benchmark.py``,
``docs/benchmarks.rst:31-43``).  This implementation is TPU-first: NHWC
layout (XLA's preferred conv layout on TPU), bfloat16 compute with fp32
parameters and batch statistics, and shapes that tile cleanly onto the MXU.
"""

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck block (ResNet-50/101/152), v1.5 variant:
    stride lives on the 3x3 conv."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, block_count in enumerate(self.stage_sizes):
            for block in range(block_count):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** stage,
                    strides=strides, conv=conv, norm=norm, act=self.act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckBlock)
