"""GPT-style decoder-only transformer — the long-context flagship model.

The reference ships only example CNNs (SURVEY §6); this model family is
what exercises the framework's TPU-first parallel subsystems together:

- **dp**: batch sharding + gradient psum (``DistributedOptimizer``)
- **tp**: weight shardings from
  :func:`horovod_tpu.parallel.tensor_parallel.transformer_sharding_rules`
  (module/param names here are chosen to match those rules)
- **sp**: attention is pluggable — dense, ring
  (:func:`~horovod_tpu.parallel.ring_attention.ring_attention`) or Ulysses
- **ep**: optional switch-MoE FFN layers
  (:func:`~horovod_tpu.parallel.moe.switch_moe`)
- **pp**: :class:`Block` is shape-preserving, so the block stack drops into
  ``horovod_tpu.parallel.pipeline.pipeline_apply`` unchanged

bfloat16 activations by default (MXU-native), fp32 layernorm/softmax.
"""

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.parallel.ring_attention import reference_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    n_layers: int = 4
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    # attn_fn(q, k, v, causal=..., scale=...) — swap in ring/ulysses/pallas
    attn_fn: Optional[Callable] = None
    # every k-th block uses a switch-MoE FFN (0 = dense only)
    moe_every: int = 0
    n_experts: int = 8
    # rematerialize each block's activations in backward (jax.checkpoint):
    # trades ~1/3 more FLOPs for O(layers) less activation HBM — the
    # lever for pushing per-chip batch (and usually MFU) once
    # activations, not weights, bound the batch size
    remat: bool = False


def default_attention():
    """The hot-path kernel: Pallas flash attention on TPU (O(T) memory,
    MXU-tiled blocks — ``ops/pallas/flash_attention.py``); the dense
    reference path elsewhere (interpret-mode Pallas on CPU is far slower
    than XLA's fused softmax for test-sized problems)."""
    import jax

    if jax.default_backend() == "tpu":
        from horovod_tpu.ops.pallas.flash_attention import flash_attention
        return flash_attention
    return reference_attention


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h, d = cfg.n_heads, cfg.d_model // cfg.n_heads
        qkv = nn.DenseGeneral((3, h, d), use_bias=False, dtype=cfg.dtype,
                              name="qkv")(x)
        q, k, v = (qkv[..., i, :, :] for i in range(3))
        attn = cfg.attn_fn or default_attention()
        o = attn(q, k, v, causal=True)
        o = o.reshape(o.shape[:-2] + (h * d,))
        return nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                        name="out")(o)


class Mlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype,
                     name="up")(x)
        x = nn.gelu(x)
        return nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                        name="down")(x)


class MoeMlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from horovod_tpu.parallel.moe import (
            moe_kernel_init, moe_param_shapes, switch_moe)

        cfg = self.cfg
        shapes = moe_param_shapes(cfg.d_model, cfg.d_ff, cfg.n_experts)
        params = {name: {"kernel": self.param(
            f"{name}_kernel", moe_kernel_init, shape)}
            for name, shape in shapes.items()}
        out, aux = switch_moe(x, params)
        self.sow("intermediates", "moe_aux_loss", aux)
        return out


class FusedLayerNorm(nn.Module):
    """LayerNorm through the fused Pallas kernel on TPU
    (``ops/pallas/layer_norm.py``: one HBM pass per direction); the
    XLA reference path elsewhere.  Parameter names/shapes match
    ``nn.LayerNorm`` so checkpoints and the TP sharding rules are
    unaffected."""
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        import jax as _jax

        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (d,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (d,),
                          jnp.float32)
        if _jax.default_backend() == "tpu":
            from horovod_tpu.ops.pallas.layer_norm import layer_norm
            return layer_norm(x, scale, bias, self.eps)
        from horovod_tpu.ops.pallas.layer_norm import layer_norm_reference
        return layer_norm_reference(x, scale, bias, self.eps)


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        y = FusedLayerNorm(name="ln1")(x)
        x = x + Attention(cfg, name="attn")(y.astype(cfg.dtype))
        y = FusedLayerNorm(name="ln2")(x)
        ff = MoeMlp(cfg, name="moe") if self.use_moe else \
            Mlp(cfg, name="mlp")
        return x + ff(y.astype(cfg.dtype))


def lm_loss(logits, tokens):
    """Mean next-token cross-entropy — the LM training loss.

    On TPU this is the fused Pallas kernel
    (``ops/pallas/softmax_xent.py``: vocab streamed in VMEM chunks, no
    materialized ``[rows, vocab]`` log-softmax); the XLA/optax lowering
    elsewhere."""
    import jax as _jax

    labels = jnp.roll(tokens, -1, axis=-1)
    if _jax.default_backend() == "tpu":
        from horovod_tpu.ops.pallas.softmax_xent import softmax_xent
        return jnp.mean(softmax_xent(logits, labels))
    from horovod_tpu.ops.pallas.softmax_xent import softmax_xent_reference
    return jnp.mean(softmax_xent_reference(logits, labels))


def apply_with_aux(model, params, tokens):
    """Forward pass returning ``(logits, moe_aux_loss)``.

    MoE blocks ``sow`` their load-balancing losses into the
    ``intermediates`` collection, which plain ``model.apply`` drops;
    training code for MoE configs must use this helper (or pass
    ``mutable=["intermediates"]`` itself) and add the aux term to the
    loss, or the router receives no balancing gradient.
    """
    import jax as _jax

    logits, state = model.apply({"params": params}, tokens,
                                mutable=["intermediates"])
    leaves = [
        leaf for path, leaf in _jax.tree_util.tree_flatten_with_path(
            state.get("intermediates", {}))[0]
        if any("moe_aux_loss" in str(getattr(k, "key", k)) for k in path)
    ]
    aux = sum(leaves) if leaves else jnp.zeros((), jnp.float32)
    return logits, aux


class Transformer(nn.Module):
    """Token ids ``[B, T]`` -> logits ``[B, T, vocab]`` (causal LM)."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     name="embed")(tokens)
        pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=cfg.dtype,
                       name="pos_embed")(jnp.arange(tokens.shape[-1]))
        x = x + pos
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.n_layers):
            use_moe = cfg.moe_every and (i + 1) % cfg.moe_every == 0
            x = block_cls(cfg, use_moe=bool(use_moe),
                          name=f"block_{i}")(x)
        x = FusedLayerNorm(name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        name="lm_head")(x.astype(cfg.dtype))
