"""VGG family (flax) — the reference's hardest-to-scale benchmark model
(68% scaling efficiency at 512 GPUs vs 90% for ResNet, ``README.rst:79``:
VGG-16's huge dense layers stress gradient allreduce bandwidth).

TPU-first: NHWC, bfloat16 compute / fp32 params, and the classifier
expressed as matmuls that tile onto the MXU.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Configuration "D" (VGG-16) / "E" (VGG-19): numbers are conv widths,
# "M" is 2x2 max-pool.
_CFG_16 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M")
_CFG_19 = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
           512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


class VGG(nn.Module):
    cfg: Sequence
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    classifier_width: int = 4096
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for _ in range(2):
            x = nn.Dense(self.classifier_width, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


VGG16 = partial(VGG, cfg=_CFG_16)
VGG19 = partial(VGG, cfg=_CFG_19)
