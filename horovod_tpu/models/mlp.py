"""Small MLP used by tests and the MNIST-style examples (the reference's
``examples/pytorch_mnist.py`` analog)."""

from typing import Sequence

import flax.linen as nn


class MLP(nn.Module):
    features: Sequence[int] = (128, 64, 10)

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x
