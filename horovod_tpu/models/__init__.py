from horovod_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.vgg import VGG, VGG16, VGG19  # noqa: F401
from horovod_tpu.models.inception import InceptionV3  # noqa: F401
from horovod_tpu.models.mlp import MLP  # noqa: F401
from horovod_tpu.models.transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
    apply_with_aux,
    lm_loss,
)
