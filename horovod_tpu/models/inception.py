"""Inception V3 (flax) — the reference's headline scaling benchmark (90%
efficiency at 512 GPUs, ``README.rst:79``, ``docs/benchmarks.rst:13``).

Standard Szegedy et al. 2015 topology (mixed 35/17/8 blocks with factorized
convolutions), TPU-first: NHWC, bfloat16 compute / fp32 params+stats, every
conv BN'd (no biases).  Input 299x299x3.
"""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


class MixedA(nn.Module):  # 35x35 blocks
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b1 = conv(64, (1, 1))(x, train)
        b5 = conv(48, (1, 1))(x, train)
        b5 = conv(64, (5, 5))(b5, train)
        b3 = conv(64, (1, 1))(x, train)
        b3 = conv(96, (3, 3))(b3, train)
        b3 = conv(96, (3, 3))(b3, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = conv(self.pool_features, (1, 1))(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):  # 35 -> 17
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b3 = conv(384, (3, 3), (2, 2), padding="VALID")(x, train)
        bd = conv(64, (1, 1))(x, train)
        bd = conv(96, (3, 3))(bd, train)
        bd = conv(96, (3, 3), (2, 2), padding="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class MixedB(nn.Module):  # 17x17 blocks, factorized 7x7
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        c = self.channels_7x7
        b1 = conv(192, (1, 1))(x, train)
        b7 = conv(c, (1, 1))(x, train)
        b7 = conv(c, (1, 7))(b7, train)
        b7 = conv(192, (7, 1))(b7, train)
        bd = conv(c, (1, 1))(x, train)
        bd = conv(c, (7, 1))(bd, train)
        bd = conv(c, (1, 7))(bd, train)
        bd = conv(c, (7, 1))(bd, train)
        bd = conv(192, (1, 7))(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = conv(192, (1, 1))(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):  # 17 -> 8
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b3 = conv(192, (1, 1))(x, train)
        b3 = conv(320, (3, 3), (2, 2), padding="VALID")(b3, train)
        b7 = conv(192, (1, 1))(x, train)
        b7 = conv(192, (1, 7))(b7, train)
        b7 = conv(192, (7, 1))(b7, train)
        b7 = conv(192, (3, 3), (2, 2), padding="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class MixedC(nn.Module):  # 8x8 blocks, expanded filter bank
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b1 = conv(320, (1, 1))(x, train)
        b3 = conv(384, (1, 1))(x, train)
        b3a = conv(384, (1, 3))(b3, train)
        b3b = conv(384, (3, 1))(b3, train)
        bd = conv(448, (1, 1))(x, train)
        bd = conv(384, (3, 3))(bd, train)
        bda = conv(384, (1, 3))(bd, train)
        bdb = conv(384, (3, 1))(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = conv(192, (1, 1))(bp, train)
        return jnp.concatenate([b1, b3a, b3b, bda, bdb, bp], axis=-1)


class InceptionV3(nn.Module):
    """Canonical Inception V3 topology WITHOUT the auxiliary classifier
    head — matching tf_cnn_benchmarks (the reference's benchmark
    vehicle, ``docs/benchmarks.rst``), which also omits AuxLogits;
    torchvision's aux_logits=True training configuration has ~1-2%
    more FLOPs."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem: 299 -> 35
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = conv(32, (3, 3), padding="VALID")(x, train)
        x = conv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = conv(80, (1, 1), padding="VALID")(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 35x35
        x = MixedA(32, dtype=self.dtype)(x, train)
        x = MixedA(64, dtype=self.dtype)(x, train)
        x = MixedA(64, dtype=self.dtype)(x, train)
        x = ReductionA(dtype=self.dtype)(x, train)
        # 17x17
        x = MixedB(128, dtype=self.dtype)(x, train)
        x = MixedB(160, dtype=self.dtype)(x, train)
        x = MixedB(160, dtype=self.dtype)(x, train)
        x = MixedB(192, dtype=self.dtype)(x, train)
        x = ReductionB(dtype=self.dtype)(x, train)
        # 8x8
        x = MixedC(dtype=self.dtype)(x, train)
        x = MixedC(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
