"""horovod_tpu — a TPU-native distributed deep-learning training framework.

Provides the capabilities of Horovod v0.19.2 (reference: /root/reference,
``horovod/__init__.py``) re-designed TPU-first:

- process/topology model: ``init()``, ``rank()``, ``size()``, ``local_rank()``,
  ``local_size()``, ``cross_rank()``, ``cross_size()`` (reference:
  ``horovod/common/basics.py:22``)
- named asynchronous collectives with tensor fusion, response cache, timeline,
  stall inspection and Join semantics (reference: ``horovod/common/operations.cc``)
- the data plane is JAX/XLA collectives (``psum`` / ``all_gather`` /
  ``ppermute``) compiled over a :class:`jax.sharding.Mesh` — ICI within a
  slice, DCN across slices — instead of MPI/NCCL/Gloo.

The top-level module exposes the JAX-native binding.  Framework bindings live
in ``horovod_tpu.torch``, ``horovod_tpu.tensorflow`` (gated),
``horovod_tpu.keras`` (gated) and ``horovod_tpu.mxnet`` (gated).
"""

__version__ = "0.1.0"

# hvd-race (docs/race_detection.md): the shim must patch the threading
# primitives BEFORE the runtime modules below import and build their
# locks, so this gate runs first.  With HVD_TPU_RACE unset the shim
# module is never imported and threading stays stock — the gate's cost
# is one env read.
from horovod_tpu.utils import env as _env_util

if _env_util.get_bool(_env_util.HVD_TPU_RACE):
    from horovod_tpu.tools.race import shim as _race_shim

    _race_shim.install_from_env()

from horovod_tpu.common.basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    abort,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mesh,
    local_device,
    nccl_built,
    mpi_built,
    gloo_built,
    xla_built,
    mpi_enabled,
    gloo_enabled,
    xla_enabled,
    ccl_built,
    ddl_built,
    mpi_threads_supported,
    is_homogeneous,
)
from horovod_tpu.common.handles import (  # noqa: F401
    HvdAbortedError,
    HvdDrainedError,
    HvdError,
    HvdReconfigureError,
)
from horovod_tpu import checkpoint  # noqa: F401
from horovod_tpu import elastic  # noqa: F401
from horovod_tpu.common.ops_enum import Average, Sum, Adasum  # noqa: F401
from horovod_tpu.ops.eager import (  # noqa: F401
    allreduce,
    allreduce_async,
    allgather,
    allgather_async,
    barrier,
    broadcast,
    broadcast_async,
    alltoall,
    alltoall_async,
    grouped_allreduce,
    grouped_allgather,
    reduce_scatter,
    reduce_scatter_async,
    synchronize,
    poll,
    join,
)
from horovod_tpu.groups import (  # noqa: F401
    Grid,
    GroupUnsatisfiableError,
    ProcessGroup,
    grid,
    new_group,
)
from horovod_tpu.common.objects import broadcast_object  # noqa: F401
from horovod_tpu.jax_api import (  # noqa: F401
    DistributedOptimizer,
    ShardedDistributedOptimizer,
    broadcast_parameters,
    broadcast_optimizer_state,
    allreduce_gradients,
    shard_chunk_size,
    sharded_state_wrap,
    sharded_state_unwrap,
)
from horovod_tpu.sharding import (  # noqa: F401
    ZeroDistributedOptimizer,
    gather_zero_state,
    reshard_zero_state,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
