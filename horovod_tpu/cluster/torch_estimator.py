"""Torch estimator (reference: ``horovod/spark/torch/estimator.py:449``
TorchEstimator — same fit contract as the Keras flavor, for torch
modules: per-rank remote trainer with DistributedOptimizer, checkpoint to
store, metric averaging)."""

import numpy as np

from horovod_tpu.cluster.backend import InProcessBackend
from horovod_tpu.cluster.store import LocalStore


def _as_torch(xb, yb):
    import torch

    def writable(a):
        a = np.asarray(a)
        # torch rejects non-writable views (Arrow buffers can be
        # read-only); copy only then
        return a if a.flags.writeable else a.copy()

    x = torch.as_tensor(writable(xb), dtype=torch.float32)
    y = torch.as_tensor(writable(yb))
    if y.dtype == torch.float64:
        y = y.float()
    return x, y


def _train_one_rank(rank, model_factory, loss_name, store, epochs,
                    batch_size, learning_rate, num_ranks, has_val=False,
                    streaming=False):
    import torch

    import horovod_tpu.torch as hvd
    from horovod_tpu.cluster.store import load_rank_shard

    model = model_factory()
    loss_fn = getattr(torch.nn.functional, loss_name)
    if streaming:
        from horovod_tpu.utils.data import lockstep_shard_batches

        batches = lockstep_shard_batches(store, rank, num_ranks,
                                         batch_size, epochs)
    else:
        from horovod_tpu.utils.data import BatchIterator

        shard = load_rank_shard(store, rank, num_ranks)
        batches = BatchIterator(shard, min(batch_size, len(shard["x"])),
                                epochs=epochs)

    optimizer = torch.optim.SGD(model.parameters(), lr=learning_rate,
                                momentum=0.9)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    loss = torch.zeros(())
    for batch in batches:
        xb, yb = _as_torch(batch["x"], batch["y"])
        optimizer.zero_grad()
        loss = loss_fn(model(xb), yb)
        loss.backward()
        optimizer.step()

    import jax.numpy as jnp

    import horovod_tpu as hvd_core

    avg_loss = float(np.asarray(hvd_core.allreduce(
        jnp.asarray([float(loss.detach())]), op=hvd_core.Average,
        name="torch_estimator.metric.loss"))[0])

    if rank == 0:
        import os

        os.makedirs(store.checkpoint_path(), exist_ok=True)
        torch.save(model.state_dict(),
                   os.path.join(store.checkpoint_path(), "model.pt"))
    if has_val:
        vs = load_rank_shard(store, rank, num_ranks, split="val")
        vx, vy = _as_torch(vs["x"], vs["y"])
        with torch.no_grad():
            local = float(loss_fn(model(vx), vy))
        rows = float(len(vx))
        # row-weighted: val shards can be uneven (np.array_split)
        total = np.asarray(hvd_core.allreduce(
            jnp.asarray([local * rows, rows]), op=hvd_core.Sum,
            name="torch_estimator.metric.val_loss"))
        return {"loss": avg_loss, "val_loss": float(total[0] / total[1])}
    return avg_loss


class TorchModel:
    def __init__(self, model, loss_fn):
        self.model = model
        self._loss_fn = loss_fn

    def predict(self, x):
        import torch

        with torch.no_grad():
            return self.model(torch.as_tensor(x, dtype=torch.float32))

    def evaluate(self, x, y):
        import torch

        y = torch.as_tensor(y)
        if y.dtype == torch.float64:
            y = y.float()
        with torch.no_grad():
            return float(self._loss_fn(self.predict(x), y))


class TorchEstimator:
    """Distributed trainer for a torch module over a Store + Backend.

    ``model_factory`` is a zero-arg callable building the module (modules
    cross process boundaries by re-construction + checkpoint load, the way
    the reference serializes models for remote trainers).  ``loss`` is the
    name of a ``torch.nn.functional`` loss.
    """

    def __init__(self, model_factory, loss="mse_loss", epochs=1,
                 batch_size=32, learning_rate=0.01, store=None,
                 backend=None, validation=None, streaming=False):
        self.model_factory = model_factory
        self.loss = loss
        self.epochs = epochs
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.store = store
        self.backend = backend
        self.validation = validation
        # stream row groups instead of loading shards (sharded-dataset
        # stores only; see docs/data.md)
        self.streaming = streaming

    def fit(self, x, y):
        import os
        import tempfile

        import torch

        store = self.store or LocalStore(tempfile.mkdtemp(
            prefix="hvd_tpu_torch_estimator_"))
        backend = self.backend or InProcessBackend()
        n = backend.num_processes()

        from horovod_tpu.cluster.store import (materialize_shards,
                                               split_validation)

        if self.streaming:
            from horovod_tpu.utils.data import require_sharded_store
            require_sharded_store(store)
        x_val = y_val = None
        if self.validation is not None:
            x, y, x_val, y_val = split_validation(x, y, self.validation)
        x, y = materialize_shards(store, x, y, n, x_val=x_val,
                                  y_val=y_val)

        metrics = backend.run(
            _train_one_rank,
            args=(self.model_factory, self.loss, store, self.epochs,
                  self.batch_size, self.learning_rate, n,
                  x_val is not None, self.streaming))

        model = self.model_factory()
        model.load_state_dict(torch.load(
            os.path.join(store.checkpoint_path(), "model.pt"),
            weights_only=True))
        model.eval()
        loss_fn = getattr(torch.nn.functional, self.loss)
        return TorchModel(model, loss_fn), metrics
