"""Keras estimator (reference: ``horovod/spark/keras/estimator.py:532``
KerasEstimator): fit materializes the dataset to the Store, trains one
worker per rank through the Keras binding (wrapped optimizer + broadcast
callback + metric averaging), checkpoints weights to the store from
rank 0, and returns a servable fitted model.

The model travels to the workers as (serialized config, weights) —
the same custom-serialization job the reference does for Spark task
shipping (``keras/util.py``)."""

import os

import numpy as np

from horovod_tpu.cluster.backend import InProcessBackend
from horovod_tpu.cluster.store import LocalStore


def _train_keras_rank(rank, model_config, weights, compile_kwargs,
                      store, epochs, batch_size, learning_rate,
                      num_ranks, has_val=False, streaming=False):
    """Runs in a worker process (ProcessBackend) or rank thread.
    ``num_ranks`` is the shard partition the dataset was materialized
    for (the backend's process count, NOT hvd.size()).  ``streaming``
    feeds ``model.fit`` a row-group-streaming generator with a
    lockstep ``steps_per_epoch`` (see utils.data) instead of the
    in-memory shard arrays."""
    import keras

    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.cluster.store import load_rank_shard

    model = keras.saving.deserialize_keras_object(model_config)
    if streaming:
        from horovod_tpu.utils.data import (ParquetShardIterator,
                                            lockstep_plan)

        batch_size, steps_per_epoch, _ = lockstep_plan(
            store, num_ranks, batch_size, epochs)
        stream = iter(ParquetShardIterator(store, rank, num_ranks,
                                           batch_size, epochs=None))
        # peek the first batch for the build shape (no second
        # row-group read) and hand it back through the generator
        first = next(stream)

        def gen(batch=first):
            while True:
                yield np.asarray(batch["x"]), np.asarray(batch["y"])
                batch = next(stream)

        fit_data = {"x": gen(), "steps_per_epoch": steps_per_epoch}
        build_shape = (None,) + tuple(first["x"].shape[1:])
    else:
        shard = load_rank_shard(store, rank, num_ranks)
        x, y = shard["x"], shard["y"]
        fit_data = {"x": np.asarray(x), "y": np.asarray(y),
                    "batch_size": batch_size}
        build_shape = (None,) + tuple(np.asarray(x).shape[1:])
    if not model.built:
        model.build(build_shape)
    model.set_weights(weights)

    optimizer = hvd_keras.DistributedOptimizer(
        keras.optimizers.get({
            "class_name": compile_kwargs.get("optimizer", "sgd"),
            "config": {"learning_rate": learning_rate}}))
    model.compile(optimizer=optimizer,
                  loss=compile_kwargs.get("loss", "mse"),
                  metrics=compile_kwargs.get("metrics"),
                  run_eagerly=True)

    callbacks = [
        hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd_keras.callbacks.MetricAverageCallback(),
    ]
    fit_kwargs = {}
    if has_val:
        vs = load_rank_shard(store, rank, num_ranks, split="val")
        vx, vy = np.asarray(vs["x"]), np.asarray(vs["y"])
        fit_kwargs["validation_data"] = (vx, vy)
    history = model.fit(epochs=epochs, callbacks=callbacks, verbose=0,
                        **fit_data, **fit_kwargs)

    if hvd_keras.rank() == 0:
        path = store.checkpoint_path()
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "keras_weights.npz"),
                 *model.get_weights())
    if has_val:
        import jax.numpy as jnp

        import horovod_tpu as hvd_core

        # one extra evaluate pass per fit: history's val_loss was
        # already equal-weight rank-averaged by MetricAverageCallback,
        # so the local shard value needed for row weighting is gone
        local = model.evaluate(vx, vy, batch_size=batch_size, verbose=0)
        if isinstance(local, (list, tuple)):
            local = local[0]
        rows = float(len(vx))
        # row-weighted global mean, matching the jax/torch estimators:
        # val shards can be uneven (np.array_split) and the
        # MetricAverageCallback's equal-weight rank mean would bias
        # rows in the smaller shards
        total = np.asarray(hvd_core.allreduce(
            jnp.asarray([float(local) * rows, rows]), op=hvd_core.Sum,
            name="keras_estimator.metric.val_loss"))
        return {"loss": float(history.history["loss"][-1]),
                "val_loss": float(total[0] / total[1])}
    return float(history.history["loss"][-1])


class KerasModel:
    """Servable result of ``KerasEstimator.fit`` (reference: the fitted
    Spark KerasModel with predict/evaluate)."""

    def __init__(self, model):
        self.model = model

    def predict(self, x):
        return self.model.predict(np.asarray(x), verbose=0)

    def evaluate(self, x, y):
        result = self.model.evaluate(np.asarray(x), np.asarray(y),
                                     verbose=0)
        # keras returns [loss, *metrics] when metrics are compiled
        return float(result[0] if isinstance(result, (list, tuple))
                     else result)


class KerasEstimator:
    """Distributed trainer for a Keras model over a Store + Backend
    (reference param subset: model, loss, optimizer, metrics, epochs,
    batch_size, learning_rate, store, backend)."""

    def __init__(self, model, loss="mse", optimizer="sgd", metrics=None,
                 epochs=1, batch_size=32, learning_rate=0.01, store=None,
                 backend=None, validation=None, streaming=False):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.epochs = epochs
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.store = store
        self.backend = backend
        self.validation = validation
        # stream row groups instead of loading shards (sharded-dataset
        # stores only; see docs/data.md)
        self.streaming = streaming

    def fit(self, x, y):
        import tempfile

        import keras

        from horovod_tpu.cluster.store import materialize_shards

        store = self.store or LocalStore(tempfile.mkdtemp(
            prefix="hvd_tpu_keras_estimator_"))
        backend = self.backend or InProcessBackend(num_proc=1)
        n = backend.num_processes()
        from horovod_tpu.cluster.store import split_validation

        if self.streaming:
            from horovod_tpu.utils.data import require_sharded_store
            require_sharded_store(store)
        x_val = y_val = None
        if self.validation is not None:
            x, y, x_val, y_val = split_validation(x, y, self.validation)
        x, y = materialize_shards(store, x, y, n, x_val=x_val,
                                  y_val=y_val)

        if not self.model.built:
            self.model.build((None,) + tuple(x.shape[1:]))
        model_config = keras.saving.serialize_keras_object(self.model)
        weights = self.model.get_weights()
        compile_kwargs = {"loss": self.loss, "optimizer": self.optimizer,
                          "metrics": self.metrics}

        metrics = backend.run(
            _train_keras_rank,
            args=(model_config, weights, compile_kwargs, store,
                  self.epochs, self.batch_size, self.learning_rate, n,
                  x_val is not None, self.streaming))

        trained = keras.saving.deserialize_keras_object(model_config)
        if not trained.built:
            trained.build((None,) + tuple(x.shape[1:]))
        with np.load(os.path.join(store.checkpoint_path(),
                                  "keras_weights.npz")) as data:
            trained.set_weights([data[k] for k in data.files])
        trained.compile(loss=self.loss, metrics=self.metrics)
        return KerasModel(trained), metrics
