"""Execution backends (reference: ``horovod/spark/common/backend.py:90`` —
``Backend.run(fn)`` abstracts where the per-rank training processes live:
Spark tasks there; here in-process device-rank threads or hvdrun-launched
OS processes.  A Spark/K8s backend is a subclass implementing ``run``)."""


class Backend:
    def num_processes(self):
        raise NotImplementedError

    def run(self, fn, args=(), kwargs=None):
        """Run ``fn(rank, *args, **kwargs)`` once per rank; return the list
        of per-rank results (rank order)."""
        raise NotImplementedError


class InProcessBackend(Backend):
    """Device-rank threads inside this process (the 8-device CPU-mesh test
    topology, or one TPU host's chips)."""

    def __init__(self, num_proc=None):
        self._num_proc = num_proc

    def num_processes(self):
        import horovod_tpu as hvd

        if self._num_proc is not None:
            from horovod_tpu.common import basics

            if basics._state is None:
                # restrict the rank set to num_proc devices BEFORE the
                # first init: the threaded eager path would otherwise
                # wait forever for device ranks that have no training
                # thread
                import jax

                devices = list(jax.devices())
                if self._num_proc < len(devices):
                    hvd.init(comm=devices[:self._num_proc])
                else:
                    hvd.init()
            else:
                hvd.init()  # no-op; verify compatibility below
            if hvd.size() != self._num_proc:
                raise RuntimeError(
                    f"InProcessBackend(num_proc={self._num_proc}) but "
                    f"horovod_tpu is initialized with {hvd.size()} "
                    f"ranks; shut down first or match num_proc")
            return self._num_proc
        hvd.init()
        return hvd.local_size()

    def run(self, fn, args=(), kwargs=None):
        from horovod_tpu.common import basics

        kwargs = kwargs or {}
        return basics.run_parallel(
            lambda rank: fn(rank, *args, **kwargs),
            num_ranks=self.num_processes())


class ProcessBackend(Backend):
    """One OS process per rank through the programmatic launcher
    (reference analog: ``horovod.spark.run`` driving task processes;
    here ``horovod_tpu.run.run``)."""

    def __init__(self, num_proc, hosts=None, extra_env=None,
                 jax_platform=None):
        self._num_proc = num_proc
        self._hosts = hosts
        self._extra_env = extra_env
        self._jax_platform = jax_platform

    def num_processes(self):
        return self._num_proc

    def run(self, fn, args=(), kwargs=None):
        from horovod_tpu.run import run as hvd_run

        platform = self._jax_platform

        def wrapper(*a, **kw):
            if platform is not None:
                # must happen before hvd.init() touches jax (some TPU
                # plugins ignore the JAX_PLATFORMS env var)
                import jax

                jax.config.update("jax_platforms", platform)
            import horovod_tpu as hvd

            hvd.init()
            try:
                return fn(hvd.rank(), *a, **kw)
            finally:
                hvd.shutdown()

        return hvd_run(wrapper, args=args, kwargs=kwargs or {},
                       np=self._num_proc, hosts=self._hosts,
                       extra_env=self._extra_env)
