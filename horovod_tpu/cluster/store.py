"""Data/checkpoint store (reference: ``horovod/spark/common/store.py:30,149``
— ``Store`` abstracts local FS / HDFS / S3 locations for intermediate
training data and checkpoints; ``LocalStore`` is the filesystem flavor).

Training data is materialized as one ``.npz`` shard per rank (the
reference writes Parquet via Petastorm; npz keeps this dependency-free —
swap the (de)serializers to change formats)."""

import os

import numpy as np


class Store:
    """Abstract locations + (de)serialization for one training job."""

    def train_data_path(self, rank=None):
        raise NotImplementedError

    def checkpoint_path(self):
        raise NotImplementedError

    def save_shard(self, rank, arrays, split="train"):
        raise NotImplementedError

    def load_shard(self, rank, split="train"):
        raise NotImplementedError

    def exists(self, path):
        raise NotImplementedError


class LocalStore(Store):
    """Filesystem store (reference: ``store.py`` LocalStore /
    FilesystemStore)."""

    def __init__(self, prefix_path):
        self.prefix_path = prefix_path
        os.makedirs(prefix_path, exist_ok=True)

    def train_data_path(self, rank=None):
        base = os.path.join(self.prefix_path, "intermediate_train_data")
        if rank is None:
            return base
        return os.path.join(base, f"part_{rank:05d}.npz")

    def val_data_path(self, rank=None):
        base = os.path.join(self.prefix_path, "intermediate_val_data")
        if rank is None:
            return base
        return os.path.join(base, f"part_{rank:05d}.npz")

    def checkpoint_path(self):
        return os.path.join(self.prefix_path, "checkpoints")

    def _split_base(self, split):
        return {"train": self.train_data_path,
                "val": self.val_data_path}[split]

    def save_shard(self, rank, arrays, split="train"):
        os.makedirs(self._split_base(split)(), exist_ok=True)
        path = self._split_base(split)(rank)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
        return path

    def load_shard(self, rank, split="train"):
        with np.load(self._split_base(split)(rank)) as data:
            return {k: data[k] for k in data.files}


    def exists(self, path):
        return os.path.exists(path)


def load_rank_shard(store, rank, size, split="train"):
    """Rank-side shard fetch across both store protocols: disjoint
    row-group reads on a sharded-dataset store (ParquetStore —
    ``cur_shard=rank, shard_count=size``, the reference's Petastorm
    reader contract), per-rank npz files otherwise."""
    if hasattr(store, "read_shard"):
        # trim-to-min equalizes shards for the LOCKSTEP train loop;
        # the val pass is one forward + row-weighted Sum allreduce and
        # must see every row, or val_loss diverges from full-set
        # evaluation
        return store.read_shard(cur_shard=rank, shard_count=size,
                                split=split,
                                trim_to_min=(split == "train"))
    return store.load_shard(rank, split=split)


def split_validation(x, y, validation):
    """The reference's float-validation semantics
    (``spark/common/params.py``: ``validation`` = split fraction in
    [0, 1)): hold out the TAIL fraction as the val set."""
    import numpy as np

    if not 0.0 < validation < 1.0:
        raise ValueError(
            f"validation must be a float in (0, 1), got {validation}")
    n_val = max(1, int(len(x) * validation))
    if n_val >= len(x):
        raise ValueError(
            f"validation={validation} leaves no training rows "
            f"({len(x)} total)")
    return (np.asarray(x[:-n_val]), np.asarray(y[:-n_val]),
            np.asarray(x[-n_val:]), np.asarray(y[-n_val:]))


def materialize_shards(store, x, y, num_ranks, x_val=None, y_val=None):
    """Split (x, y) into per-rank shards and persist them to the store
    (the common front half of every estimator's ``fit``; reference: the
    DataFrame->Parquet materialization in ``spark/common/store.py``).
    ``(x_val, y_val)`` materializes the validation split alongside.
    Returns the train arrays as numpy."""
    import numpy as np

    x = np.asarray(x)
    y = np.asarray(y)
    if len(x) < num_ranks:
        raise ValueError(
            f"need at least one sample per rank ({num_ranks}), "
            f"got {len(x)}")
    if x_val is not None and len(x_val) < num_ranks:
        raise ValueError(
            f"validation split has {len(x_val)} rows — fewer than one "
            f"per rank ({num_ranks}); lower num_proc or raise the "
            f"validation fraction")
    if hasattr(store, "materialize"):
        # sharded-dataset store: ONE dataset, ranks read disjoint
        # partitions — per-rank equality comes from the reader's
        # metadata-driven min-trim, not from pre-splitting.  The store
        # owns its partition-granularity policy; num_ranks is the hint.
        val = None if x_val is None else {"x": np.asarray(x_val),
                                          "y": np.asarray(y_val)}
        store.materialize({"x": x, "y": y}, validation=val,
                          num_ranks=num_ranks)
        return x, y
    if x_val is not None:
        for rank, (xs, ys) in enumerate(
                zip(np.array_split(np.asarray(x_val), num_ranks),
                    np.array_split(np.asarray(y_val), num_ranks))):
            store.save_shard(rank, {"x": xs, "y": ys}, split="val")
    # EQUAL shard lengths: uneven shards would give ranks different
    # per-epoch step counts, silently pairing gradients from different
    # optimization steps in the name-matched eager exchange and then
    # deadlocking on the unpaired remainder
    even = (len(x) // num_ranks) * num_ranks
    if even != len(x):
        from horovod_tpu.utils.logging import get_logger
        get_logger().warning(
            "dropping %d trailing sample(s) so every rank gets an "
            "equal shard (%d each)", len(x) - even, even // num_ranks)
        x, y = x[:even], y[:even]
    for rank, (xs, ys) in enumerate(
            zip(np.array_split(x, num_ranks),
                np.array_split(y, num_ranks))):
        store.save_shard(rank, {"x": xs, "y": ys})
    return x, y
