"""Data/checkpoint store (reference: ``horovod/spark/common/store.py:30,149``
— ``Store`` abstracts local FS / HDFS / S3 locations for intermediate
training data and checkpoints; ``LocalStore`` is the filesystem flavor).

Training data is materialized as one ``.npz`` shard per rank (the
reference writes Parquet via Petastorm; npz keeps this dependency-free —
swap the (de)serializers to change formats)."""

import os

import numpy as np


class Store:
    """Abstract locations + (de)serialization for one training job."""

    def train_data_path(self, rank=None):
        raise NotImplementedError

    def checkpoint_path(self):
        raise NotImplementedError

    def save_shard(self, rank, arrays):
        raise NotImplementedError

    def load_shard(self, rank):
        raise NotImplementedError

    def exists(self, path):
        raise NotImplementedError


class LocalStore(Store):
    """Filesystem store (reference: ``store.py`` LocalStore /
    FilesystemStore)."""

    def __init__(self, prefix_path):
        self.prefix_path = prefix_path
        os.makedirs(prefix_path, exist_ok=True)

    def train_data_path(self, rank=None):
        base = os.path.join(self.prefix_path, "intermediate_train_data")
        if rank is None:
            return base
        return os.path.join(base, f"part_{rank:05d}.npz")

    def checkpoint_path(self):
        return os.path.join(self.prefix_path, "checkpoints")

    def save_shard(self, rank, arrays):
        os.makedirs(self.train_data_path(), exist_ok=True)
        path = self.train_data_path(rank)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
        return path

    def load_shard(self, rank):
        with np.load(self.train_data_path(rank)) as data:
            return {k: data[k] for k in data.files}

    def exists(self, path):
        return os.path.exists(path)


def load_rank_shard(store, rank, size):
    """Rank-side shard fetch across both store protocols: disjoint
    row-group reads on a sharded-dataset store (ParquetStore —
    ``cur_shard=rank, shard_count=size``, the reference's Petastorm
    reader contract), per-rank npz files otherwise."""
    if hasattr(store, "read_shard"):
        return store.read_shard(cur_shard=rank, shard_count=size)
    return store.load_shard(rank)


def materialize_shards(store, x, y, num_ranks):
    """Split (x, y) into per-rank shards and persist them to the store
    (the common front half of every estimator's ``fit``; reference: the
    DataFrame->Parquet materialization in ``spark/common/store.py``).
    Returns the arrays as numpy."""
    import numpy as np

    x = np.asarray(x)
    y = np.asarray(y)
    if len(x) < num_ranks:
        raise ValueError(
            f"need at least one sample per rank ({num_ranks}), "
            f"got {len(x)}")
    if hasattr(store, "materialize"):
        # sharded-dataset store: ONE dataset, ranks read disjoint
        # partitions — per-rank equality comes from the reader's
        # metadata-driven min-trim, not from pre-splitting.  The store
        # owns its partition-granularity policy; num_ranks is the hint.
        store.materialize({"x": x, "y": y}, num_ranks=num_ranks)
        return x, y
    # EQUAL shard lengths: uneven shards would give ranks different
    # per-epoch step counts, silently pairing gradients from different
    # optimization steps in the name-matched eager exchange and then
    # deadlocking on the unpaired remainder
    even = (len(x) // num_ranks) * num_ranks
    if even != len(x):
        from horovod_tpu.utils.logging import get_logger
        get_logger().warning(
            "dropping %d trailing sample(s) so every rank gets an "
            "equal shard (%d each)", len(x) - even, even // num_ranks)
        x, y = x[:even], y[:even]
    for rank, (xs, ys) in enumerate(
            zip(np.array_split(x, num_ranks),
                np.array_split(y, num_ranks))):
        store.save_shard(rank, {"x": xs, "y": ys})
    return x, y
