"""JAX/flax estimator (reference: ``horovod/spark/keras/estimator.py:532``
KerasEstimator — fit materializes the dataset to the store, trains one
worker per rank via the backend with the wrapped optimizer, checkpoints to
the store, averages metrics, and returns a servable model wrapper)."""

import numpy as np

from horovod_tpu.cluster.backend import InProcessBackend
from horovod_tpu.cluster.store import LocalStore


def _default_loss(preds, y):
    import jax.numpy as jnp

    # y may be a jax tracer inside the jitted step: inspect .dtype
    # directly (np.asarray on a tracer raises at trace time)
    if y.ndim == 1 and jnp.issubdtype(y.dtype, jnp.integer):
        import optax
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(preds, y))
    return jnp.mean((preds - y) ** 2)


def _val_loss(params, model, loss_fn, store, rank, num_ranks):
    """Rank's validation loss over its val shard, averaged across ranks
    (reference: the estimators' validation pass feeding val_loss into
    the returned history)."""
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.cluster.store import load_rank_shard

    shard = load_rank_shard(store, rank, num_ranks, split="val")
    preds = model.apply(params, jnp.asarray(shard["x"]))
    local = float(loss_fn(preds, jnp.asarray(shard["y"])))
    rows = float(len(shard["x"]))
    # row-WEIGHTED global mean: val shards can be uneven
    # (np.array_split), and a mean-of-shard-means would weight rows
    # unequally and disagree with the SPMD path's full-set loss
    total = np.asarray(hvd.allreduce(
        jnp.asarray([local * rows, rows]), op=hvd.Sum,
        name="estimator.metric.val_loss"))
    return float(total[0] / total[1])


# shared with the torch estimator (and kept under the old name for
# callers): the lockstep/empty-shard logic lives in utils.data
from horovod_tpu.utils.data import min_shard_rows as _min_shard_rows  # noqa: E402


def _train_one_rank(rank, model, loss_fn, store, epochs, batch_size,
                    learning_rate, seed, num_ranks, has_val=False,
                    streaming=False):
    """Runs inside a rank context (thread or process).  ``num_ranks`` is
    the backend's process count — the shard partition the dataset was
    materialized for (NOT hvd.size(), which can exceed it in multi-host
    device-rank mode and would silently drop row groups).
    ``streaming=True`` (sharded-dataset stores) iterates the rank's row
    groups one at a time instead of loading the shard."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.cluster.store import load_rank_shard
    from horovod_tpu.utils import checkpoint as ckpt

    if streaming:
        import itertools

        from horovod_tpu.utils.data import lockstep_shard_batches

        batches = lockstep_shard_batches(store, rank, num_ranks,
                                         batch_size, epochs)
        # peek the first batch for the init sample instead of paying a
        # second row-group read — chain it back for training
        first = next(batches)
        sample = first["x"][:1]
        batches = itertools.chain([first], batches)
    else:
        from horovod_tpu.utils.data import BatchIterator

        shard = load_rank_shard(store, rank, num_ranks)
        x, y = shard["x"], shard["y"]
        sample = x[:1]
        batches = BatchIterator({"x": x, "y": y},
                                min(batch_size, len(x)), epochs=epochs)

    params = model.init(jax.random.PRNGKey(seed), jnp.asarray(sample))
    # reference workflow: rank 0's init everywhere before training
    params = hvd.broadcast_parameters(params, root_rank=0)

    opt = optax.sgd(learning_rate, momentum=0.9)
    opt_state = opt.init(params)

    @jax.jit
    def grads_fn(params, xb, yb):
        def local_loss(p):
            return loss_fn(model.apply(p, xb), yb)

        return jax.value_and_grad(local_loss)(params)

    last_loss = 0.0
    for batch in batches:
        xb = jnp.asarray(batch["x"])
        yb = jnp.asarray(batch["y"])
        loss, grads = grads_fn(params, xb, yb)
        # gradient exchange on the eager path, one fused group per step
        leaves, treedef = jax.tree.flatten(grads)
        handles = [hvd.allreduce_async(leaf, op=hvd.Average,
                                       name=f"estimator.grad.{j}")
                   for j, leaf in enumerate(leaves)]
        reduced = [hvd.synchronize(h) for h in handles]
        grads = jax.tree.unflatten(treedef, reduced)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        last_loss = loss

    # epoch metric averaged across ranks (reference: MetricAverageCallback)
    avg_loss = float(np.asarray(hvd.allreduce(
        jnp.asarray([float(last_loss)]), op=hvd.Average,
        name="estimator.metric.loss"))[0])

    if rank == 0:
        ckpt.save_checkpoint(store.checkpoint_path(), params, step=0,
                             rank=0)
    if has_val:
        return {"loss": avg_loss,
                "val_loss": _val_loss(params, model, loss_fn, store,
                                      rank, num_ranks)}
    return avg_loss


def _spmd_streamed_batches(store, num_ranks, batch_per_rank, epochs):
    """Zip one streamed iterator per shard into mesh-ordered global
    batches: shard r's rows land in mesh position r, matching the
    in-memory path's layout.  Memory bound: one row group per shard in
    flight (the reference's Petastorm readers stream the same way).

    The equal-shard trim is applied PER EPOCH, like the in-memory path:
    every epoch restarts each shard at its first row and takes exactly
    ``steps_per_epoch`` (smallest shard's batch count) global batches.
    A run-level trim (zip until the shortest iterator exhausts) would
    let epoch boundaries drift across unequal shards, pairing rows from
    different epoch phases in multi-epoch runs."""
    import itertools

    from horovod_tpu.utils.data import ParquetShardIterator

    steps_per_epoch = max(
        _min_shard_rows(store, num_ranks) // batch_per_rank, 1)
    for _ in range(epochs):
        its = [itertools.islice(
            iter(ParquetShardIterator(store, r, num_ranks,
                                      batch_per_rank, epochs=1)),
            steps_per_epoch) for r in range(num_ranks)]
        for parts in zip(*its):
            yield {k: np.concatenate([p[k] for p in parts])
                   for k in parts[0]}


def _train_spmd(model, loss_fn, store, epochs, batch_size, learning_rate,
                seed, num_ranks, has_val=False, streaming=False):
    """The SPMD fit path (single process, device-rank mode): ONE jitted
    ``shard_map`` training step over the ``hvd`` mesh — gradients psum
    inside the compiled program instead of per-leaf eager allreduces
    (VERDICT r1 weak #8: the advertised fit path must ride the SPMD
    plane).  ``streaming=True`` (sharded-dataset stores only) feeds the
    loop through ``ParquetShardIterator`` + ``prefetch_to_device``
    instead of materializing every shard in host memory."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.parallel._compat import shard_map
    from horovod_tpu.utils import checkpoint as ckpt

    from horovod_tpu.cluster.store import load_rank_shard

    mesh = hvd.mesh()
    stream_src = None
    if streaming:
        import itertools

        # row counts come from footer metadata alone — no data reads
        per = _min_shard_rows(store, num_ranks)
        stream_src = _spmd_streamed_batches(
            store, num_ranks, min(batch_size, per), epochs)
        # peek the first global batch for the init sample (no second
        # row-group read) and chain it back for training
        first = next(stream_src)
        sample = first["x"][:1]
        stream_src = itertools.chain([first], stream_src)
    else:
        shards = [load_rank_shard(store, r, num_ranks)
                  for r in range(num_ranks)]
        per = min(len(s["x"]) for s in shards)
        sample = shards[0]["x"][:1]

    params = model.init(jax.random.PRNGKey(seed), jnp.asarray(sample))
    opt = hvd.DistributedOptimizer(optax.sgd(learning_rate, momentum=0.9),
                                   named_axes=("hvd",))
    opt_state = opt.init(params)

    def per_shard(params, opt_state, xb, yb):
        def local_loss(p):
            return loss_fn(model.apply(p, xb), yb)

        loss, grads = jax.value_and_grad(local_loss)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                jax.lax.pmean(loss, "hvd"))

    step = jax.jit(shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P())))

    sharded = NamedSharding(mesh, P("hvd"))
    batch_per_rank = min(batch_size, per)
    loss = None
    if streaming:
        from horovod_tpu.utils.data import prefetch_to_device

        for batch in prefetch_to_device(stream_src, size=2,
                                        sharding=sharded):
            params, opt_state, loss = step(
                params, opt_state, batch["x"], batch["y"])
    else:
        for _ in range(epochs):
            for i in range(0, max(per - batch_per_rank + 1, 1),
                           batch_per_rank):
                xb = np.concatenate([
                    s["x"][i:i + batch_per_rank] for s in shards])
                yb = np.concatenate([
                    s["y"][i:i + batch_per_rank] for s in shards])
                params, opt_state, loss = step(
                    params, opt_state,
                    jax.device_put(jnp.asarray(xb), sharded),
                    jax.device_put(jnp.asarray(yb), sharded))
    avg_loss = float(np.asarray(jax.device_get(loss))) \
        if loss is not None else 0.0
    ckpt.save_checkpoint(store.checkpoint_path(), params, step=0, rank=0)
    if has_val:
        # single-process SPMD: evaluate the FULL val set directly
        val_shards = [load_rank_shard(store, r, num_ranks, split="val")
                      for r in range(num_ranks)]
        vx = np.concatenate([s["x"] for s in val_shards])
        vy = np.concatenate([s["y"] for s in val_shards])
        val = float(loss_fn(model.apply(params, jnp.asarray(vx)),
                            jnp.asarray(vy)))
        return [{"loss": avg_loss, "val_loss": val}] * num_ranks
    return [avg_loss] * num_ranks


class JaxModel:
    """Servable result of ``JaxEstimator.fit`` (reference analog: the
    fitted Spark Model with predict/evaluate)."""

    def __init__(self, model, params, loss_fn):
        self.model = model
        self.params = params
        self._loss_fn = loss_fn

    def predict(self, x):
        import jax.numpy as jnp

        return self.model.apply(self.params, jnp.asarray(x))

    def evaluate(self, x, y):
        import jax.numpy as jnp

        return float(self._loss_fn(self.predict(x), jnp.asarray(y)))


class JaxEstimator:
    """Distributed trainer for a flax module over a Store + Backend.

    Parameters mirror the reference's EstimatorParams subset that applies
    outside Spark (``horovod/spark/common/params.py``): model, loss,
    epochs, batch_size, learning_rate, store, backend, seed.
    """

    def __init__(self, model, loss=None, epochs=1, batch_size=32,
                 learning_rate=0.01, store=None, backend=None, seed=0,
                 validation=None, streaming=False):
        self.model = model
        self.loss = loss or _default_loss
        self.epochs = epochs
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.store = store
        self.backend = backend
        self.seed = seed
        # float in (0, 1): tail fraction held out as the val split,
        # reported as val_loss in the metrics (reference:
        # spark/common/params.py 'validation')
        self.validation = validation
        # stream row groups + device prefetch instead of loading every
        # shard in host memory (sharded-dataset stores only; the
        # reference's Petastorm readers stream the same way)
        self.streaming = streaming

    def fit(self, x, y):
        """Materialize (x, y) shards to the store, train per rank, return
        (JaxModel, per-rank metric list)."""
        import tempfile

        import jax

        store = self.store or LocalStore(tempfile.mkdtemp(
            prefix="hvd_tpu_estimator_"))
        backend = self.backend or InProcessBackend()
        n = backend.num_processes()

        from horovod_tpu.cluster.store import (materialize_shards,
                                               split_validation)

        if self.streaming:
            # check BEFORE materializing: the error depends only on the
            # store type, and materialization writes the whole dataset
            from horovod_tpu.utils.data import require_sharded_store
            require_sharded_store(store)
        x_val = y_val = None
        if self.validation is not None:
            x, y, x_val, y_val = split_validation(x, y, self.validation)
        x, y = materialize_shards(store, x, y, n, x_val=x_val,
                                  y_val=y_val)
        has_val = x_val is not None

        use_spmd = False
        if isinstance(backend, InProcessBackend):
            import horovod_tpu as hvd

            # backend.num_processes() above already initialized — with a
            # comm-restricted rank set when num_proc is below the device
            # count (see InProcessBackend)
            use_spmd = n == hvd.mesh().devices.size
        if use_spmd:
            metrics = _train_spmd(
                self.model, self.loss, store, self.epochs,
                self.batch_size, self.learning_rate, self.seed, n,
                has_val, streaming=self.streaming)
        else:
            metrics = backend.run(
                _train_one_rank,
                args=(self.model, self.loss, store, self.epochs,
                      self.batch_size, self.learning_rate, self.seed, n,
                      has_val, self.streaming))

        from horovod_tpu.utils import checkpoint as ckpt

        import jax.numpy as jnp

        template = self.model.init(jax.random.PRNGKey(self.seed),
                                   jnp.asarray(x[:1]))
        params, restored_step = ckpt.restore_checkpoint(
            store.checkpoint_path(), template)
        if restored_step is None:
            raise RuntimeError(
                f"training finished but no checkpoint was found at "
                f"{store.checkpoint_path()} — with a multi-host "
                f"ProcessBackend the store prefix must be on a shared "
                f"filesystem (rank 0 writes the checkpoint)")
        return JaxModel(self.model, params, self.loss), metrics
