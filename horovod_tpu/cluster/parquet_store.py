"""Parquet/Arrow shard store — the reference's estimator data path
(DataFrame -> Parquet intermediate store -> per-rank sharded reads),
redesigned TPU-first.

Reference: ``horovod/spark/common/store.py:30,149`` (``Store`` /
``FilesystemStore`` / ``HDFSStore`` with ``intermediate_train_data`` /
``intermediate_val_data`` Parquet directories), consumed per rank by
``horovod/spark/keras/remote.py`` via Petastorm readers with
``cur_shard=hvd.rank(), shard_count=hvd.size()``.

TPU-first redesign (NOT a Petastorm translation):

- **Row groups are the sharding unit.**  The dataset is written with many
  equal-size row groups; rank *r* of *n* owns row groups where
  ``rg % n == r``.  Shard selection is **metadata-only** — a rank reads
  the footer, picks its groups, and streams exactly those byte ranges;
  no rank ever touches another rank's rows (the reference gets the same
  property from Petastorm's ``cur_shard``/``shard_count`` row-group
  filter).
- **Static shapes end to end.**  Tensor columns (ndim >= 2) are stored as
  Arrow ``FixedSizeList`` with the trailing shape recorded in file
  metadata, so every rank rebuilds dense C-contiguous numpy arrays of
  identical static shape — these feed ``jax.device_put`` directly and
  never trigger an XLA recompile from shape drift.
- **Equal shards by construction.**  Per-shard row counts are computed
  from footer metadata alone and every shard trims to the global
  minimum, so all ranks run identical per-epoch step counts (unequal
  shards would pair gradients from different steps in the name-matched
  eager exchange, then deadlock on the remainder).
- dtypes round-trip exactly: the source numpy dtype of every column is
  recorded in metadata and restored on read (bfloat16 — which Parquet
  cannot hold — travels as float32 and is cast back on the way out).
"""

import json
import math
import os

import numpy as np

from horovod_tpu.cluster.store import Store

_TRAIN_DIR = "intermediate_train_data"
_VAL_DIR = "intermediate_val_data"
_PART = "part-00000.parquet"
_META_PREFIX = "hvd_tpu."


def _require_pyarrow():
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401
    except ImportError as exc:  # pragma: no cover - image always has it
        raise ImportError(
            "ParquetStore requires pyarrow; install it or use LocalStore "
            "(npz shards)") from exc


class ParquetStore(Store):
    """Filesystem Parquet store with per-rank disjoint row-group reads.

    ``rows_per_row_group`` fixes the sharding granularity at write time;
    the default targets ``default_row_groups`` groups (64 — divides
    evenly across 2/4/8/16/32-rank jobs) with at least one row each.
    """

    #: default number of row groups a materialized split aims for
    default_row_groups = 64

    def __init__(self, prefix_path, rows_per_row_group=None):
        _require_pyarrow()
        self.prefix_path = prefix_path
        self.rows_per_row_group = rows_per_row_group
        os.makedirs(prefix_path, exist_ok=True)

    # ------------------------------------------------------------- paths --
    def train_data_path(self, idx=None):
        d = _TRAIN_DIR if idx is None else f"{_TRAIN_DIR}.{idx}"
        return os.path.join(self.prefix_path, d)

    def val_data_path(self, idx=None):
        d = _VAL_DIR if idx is None else f"{_VAL_DIR}.{idx}"
        return os.path.join(self.prefix_path, d)

    def runs_path(self):
        return os.path.join(self.prefix_path, "runs")

    def run_path(self, run_id):
        return os.path.join(self.runs_path(), str(run_id))

    def checkpoint_path(self, run_id=None):
        if run_id is None:
            return os.path.join(self.prefix_path, "checkpoints")
        return os.path.join(self.run_path(run_id), "checkpoints")

    def logs_path(self, run_id):
        return os.path.join(self.run_path(run_id), "logs")

    def exists(self, path):
        return os.path.exists(path)

    # ------------------------------------------------- dataset inspection --
    def is_parquet_dataset(self, path):
        return os.path.isfile(os.path.join(path, _PART))

    def get_parquet_dataset(self, path):
        import pyarrow.parquet as pq

        return pq.ParquetFile(os.path.join(path, _PART))

    # --------------------------------------------------------- write path --
    def materialize(self, data, validation=None, idx=None,
                    rows_per_row_group=None, num_ranks=None):
        """Write ``data`` (a ``{column: ndarray}`` dict or a pandas
        DataFrame) as the train split — and ``validation`` likewise as
        the val split — each a Parquet dataset cut into many equal row
        groups (the reference analog: ``prepare_data`` materializing the
        DataFrame with ``df.repartition``).  Returns the train path.

        Granularity: an explicit ``rows_per_row_group`` (argument or the
        store's configured value) wins; otherwise ``num_ranks`` sizes
        groups fine enough that every rank gets several and the
        equal-shard trim stays small."""
        def granularity(split_data):
            # explicit values win — _build_table owns that precedence
            # chain (arg over store attr over default); this helper only
            # supplies the num_ranks-derived argument when nothing
            # explicit is in play
            if rows_per_row_group is not None \
                    or self.rows_per_row_group is not None \
                    or not num_ranks:
                return rows_per_row_group
            n = len(next(iter(split_data.values()))) \
                if isinstance(split_data, dict) else len(split_data)
            # per-SPLIT granularity: a small val split sharing the train
            # split's group size would yield fewer groups than ranks
            return max(1, n // max(num_ranks * 8,
                                   self.default_row_groups))

        train = self._write_split(self.train_data_path(idx), data,
                                  granularity(data))
        if validation is not None:
            self._write_split(self.val_data_path(idx), validation,
                              granularity(validation))
        return train

    def _write_split(self, path, data, rows_per_row_group=None):
        import pyarrow.parquet as pq

        table, schema, n, per_group = self._build_table(
            data, rows_per_row_group)
        os.makedirs(path, exist_ok=True)
        tmp = os.path.join(path, _PART + ".tmp")
        with pq.ParquetWriter(tmp, schema) as writer:
            for start in range(0, n, per_group):
                writer.write_table(table.slice(start, per_group))
        os.replace(tmp, os.path.join(path, _PART))
        with open(os.path.join(path, "_SUCCESS"), "w"):
            pass  # completion marker, mirrors the Spark output contract
        return path

    def _build_table(self, data, rows_per_row_group=None):
        import pyarrow as pa

        if hasattr(data, "to_dict") and hasattr(data, "columns"):
            data = {c: np.asarray(data[c]) for c in data.columns}
        if not data:
            raise ValueError("empty dataset")
        n_rows = {k: len(v) for k, v in data.items()}
        if len(set(n_rows.values())) != 1:
            raise ValueError(f"column lengths differ: {n_rows}")
        n = next(iter(n_rows.values()))
        if n == 0:
            raise ValueError("dataset has zero rows")

        fields, arrays, meta = [], [], {}
        for name, col in data.items():
            col = np.asarray(col)
            meta[f"{_META_PREFIX}dtype.{name}"] = str(col.dtype)
            meta[f"{_META_PREFIX}shape.{name}"] = json.dumps(
                list(col.shape[1:]))
            if col.dtype == np.dtype("float16") or col.dtype.name == \
                    "bfloat16":
                col = col.astype(np.float32)  # parquet-safe carrier
            if col.ndim == 1:
                arr = pa.array(col)
            else:
                flat = np.ascontiguousarray(col).reshape(len(col), -1)
                values = pa.array(flat.ravel())
                arr = pa.FixedSizeListArray.from_arrays(
                    values, flat.shape[1])
            arrays.append(arr)
            fields.append(pa.field(name, arr.type))

        schema = pa.schema(fields, metadata={
            k.encode(): str(v).encode() for k, v in meta.items()})
        table = pa.Table.from_arrays(arrays, schema=schema)

        per_group = rows_per_row_group or self.rows_per_row_group or max(
            1, math.ceil(n / self.default_row_groups))
        return table, schema, n, per_group

    # ---------------------------------------------------------- read path --
    def shard_row_counts(self, shard_count, split="train", idx=None,
                         parquet_file=None):
        """Per-shard row counts from footer metadata ALONE (no data
        reads) — every rank derives the same global minimum.  Pass an
        already-open ``parquet_file`` to reuse its footer instead of
        re-opening the dataset."""
        pf = parquet_file or self._open(split, idx)
        counts = [0] * shard_count
        for rg in range(pf.metadata.num_row_groups):
            counts[rg % shard_count] += pf.metadata.row_group(rg).num_rows
        return counts

    def read_shard(self, cur_shard, shard_count, split="train", idx=None,
                   columns=None, trim_to_min=True):
        """Read THIS rank's disjoint row groups (``rg % shard_count ==
        cur_shard``) and return ``{column: ndarray}`` with original
        dtypes/shapes restored (reference:
        ``horovod/spark/keras/remote.py`` — ``cur_shard=hvd.rank(),
        shard_count=hvd.size()``)."""
        if not 0 <= cur_shard < shard_count:
            raise ValueError(
                f"cur_shard {cur_shard} outside [0, {shard_count})")
        pf = self._open(split, idx)
        mine = [rg for rg in range(pf.metadata.num_row_groups)
                if rg % shard_count == cur_shard]
        counts = self.shard_row_counts(shard_count, split, idx,
                                       parquet_file=pf)
        min_rows = min(counts)
        if min_rows == 0:
            raise ValueError(
                f"shard {counts.index(0)} of {shard_count} would be "
                f"empty ({pf.metadata.num_row_groups} row groups, "
                f"{pf.metadata.num_rows} rows) — rewrite with smaller "
                f"rows_per_row_group or fewer ranks")
        table = pf.read_row_groups(mine, columns=columns)
        limit = min_rows if trim_to_min else table.num_rows
        if trim_to_min and table.num_rows > limit:
            from horovod_tpu.utils.logging import get_logger

            get_logger().warning(
                "shard %d/%d trims %d of %d rows to match the smallest "
                "shard (%d rows) — rewrite with smaller "
                "rows_per_row_group to reduce the loss",
                cur_shard, shard_count, table.num_rows - limit,
                table.num_rows, limit)
        return self._to_numpy(table, pf.schema_arrow.metadata, limit)

    def _open(self, split, idx):
        path = {"train": self.train_data_path,
                "val": self.val_data_path}[split](idx)
        return self.get_parquet_dataset(path)

    @staticmethod
    def _to_numpy(table, metadata, limit):
        import pyarrow as pa

        metadata = metadata or {}
        out = {}
        for name in table.column_names:
            col = table.column(name).combine_chunks()
            shape_key = f"{_META_PREFIX}shape.{name}".encode()
            dtype_key = f"{_META_PREFIX}dtype.{name}".encode()
            trailing = json.loads(metadata.get(shape_key, b"[]"))
            if isinstance(col.type, pa.FixedSizeListType):
                arr = np.asarray(col.values)
                arr = arr.reshape(len(col), *trailing) if trailing else \
                    arr.reshape(len(col), -1)
            else:
                arr = np.asarray(col)
            want = metadata.get(dtype_key)
            if want is not None:
                want = want.decode()
                if arr.dtype.name != want:
                    if want == "bfloat16":
                        import ml_dtypes

                        arr = arr.astype(ml_dtypes.bfloat16)
                    else:
                        arr = arr.astype(want)
            out[name] = np.ascontiguousarray(arr[:limit])
        return out

    # --------------------------------------- legacy shard-file protocol --
    # ParquetStore is also a drop-in Store for the npz per-rank protocol
    # so existing callers (checkpoint-only use) keep working.
    def save_shard(self, rank, arrays, split="train"):
        raise NotImplementedError(
            "ParquetStore shards by row group — use materialize() + "
            "read_shard() (per-rank npz files are the LocalStore "
            "protocol)")

    def load_shard(self, rank, split="train"):
        raise NotImplementedError(
            "ParquetStore shards by row group — use read_shard(rank, n)")


class FilesystemStore(ParquetStore):
    """ParquetStore over a ``pyarrow.fs`` URI — the HDFS/S3 analog of the
    reference's ``HDFSStore`` (``store.py:149``).  The data path runs
    through the pyarrow filesystem; ``sync_run_dir`` uploads a local run
    directory (checkpoints/logs) into the store the way the reference's
    ``sync_fn`` pushes local output to HDFS.

    With a ``file://`` URI this is exercised end-to-end in tests; hdfs://
    and s3:// work wherever the corresponding pyarrow filesystem is
    available in the runtime (none are reachable in this image).
    """

    def __init__(self, prefix_url, rows_per_row_group=None):
        from pyarrow import fs as pafs

        self._fs, prefix = pafs.FileSystem.from_uri(prefix_url)
        self.prefix_url = prefix_url
        if isinstance(self._fs, pafs.LocalFileSystem):
            super().__init__(prefix, rows_per_row_group)
        else:  # pragma: no cover - no remote fs reachable in this image
            _require_pyarrow()
            self.prefix_path = prefix
            self.rows_per_row_group = rows_per_row_group
            self._fs.create_dir(prefix, recursive=True)

    def exists(self, path):
        from pyarrow import fs as pafs

        return self._fs.get_file_info(path).type != pafs.FileType.NotFound

    def get_parquet_dataset(self, path):
        import pyarrow.parquet as pq

        return pq.ParquetFile(os.path.join(path, _PART),
                              filesystem=self._fs)

    def is_parquet_dataset(self, path):
        return self.exists(os.path.join(path, _PART))

    def _write_split(self, path, data, rows_per_row_group=None):
        from pyarrow import fs as pafs

        if isinstance(self._fs, pafs.LocalFileSystem):
            return super()._write_split(path, data, rows_per_row_group)
        # remote object stores have no atomic rename: write straight to
        # the final name, then the _SUCCESS marker
        import pyarrow.parquet as pq  # pragma: no cover - needs remote fs

        table, schema, n, per_group = self._build_table(
            data, rows_per_row_group)
        self._fs.create_dir(path, recursive=True)
        with pq.ParquetWriter(os.path.join(path, _PART), schema,
                              filesystem=self._fs) as writer:
            for start in range(0, n, per_group):
                writer.write_table(table.slice(start, per_group))
        with self._fs.open_output_stream(
                os.path.join(path, "_SUCCESS")):
            pass
        return path

    def sync_run_dir(self, local_dir, run_id):
        """Recursively copy a local run directory into the store
        (reference: ``Store.sync_fn`` — local training output pushed to
        the remote store after each epoch)."""
        from pyarrow import fs as pafs

        dest = self.run_path(run_id)
        self._fs.create_dir(dest, recursive=True)
        pafs.copy_files(local_dir, dest,
                        destination_filesystem=self._fs)
        return dest
