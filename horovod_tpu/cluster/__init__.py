"""Cluster glue: estimator framework (reference: ``horovod/spark/`` §2.5 —
Estimators that materialize a dataset to a Store, train one process per
rank through a Backend, checkpoint to the store and hand back a servable
model).  Spark itself is optional glue in the reference; the equivalent
here is backend-pluggable (in-process device ranks, hvdrun processes) with
the same Store/Params/Estimator shape, so a Spark backend is one subclass
away."""

from horovod_tpu.cluster.store import LocalStore, Store  # noqa: F401
from horovod_tpu.cluster.parquet_store import (  # noqa: F401
    FilesystemStore,
    ParquetStore,
)
from horovod_tpu.cluster.backend import (  # noqa: F401
    Backend,
    InProcessBackend,
    ProcessBackend,
)
from horovod_tpu.cluster.estimator import (  # noqa: F401
    JaxEstimator,
    JaxModel,
)
from horovod_tpu.cluster.keras_estimator import (  # noqa: F401
    KerasEstimator,
    KerasModel,
)
from horovod_tpu.cluster.torch_estimator import (  # noqa: F401
    TorchEstimator,
    TorchModel,
)
