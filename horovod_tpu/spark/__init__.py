"""Spark attachment (reference: ``horovod/spark/__init__.py``).

``horovod_tpu.spark.run(fn)`` executes a training fn inside Spark tasks
(``runner.py``; requires PySpark, per-symbol import-guarded).  The
estimator framework itself — Store, Backend, JaxEstimator,
TorchEstimator, KerasEstimator — lives Spark-free in
:mod:`horovod_tpu.cluster` (reference §2.5 capabilities); on a Spark
cluster, pair those estimators with a Backend built on :func:`run`.
"""

from horovod_tpu.spark.runner import run  # noqa: F401
from horovod_tpu.spark.backend import SparkBackend  # noqa: F401

# estimator surface re-exported for reference-parity imports
# (horovod.spark.keras.KerasEstimator etc. map here)
from horovod_tpu.cluster import (  # noqa: F401
    JaxEstimator,
    KerasEstimator,
    LocalStore,
    Store,
    TorchEstimator,
)
