"""Spark binding gate (reference: ``horovod/spark/__init__.py``).

PySpark is not part of this image; the estimator framework itself —
Store, Backend, JaxEstimator, TorchEstimator (reference §2.5 capabilities)
— lives Spark-free in :mod:`horovod_tpu.cluster`.  A Spark deployment
implements ``horovod_tpu.cluster.Backend.run`` over Spark tasks (the
reference's ``backend.py:90`` shape) and reuses everything else.
"""

try:
    import pyspark  # noqa: F401
except ImportError as exc:  # pragma: no cover
    raise ImportError(
        "horovod_tpu.spark requires PySpark, which is not installed in "
        "this environment. The estimator framework (Store / Backend / "
        "JaxEstimator / TorchEstimator) is available Spark-free in "
        "horovod_tpu.cluster; implement a Backend over Spark tasks to "
        "attach it to a cluster."
    ) from exc
