"""Import-path parity with ``horovod.spark.keras`` (reference:
``horovod/spark/keras/__init__.py`` — KerasEstimator lives under the
spark namespace there).  The estimator itself is Spark-free
(:mod:`horovod_tpu.cluster`); pair it with a Backend built on
:func:`horovod_tpu.spark.run` on a real Spark cluster."""

from horovod_tpu.cluster import KerasEstimator, LocalStore, Store  # noqa: F401
