"""Import-path parity with ``horovod.spark.torch`` (reference:
``horovod/spark/torch/__init__.py``)."""

from horovod_tpu.cluster import LocalStore, Store, TorchEstimator  # noqa: F401
