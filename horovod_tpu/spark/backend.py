"""Spark backend for the estimator framework (reference:
``horovod/spark/common/backend.py`` — ``SparkBackend.run`` places one
training task per rank through ``horovod.spark.run``).

Pairs the Spark-free estimators in :mod:`horovod_tpu.cluster` with
Spark task placement: ``KerasEstimator(backend=SparkBackend(2), ...)``
trains inside barrier Spark tasks exactly like the reference's
estimators do."""

from horovod_tpu.cluster.backend import Backend
from horovod_tpu.spark import runner


class SparkBackend(Backend):
    def __init__(self, num_proc=None, use_barrier=True, verbose=False,
                 jax_platform=None):
        self._num_proc = num_proc
        self._use_barrier = use_barrier
        self._verbose = verbose
        self._jax_platform = jax_platform

    def num_processes(self):
        if self._num_proc is not None:
            return self._num_proc
        runner._require_pyspark()
        from pyspark.sql import SparkSession

        sc = SparkSession.builder.getOrCreate().sparkContext
        return max(int(sc.defaultParallelism), 1)

    def run(self, fn, args=(), kwargs=None):
        # Backend contract: fn(rank, *args).  runner.run's task fn runs
        # inside an initialized rank context, so the wrapper reads the
        # rank there (reference: SparkBackend wraps the train fn the
        # same way, backend.py:90).
        def wrapper(*a, **kw):
            import horovod_tpu as hvd

            return fn(hvd.rank(), *a, **kw)

        env = ({"JAX_PLATFORMS": self._jax_platform}
               if self._jax_platform else None)
        return runner.run(wrapper, args=args, kwargs=kwargs,
                          num_proc=self.num_processes(),
                          use_barrier=self._use_barrier,
                          verbose=self._verbose, env=env)
