"""Horovod-on-Spark: run a distributed training fn inside Spark tasks
(reference: ``horovod/spark/runner.py:131`` — one Spark task per rank,
tasks register with a driver service, the training fn ships to the
tasks, results return per rank).

The port keeps the reference's topology — a barrier-stage RDD with one
partition per rank — and replaces the mpirun/gloo orchestration with
this framework's env contract + rendezvous KV: the driver hosts the
RendezvousServer, each Spark task assumes its rank, connects back, and
runs the fn through the tcp controller exactly like an ``hvdrun``
worker.  Requires PySpark (import-guarded).  Executed for real by
``tests/test_spark.py`` against a local-mode stand-in
(``tests/_pyspark_shim``) that reproduces the API surface, cloudpickle
serialization, separate-process executors, and barrier gang-failure
semantics this module depends on — genuine PySpark cannot be installed
in the CI image (no network egress to PyPI)."""

import os
import socket

try:
    import pyspark  # noqa: F401
    _PYSPARK_ERROR = None
except ImportError as _exc:  # pragma: no cover — pyspark absent in image
    pyspark = None
    _PYSPARK_ERROR = _exc


def _require_pyspark():
    if pyspark is None:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.spark requires PySpark, which is not installed "
            "in this environment. The estimator framework (Store / "
            "Backend / estimators) is available Spark-free in "
            "horovod_tpu.cluster.") from _PYSPARK_ERROR


def _driver_ip():
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:  # pragma: no cover
        return "127.0.0.1"


def _task_fn(index, num_proc, fn, args, kwargs, rendezvous_addr,
             rendezvous_port, secret_b64, extra_env):
    """Runs inside one Spark task (= one rank)."""
    from horovod_tpu.utils import env as env_util

    for key, value in (extra_env or {}).items():
        os.environ[key] = value
    if "JAX_PLATFORMS" in os.environ:
        # must land before hvd.init touches jax.local_devices(); some
        # TPU plugins ignore the env var, so pin programmatically too
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # register this task's start with the driver (start_timeout watches
    # for the full gang; reference: task-to-driver registration,
    # spark/driver_service.py).  A rank that is ALREADY registered is a
    # Spark task retry — a retried rank cannot rejoin a gang whose
    # peers are mid-collective (or torn down), so fail the stage fast
    # instead of hanging on a half-dead rendezvous.
    import time as time_mod

    from horovod_tpu.run import http_client

    probe_deadline = time_mod.monotonic() + 15.0
    while True:
        try:
            # retry_for=0: this loop owns its own 15s fail-open budget;
            # the verb's built-in transport retry would overrun it
            http_client.get(rendezvous_addr, int(rendezvous_port),
                            "spark-start", str(index), retry_for=0)
            raise RuntimeError(
                f"task for rank {index} appears to be a Spark retry; "
                f"horovod jobs cannot retry individual ranks — fail "
                f"the whole job and resubmit")
        except KeyError:
            break  # key absent: first attempt, expected
        except OSError:
            # transient transport blip must not kill a healthy first
            # attempt (same rationale as http_client.put's retry);
            # fail OPEN after the budget — if the rendezvous is truly
            # dead the job fails at the next contact anyway
            if time_mod.monotonic() > probe_deadline:
                break
            time_mod.sleep(0.25)
    http_client.put(rendezvous_addr, int(rendezvous_port),
                    "spark-start", str(index), b"1")
    os.environ[env_util.HVD_RANK] = str(index)
    os.environ[env_util.HVD_SIZE] = str(num_proc)
    os.environ[env_util.HVD_LOCAL_RANK] = "0"
    os.environ[env_util.HVD_LOCAL_SIZE] = "1"
    os.environ[env_util.HVD_CROSS_RANK] = str(index)
    os.environ[env_util.HVD_CROSS_SIZE] = str(num_proc)
    os.environ[env_util.HVD_RENDEZVOUS_ADDR] = rendezvous_addr
    os.environ[env_util.HVD_RENDEZVOUS_PORT] = str(rendezvous_port)
    os.environ[env_util.HVD_SECRET_KEY] = secret_b64
    os.environ[env_util.HVD_CONTROLLER] = "tcp"

    import horovod_tpu as hvd

    hvd.init()
    try:
        return fn(*args, **kwargs)
    finally:
        hvd.shutdown()


def run(fn, args=(), kwargs=None, num_proc=None, start_timeout=None,
        use_barrier=True, verbose=False, env=None):
    """Run ``fn(*args, **kwargs)`` as a Horovod job inside Spark tasks;
    returns the list of per-rank results (reference signature:
    ``spark/runner.py:131``; ``env`` merges into each task's
    environment, as there)."""
    _require_pyspark()
    del verbose
    from pyspark.sql import SparkSession

    from horovod_tpu.run.http_server import RendezvousServer
    from horovod_tpu.run.service import secret as secret_mod
    import base64

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)

    rendezvous = RendezvousServer()
    port = rendezvous.start()
    addr = _driver_ip()
    secret_b64 = base64.b64encode(secret_mod.make_secret_key()).decode()

    def mapper(index, _iterator):
        yield _task_fn(index, num_proc, fn, args, kwargs, addr, port,
                       secret_b64, env)

    try:
        rdd = sc.parallelize(range(num_proc), num_proc)
        if use_barrier and hasattr(rdd, "barrier"):
            # barrier mode guarantees all ranks are scheduled together
            # (a partial gang would deadlock the collectives)
            mapped = rdd.barrier().mapPartitionsWithIndex(mapper)
        else:
            mapped = rdd.mapPartitionsWithIndex(mapper)
        if not start_timeout:
            return mapped.collect()
        # start_timeout semantics (reference: spark/runner.py — fail
        # when the cluster cannot schedule the full gang in time, e.g.
        # fewer slots than num_proc): collect in a thread, watch the
        # tasks' start registrations in the rendezvous KV.
        import threading

        box = {}

        def _collect():
            try:
                box["results"] = mapped.collect()
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                box["error"] = exc

        thread = threading.Thread(target=_collect, daemon=True)
        thread.start()
        import time as time_mod

        deadline = time_mod.monotonic() + start_timeout
        started = set()
        while thread.is_alive() and len(started) < num_proc:
            for i in range(num_proc):
                if i not in started and rendezvous.get(
                        "spark-start", str(i)) is not None:
                    started.add(i)
            if (len(started) < num_proc
                    and time_mod.monotonic() > deadline):
                raise RuntimeError(
                    f"Spark could not start all {num_proc} training "
                    f"tasks within start_timeout={start_timeout}s "
                    f"({len(started)} started); does the cluster have "
                    f"enough task slots?")
            thread.join(timeout=0.5)
        thread.join()
        if "error" in box:
            raise box["error"]
        return box["results"]
    finally:
        rendezvous.stop()
