"""Horovod-on-Spark: run a distributed training fn inside Spark tasks
(reference: ``horovod/spark/runner.py:131`` — one Spark task per rank,
tasks register with a driver service, the training fn ships to the
tasks, results return per rank).

The port keeps the reference's topology — a barrier-stage RDD with one
partition per rank — and replaces the mpirun/gloo orchestration with
this framework's env contract + rendezvous KV: the driver hosts the
RendezvousServer, each Spark task assumes its rank, connects back, and
runs the fn through the tcp controller exactly like an ``hvdrun``
worker.  Requires PySpark (import-guarded; absent from this image —
exercised by inspection, a documented scope note)."""

import os
import socket

try:
    import pyspark  # noqa: F401
    _PYSPARK_ERROR = None
except ImportError as _exc:  # pragma: no cover — pyspark absent in image
    pyspark = None
    _PYSPARK_ERROR = _exc


def _require_pyspark():
    if pyspark is None:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.spark requires PySpark, which is not installed "
            "in this environment. The estimator framework (Store / "
            "Backend / estimators) is available Spark-free in "
            "horovod_tpu.cluster.") from _PYSPARK_ERROR


def _driver_ip():
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:  # pragma: no cover
        return "127.0.0.1"


def _task_fn(index, num_proc, fn, args, kwargs, rendezvous_addr,
             rendezvous_port, secret_b64):
    """Runs inside one Spark task (= one rank)."""
    from horovod_tpu.utils import env as env_util

    os.environ[env_util.HVD_RANK] = str(index)
    os.environ[env_util.HVD_SIZE] = str(num_proc)
    os.environ[env_util.HVD_LOCAL_RANK] = "0"
    os.environ[env_util.HVD_LOCAL_SIZE] = "1"
    os.environ[env_util.HVD_CROSS_RANK] = str(index)
    os.environ[env_util.HVD_CROSS_SIZE] = str(num_proc)
    os.environ[env_util.HVD_RENDEZVOUS_ADDR] = rendezvous_addr
    os.environ[env_util.HVD_RENDEZVOUS_PORT] = str(rendezvous_port)
    os.environ[env_util.HVD_SECRET_KEY] = secret_b64
    os.environ[env_util.HVD_CONTROLLER] = "tcp"

    import horovod_tpu as hvd

    hvd.init()
    try:
        return fn(*args, **kwargs)
    finally:
        hvd.shutdown()


def run(fn, args=(), kwargs=None, num_proc=None, start_timeout=None,
        use_barrier=True, verbose=False):
    """Run ``fn(*args, **kwargs)`` as a Horovod job inside Spark tasks;
    returns the list of per-rank results (reference signature:
    ``spark/runner.py:131``)."""
    _require_pyspark()
    del verbose
    from pyspark.sql import SparkSession

    from horovod_tpu.run.http_server import RendezvousServer
    from horovod_tpu.run.service import secret as secret_mod
    import base64

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)

    rendezvous = RendezvousServer()
    port = rendezvous.start()
    addr = _driver_ip()
    secret_b64 = base64.b64encode(secret_mod.make_secret_key()).decode()

    def mapper(index, _iterator):
        yield _task_fn(index, num_proc, fn, args, kwargs, addr, port,
                       secret_b64)

    try:
        rdd = sc.parallelize(range(num_proc), num_proc)
        if use_barrier and hasattr(rdd, "barrier"):
            # barrier mode guarantees all ranks are scheduled together
            # (a partial gang would deadlock the collectives)
            results = rdd.barrier().mapPartitionsWithIndex(
                mapper).collect()
        else:
            if start_timeout:
                sc.setLocalProperty("spark.task.maxFailures", "1")
            results = rdd.mapPartitionsWithIndex(mapper).collect()
        return results
    finally:
        rendezvous.stop()
