"""``bin/hvd-lint`` — the project-invariant static-analysis gate.

Usage::

    bin/hvd-lint horovod_tpu/                 # the tier-1 gate run
    bin/hvd-lint --format json horovod_tpu/   # machine-readable
    bin/hvd-lint --checkers config-surface horovod_tpu/common/
    bin/hvd-lint --write-baseline horovod_tpu/   # refresh suppressions

Exit codes: 0 = clean (baselined findings included), 1 = active
findings, 2 = usage error.  The baseline lives at
``.hvd-lint-baseline.json`` in the repo root; the tier-1 gate
(tests/test_lint.py) keeps it small and justified.
"""

import argparse
import json
import os
import sys

from horovod_tpu.tools.lint import findings as findings_mod
from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.checkers import ALL_CHECKERS

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, ".hvd-lint-baseline.json")

# The project policy: which modules each concurrency checker holds to
# its invariant.  config-surface and wire-safety are global; the lock
# and wakeability checkers scope to the concurrent runtime — the ring
# data plane, the transport, and the controllers (docs/linting.md).
PROJECT_CONFIG = {
    "lock_modules": [
        "ops/tcp_dataplane.py",
        "ops/tcp_controller.py",
        "ops/global_controller.py",
        "run/service/network.py",
        "run/service/driver_service.py",
    ],
    "wakeability_modules": [
        "ops/tcp_dataplane.py",
        "ops/tcp_controller.py",
        "ops/global_controller.py",
        "ops/python_controller.py",
        "run/service/network.py",
    ],
    "wire_pickle_allowlist": [
        "run/service/network.py",
    ],
    "parse_modules": [
        "run/service/network.py",
        "common/wire.py",
    ],
    "docs_dir": os.path.join(REPO_ROOT, "docs"),
}


def run_lint(paths, config=None, checkers=None, _return_project=False):
    """Programmatic entry: returns the list of findings (pre-baseline).
    ``config=None`` applies the project policy; tests pass their own."""
    project = model.load_project(paths)
    cfg = PROJECT_CONFIG if config is None else config
    out = []
    for name, checker in ALL_CHECKERS.items():
        if checkers is not None and name not in checkers:
            continue
        out.extend(checker.check(project, cfg))
    out.sort(key=lambda f: (f.path, f.line, f.checker, f.detail))
    if _return_project:
        return out, project
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-lint",
        description="Project-invariant static analysis for horovod_tpu "
                    "(docs/linting.md).")
    parser.add_argument("paths", nargs="*",
                        default=[os.path.join(REPO_ROOT, "horovod_tpu")],
                        help="Files or directories to scan "
                             "(default: the horovod_tpu package).")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="Baseline JSON of suppressed finding keys "
                             "(default: .hvd-lint-baseline.json in the "
                             "repo root).")
    parser.add_argument("--no-baseline", action="store_true",
                        help="Report every finding, suppressing "
                             "nothing.")
    parser.add_argument("--write-baseline", action="store_true",
                        help="Rewrite the baseline from the current "
                             "findings (existing justifications are "
                             "kept; new entries get a TODO the gate "
                             "test rejects until justified).")
    parser.add_argument("--checkers", default=None,
                        help="Comma-separated checker subset "
                             f"(available: {', '.join(ALL_CHECKERS)}).")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text")
    args = parser.parse_args(argv)

    selected = None
    if args.checkers:
        selected = [c.strip() for c in args.checkers.split(",")]
        unknown = [c for c in selected if c not in ALL_CHECKERS]
        if unknown:
            parser.error(f"unknown checker(s): {', '.join(unknown)}")

    all_findings, project = run_lint(args.paths, checkers=selected,
                                     _return_project=True)

    baseline = {} if args.no_baseline else \
        findings_mod.load_baseline(args.baseline)
    if args.write_baseline:
        # previous entries this run could not have re-observed — an
        # unselected checker, or a path outside the scan — carry over
        # verbatim: a scoped --write-baseline must never delete other
        # scopes' justifications
        scanned = set(project.modules)

        def out_of_scope(key):
            checker, _, rest = key.partition(":")
            relpath = rest.partition(":")[0]
            if selected is not None and checker not in selected:
                return True
            return relpath not in scanned

        previous = findings_mod.load_baseline(args.baseline)
        findings_mod.write_baseline(args.baseline, all_findings,
                                    previous=previous,
                                    out_of_scope=out_of_scope)
        written = len(findings_mod.load_baseline(args.baseline))
        print(f"wrote {written} suppression(s) to {args.baseline}")
        return 0
    active, suppressed, stale = findings_mod.split_baselined(
        all_findings, baseline)

    if args.format == "json":
        json.dump({
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline_keys": stale,
        }, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for finding in active:
            print(finding.render())
        summary = (f"hvd-lint: {len(active)} finding(s), "
                   f"{len(suppressed)} baselined")
        if stale:
            summary += (f", {len(stale)} stale baseline key(s) — "
                        f"run --write-baseline to prune")
        print(summary)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
