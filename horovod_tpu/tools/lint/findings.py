"""Finding objects and the baseline-suppression workflow.

A finding's ``key`` deliberately omits the line number: baselines must
survive unrelated edits to the same file, so identity is
``checker:relpath:context:detail`` where ``context`` is the enclosing
``Class.method`` (or ``<module>``) and ``detail`` names the flagged
thing (an attribute, an env var, a lock cycle...).  The line number is
carried separately for display only.

The baseline file is JSON::

    {"suppressions": [
        {"key": "config-surface:horovod_tpu/x.py:<module>:HVD_FOO",
         "justification": "one line on why this is deliberately deferred"}
    ]}

``bin/hvd-lint --write-baseline`` regenerates it from the current
findings (justifications of surviving keys are preserved); the tier-1
gate (tests/test_lint.py) asserts the checked-in baseline stays small
and justified.
"""

import json


class Finding:
    __slots__ = ("checker", "path", "line", "context", "detail", "message")

    def __init__(self, checker, path, line, context, detail, message):
        self.checker = checker
        self.path = path          # repo-relative, forward slashes
        self.line = line
        self.context = context    # "Class.method" | "func" | "<module>"
        self.detail = detail
        self.message = message

    @property
    def key(self):
        return f"{self.checker}:{self.path}:{self.context}:{self.detail}"

    def as_dict(self):
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "context": self.context,
                "detail": self.detail, "message": self.message,
                "key": self.key}

    def render(self):
        return (f"{self.path}:{self.line}: [{self.checker}] "
                f"{self.message}  ({self.context})")

    def __repr__(self):
        return f"Finding({self.key!r})"


def load_baseline(path):
    """{key: justification} from the baseline JSON (missing file = {})."""
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    out = {}
    for entry in data.get("suppressions", []):
        out[entry["key"]] = entry.get("justification", "")
    return out


def write_baseline(path, findings, previous=None, out_of_scope=None):
    """Write the current findings as the new baseline, keeping the old
    justifications for keys that survive (new keys get a TODO marker the
    gate test refuses, so every suppression is consciously justified).

    ``out_of_scope(key) -> bool``: previous entries the current run
    could not have re-observed (a ``--checkers`` subset or a sub-path
    scan) are carried over verbatim instead of being silently deleted
    with their justifications."""
    previous = previous or {}
    keys = {f.key for f in findings}
    if out_of_scope is not None:
        keys.update(k for k in previous
                    if k not in keys and out_of_scope(k))
    entries = []
    for key in sorted(keys):
        entries.append({
            "key": key,
            "justification": previous.get(
                key, "TODO: justify this suppression"),
        })
    with open(path, "w") as f:
        json.dump({"suppressions": entries}, f, indent=2)
        f.write("\n")


def split_baselined(findings, baseline):
    """(active, suppressed) partition; also returns stale baseline keys
    that no longer match any finding (kept in the exit-0 path — a stale
    key is cleanup, not a failure — but surfaced in the report)."""
    active, suppressed = [], []
    matched = set()
    for finding in findings:
        if finding.key in baseline:
            suppressed.append(finding)
            matched.add(finding.key)
        else:
            active.append(finding)
    stale = sorted(set(baseline) - matched)
    return active, suppressed, stale
