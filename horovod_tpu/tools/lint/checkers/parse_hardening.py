"""Checker 7 — parse hardening (docs/fuzzing.md).

The constraint class behind hvd-fuzz's unbounded-read oracle, made
static: a byte-parsing site that decodes a length/count out of wire
bytes (``struct.unpack``/``unpack_from``, ``int.from_bytes``) must
compare it against a ``MAX_*`` bound before the value reaches an
allocation or a socket read.  Trusting a length field unchecked turns
one hostile frame into a gigabyte ``bytearray`` (or a read that never
completes) before the HMAC is ever looked at.

Two details:

- **unbounded-alloc**: a decoded length flows into ``bytearray()`` /
  ``bytes()`` with no ``MAX_*`` comparison in the function.
- **unchecked-length-read**: a decoded length sizes a socket read
  (``recv``/``recv_into``/``read``/``_read_exact``/
  ``_read_exact_into``) with no ``MAX_*`` comparison in the function.

The comparison is recognized lexically anywhere in the same function
(the transport's cap-then-allocate idiom); ``min(value, MAX_*)``
clamping counts too.  Scope: ``parse_modules`` (None = every scanned
module, which is what the fixture tests use)."""

import ast

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "parse-hardening"

_DECODERS = {"unpack", "unpack_from", "from_bytes"}
_ALLOC_SINKS = {"bytearray", "bytes"}
_READ_SINKS = {"recv", "recv_into", "read", "_read_exact",
               "_read_exact_into"}


def _decoded_names(funcdef):
    """{name: assignment lineno} for every variable bound (possibly via
    tuple unpacking or a subscript of the call) to a wire decoder."""
    out = {}
    for node in ast.walk(funcdef):
        if not isinstance(node, ast.Assign):
            continue
        decodes = any(
            isinstance(sub, ast.Call)
            and (model.expr_text(sub.func) or "").rsplit(".", 1)[-1]
            in _DECODERS
            for sub in ast.walk(node.value))
        if not decodes:
            continue
        for target in node.targets:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    out[sub.id] = node.lineno
    return out


def _is_max_bound(node):
    return any(
        isinstance(sub, ast.Name) and sub.id.startswith("MAX_")
        or isinstance(sub, ast.Attribute) and sub.attr.startswith("MAX_")
        for sub in ast.walk(node))


def _bounded_names(funcdef, tracked):
    """Tracked names that some Compare (or ``min()`` clamp) holds
    against a MAX_* bound anywhere in the function."""
    bounded = set()
    for node in ast.walk(funcdef):
        if isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if any(_is_max_bound(op) for op in operands):
                for op in operands:
                    for sub in ast.walk(op):
                        if isinstance(sub, ast.Name) \
                                and sub.id in tracked:
                            bounded.add(sub.id)
        elif isinstance(node, ast.Call):
            text = (model.expr_text(node.func) or "").rsplit(".", 1)[-1]
            if text == "min" and any(_is_max_bound(a)
                                     for a in node.args):
                for arg in node.args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) \
                                and sub.id in tracked:
                            bounded.add(sub.id)
    return bounded


def check(project, config):
    findings = []
    scope = config.get("parse_modules")
    for module in project.modules.values():
        if not model.in_scope(module, scope):
            continue
        for ctx, _cls, funcdef in model.iter_functions(module):
            tracked = _decoded_names(funcdef)
            if not tracked:
                continue
            bounded = _bounded_names(funcdef, tracked)
            unbounded = set(tracked) - bounded
            if not unbounded:
                continue
            for node in ast.walk(funcdef):
                if not isinstance(node, ast.Call):
                    continue
                meth = (model.expr_text(node.func) or "") \
                    .rsplit(".", 1)[-1]
                if meth in _ALLOC_SINKS:
                    detail = "unbounded-alloc"
                    what = "sizes an allocation"
                elif meth in _READ_SINKS:
                    detail = "unchecked-length-read"
                    what = "sizes a socket read"
                else:
                    continue
                used = sorted(
                    sub.id for arg in node.args for sub in ast.walk(arg)
                    if isinstance(sub, ast.Name) and sub.id in unbounded)
                if not used:
                    continue
                if module.is_wire_safe_annotated(node.lineno) \
                        or module.has_ignore(node.lineno, NAME):
                    continue
                findings.append(Finding(
                    NAME, module.relpath, node.lineno, ctx, detail,
                    f"length field {used[0]!r} decoded from wire bytes "
                    f"{what} ({meth}) with no MAX_* bound check in the "
                    f"function — a hostile frame buys the allocation "
                    f"before any verification (docs/fuzzing.md)"))
    return findings
