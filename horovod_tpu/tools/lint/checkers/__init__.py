"""Checker registry.  Every checker module exposes

    NAME: str                      # the id used in ignore[...] comments
    check(project, config) -> [Finding]

``config`` keys (all optional — a missing/None scope means "all loaded
modules", which is what the fixture tests use; the project policy in
``cli.py`` narrows each checker to the modules whose invariants it
encodes):

- ``lock_modules``: relpath suffixes checked for lock discipline
- ``wakeability_modules``: relpath suffixes on the collective path
- ``thread_lifecycle_modules``: relpath suffixes whose Thread starts
  must be joined or daemon-and-registered
- ``wire_pickle_allowlist``: modules allowed to unpickle network input
- ``parse_modules``: relpath suffixes holding byte-parsing sites to the
  bound-before-allocate rule (docs/fuzzing.md)
- ``docs_dir``: where the tri-surface checker greps for knob mentions
- ``skip_tri_surface``: disable the project-level tri-surface rule
"""

from horovod_tpu.tools.lint.checkers import (
    config_surface,
    lock_discipline,
    lock_order,
    parse_hardening,
    thread_lifecycle,
    wakeability,
    wire_safety,
)

ALL_CHECKERS = {
    lock_discipline.NAME: lock_discipline,
    lock_order.NAME: lock_order,
    wakeability.NAME: wakeability,
    thread_lifecycle.NAME: thread_lifecycle,
    config_surface.NAME: config_surface,
    wire_safety.NAME: wire_safety,
    parse_hardening.NAME: parse_hardening,
}
