"""thread-lifecycle: every started thread has a declared way to end.

The fault-tolerant runtime's contract (docs/fault_tolerance.md) is that
no thread outlives its owner silently: a wedged background thread is
exactly the unreproducible-stall material hvd-race exists to catch
dynamically, and this checker keeps the *static* inventory honest.

Rule — every ``threading.Thread(...)`` construction in scope must be:

1. **joined**: some ``<x>.join(...)`` call exists in the same class
   (or, for module-level functions, the same module) — the owner's
   shutdown path waits for the thread; OR
2. **daemon + registered**: the construction passes ``daemon=True``
   AND the construction lines (or the contiguous comment block above)
   carry a ``# lifecycle:`` / ``# wakeable:`` annotation saying how the
   thread exits or why it may be abandoned (the same register-it-or-
   join-it convention abort-wakeability applies to blocking waits).

The join detection is deliberately coarse (any ``.join(`` in the owning
class counts): the checker enforces that a lifecycle *story* exists per
owner, not which exact attribute carries it — the precise wait graph is
hvd-race's job at runtime.

``config["thread_lifecycle_modules"]``: relpath suffixes in scope
(None/missing = every scanned module).  Inline escape:
``# hvd-lint: ignore[thread-lifecycle]``.
"""

import ast
import re

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "thread-lifecycle"

_LIFECYCLE_RE = re.compile(r"lifecycle:|wakeable:")


def _is_thread_ctor(node):
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    name = func.attr if isinstance(func, ast.Attribute) \
        else func.id if isinstance(func, ast.Name) else None
    # Timer subclasses Thread with the same lifecycle obligations
    return name in ("Thread", "Timer")


def _has_daemon_true(node):
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _is_thread_join(node):
    """A ``<expr>.join(...)`` call that can plausibly be a thread join:
    string/bytes separators (``", ".join(...)``, ``b"".join(...)``) and
    path joins (``os.path.join``) must not discharge the rule — a log
    line's comma join is not a shutdown path."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr == "join"):
        return False
    if isinstance(func.value, ast.Constant):
        return False  # literal str/bytes separator
    text = model.expr_text(func.value)
    if text is not None and text.split(".")[-1] in ("path", "posixpath",
                                                    "ntpath"):
        return False
    return True


def _joins_in(funcdefs):
    """True when any plausible thread join appears in the given
    function bodies (the owner waits for SOME thread on its shutdown
    path)."""
    for funcdef in funcdefs:
        for node in ast.walk(funcdef):
            if isinstance(node, ast.Call) and _is_thread_join(node):
                return True
    return False


def _annotated(module, node):
    """Annotation on any line of the (possibly multi-line) construction
    or the contiguous comment block above it."""
    for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
        if module.comment(line) and _LIFECYCLE_RE.search(
                module.comment(line)):
            return True
    return module.annotated(node.lineno, _LIFECYCLE_RE)


def _walk_shallow(funcdef):
    """Walk a function body without descending into nested def/class
    bodies — those are yielded as their own iter_functions entries, and
    descending here would double-report their thread constructions."""
    stack = list(funcdef.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


def check(project, config):
    scope = config.get("thread_lifecycle_modules")
    findings = []
    for module in project.modules.values():
        if not model.in_scope(module, scope):
            continue
        # owner -> the function bodies whose joins count for it
        for context, cls, funcdef in model.iter_functions(module):
            for node in _walk_shallow(funcdef):
                if not _is_thread_ctor(node):
                    continue
                if module.has_ignore(node.lineno, NAME):
                    continue
                if _annotated(module, node):
                    continue
                owner_funcs = (cls.methods.values() if cls is not None
                               else [f for _c, k, f in
                                     model.iter_functions(module)
                                     if k is None])
                joined = _joins_in(list(owner_funcs))
                daemon = _has_daemon_true(node)
                if joined:
                    continue
                owner = cls.name if cls is not None else "<module>"
                if daemon:
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno, context,
                        f"daemon-unregistered:{owner}",
                        "daemon thread is neither joined on the "
                        "owner's shutdown path nor registered with a "
                        "'# lifecycle:' annotation saying how it "
                        "exits"))
                else:
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno, context,
                        f"unjoined:{owner}",
                        "non-daemon thread is never joined in its "
                        "owning " +
                        ("class" if cls is not None else "module") +
                        " and carries no '# lifecycle:' annotation — "
                        "it can outlive shutdown silently"))
    return findings
