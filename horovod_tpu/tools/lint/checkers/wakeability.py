"""Checker 3 — abort wakeability.

The fault-tolerance contract (docs/fault_tolerance.md): once any rank
initiates a coordinated abort, every rank must raise the typed error
within the abort deadline — so no blocking primitive on the collective
path may sleep forever on an event only a (possibly dead) peer can
produce.  Every ``Condition.wait`` / ``Event.wait`` / ``queue.get`` /
``socket.recv`` in the scoped modules must either

- carry a timeout argument (a ``timeout=None`` variable still passes —
  the static check reads the signature, the runtime contract is the
  caller's), or
- be registered with the abort-wakeup set via a ``# wakeable: <how>``
  annotation naming the mechanism that interrupts it (the abort
  broadcast notifying the mailbox condition, a close() sentinel, socket
  teardown breaking the recv...).

Socket ``recv``/``recv_into`` can never express a timeout at the call
site, so those always need the annotation.

The session layer's framed reads (``read_message`` /
``read_bulk_message``) are blocking socket reads one level up: a call
is bounded when the same function arms a real ``settimeout`` (a
non-None argument) on a socket, and otherwise needs the ``# wakeable:``
registration naming what breaks the read — for the resume handshake
and the replay/ack pumps that is the socket close the healing or
aborting side performs.
"""

import ast

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "abort-wakeability"

_SOCKET_NAMES = {"sock", "s", "conn", "connection"}
_FRAMED_READS = {"read_message", "read_bulk_message"}


def _bounded_by_settimeout(funcdef):
    """Whether this function arms a real socket timeout: a
    ``<sock>.settimeout(x)`` call with ``x`` not the constant None
    (nested defs excluded — they are scanned as their own functions)."""
    stack = list(funcdef.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            callee = model.expr_text(node.func) or ""
            if callee.rsplit(".", 1)[-1] == "settimeout" and node.args:
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and arg.value is None):
                    return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _local_kinds(funcdef):
    """var -> kind for locals assigned from sync-primitive or socket
    constructors, plus socket-named parameters."""
    kinds = {}
    for arg in getattr(funcdef.args, "args", []):
        if arg.arg in _SOCKET_NAMES or "sock" in arg.arg:
            kinds[arg.arg] = "socket"
    for node in ast.walk(funcdef):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        callee = model.expr_text(node.value.func) or ""
        tail = callee.rsplit(".", 1)[-1]
        kind = None
        if tail in ("Condition",):
            kind = "condition"
        elif tail == "Event":
            kind = "event"
        elif tail in ("Queue", "LifoQueue", "PriorityQueue",
                      "SimpleQueue"):
            kind = "queue"
        elif ("socket" in callee or "connect" in tail
              or tail == "accept"):
            kind = "socket"
        if kind:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    kinds[target.id] = kind
    return kinds


def _has_timeout(call, meth):
    """Whether the call is bounded.  Signatures differ: for
    ``Condition.wait(timeout)`` / ``Event.wait(timeout)`` the first
    positional IS the timeout, but ``Queue.get(block, timeout)`` takes
    ``block`` first — ``get(True)`` blocks forever and must not pass."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if meth == "get":
        if len(call.args) >= 2:
            return True  # explicit (block, timeout) positionals
        # get(False) / block=False is non-blocking, hence bounded
        for value in list(call.args[:1]) + [
                kw.value for kw in call.keywords if kw.arg == "block"]:
            if isinstance(value, ast.Constant) and value.value is False:
                return True
        return False
    return bool(call.args)


def check(project, config):
    findings = []
    scope = config.get("wakeability_modules")
    for module in project.modules.values():
        if not model.in_scope(module, scope):
            continue
        for ctx, cls, funcdef in model.iter_functions(module):
            attrs = project.class_lock_attrs(cls) if cls else {}
            locals_ = _local_kinds(funcdef)
            has_socket_timeout = _bounded_by_settimeout(funcdef)

            def kind_of(base):
                tail = base.rsplit(".", 1)[-1]
                if base in locals_:
                    return locals_[base]
                if tail in attrs:
                    return attrs[tail]
                if tail.endswith("_cv") or tail == "cv":
                    return "condition"
                if "sock" in tail:
                    return "socket"
                return None

            def visit(node, stack, acquiring=None, _ctx=ctx):
                if acquiring is not None or not isinstance(
                        node, ast.Call):
                    return
                callee = model.expr_text(node.func)
                if callee is None:
                    return
                if callee.rsplit(".", 1)[-1] in _FRAMED_READS:
                    # a framed read blocks on the socket one level up;
                    # bounded only by an armed settimeout in the same
                    # function
                    if has_socket_timeout:
                        return
                    if module.is_wakeable_annotated(node.lineno) \
                            or module.has_ignore(node.lineno, NAME):
                        return
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno, _ctx,
                        callee.rsplit(".", 1)[-1],
                        f"blocking framed read {callee}() with no armed "
                        f"settimeout in the function and no "
                        f"'# wakeable:' registration — a coordinated "
                        f"abort cannot wake it "
                        f"(docs/fault_tolerance.md)"))
                    return
                if "." not in callee:
                    return
                base, meth = callee.rsplit(".", 1)
                kind = kind_of(base)
                blocking = (
                    (meth == "wait" and kind in ("condition", "event"))
                    or (meth == "get" and kind == "queue")
                    or (meth in ("recv", "recv_into")
                        and kind == "socket"))
                if not blocking:
                    return
                # recv can't take a timeout at the call site; the
                # others pass with one
                if meth not in ("recv", "recv_into") \
                        and _has_timeout(node, meth):
                    return
                if module.is_wakeable_annotated(node.lineno) \
                        or module.has_ignore(node.lineno, NAME):
                    return
                findings.append(Finding(
                    NAME, module.relpath, node.lineno, _ctx, callee,
                    f"blocking {callee}() on the collective "
                    f"path with no timeout and no '# wakeable:' "
                    f"registration — a coordinated abort cannot wake "
                    f"it (docs/fault_tolerance.md)"))

            model.walk_with_locks(funcdef, visit,
                                  known_attrs=attrs)
    return findings
