"""Checker 4 — config tri-surface.

The configuration model (docs/running.md, docs/tuning.md): every knob
exists on three surfaces — ``HVD_*`` env var, ``hvdrun`` CLI flag, and
YAML config-file key — and the env surface is reached ONLY through a
``utils/env.py`` constant plus typed getter, so a malformed value warns
once instead of silently vanishing and the knob inventory stays
greppable in one file.

Rules:

- **raw-env-read**: ``os.environ.get(...)`` / ``os.environ[...]`` /
  ``os.getenv(...)`` of an ``HVD_*`` key anywhere outside
  ``utils/env.py`` — route through ``env_util.get_str/int/float/bool``
  (or ``get_required`` for hard launcher-contract reads).  Writes
  (``os.environ[X] = ...``) are launcher plumbing and stay raw.
- **literal-key**: an env getter called with a string literal instead
  of the declared ``env_util`` constant (or with an ``HVD_*`` literal
  that has no constant at all — declare it).
- **tri-surface** (project-level, evaluated when ``utils/env.py`` is in
  the scan): every knob constant — anything not listed in env.py's
  ``LAUNCHER_CONTRACT`` — must appear in ``run/config_parser.py``'s
  ``_PARAMS``/``_NEGATIONS`` mapping, its mapped arg must exist as an
  ``hvdrun`` ``--flag`` in ``run/runner.py``, and the variable must be
  mentioned somewhere under ``docs/``.
"""

import ast
import os

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "config-surface"

_ENV_READ_FUNCS = {"os.environ.get", "environ.get", "os.getenv",
                   "getenv"}
_ENV_SUBSCRIPTS = {"os.environ", "environ"}
_GETTER_BASES = {"env_util", "env"}


def _env_py(project):
    return project.find_module("utils/env.py")


def _constants(env_module):
    """{py_name: env_var_value} for top-level HVD_* string constants."""
    out = {}
    for node in env_module.tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and node.value.value.startswith("HVD"):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.value.value
    return out


def _contract(env_module):
    """Py names listed in env.py's LAUNCHER_CONTRACT declaration."""
    for node in env_module.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "LAUNCHER_CONTRACT"
                for t in node.targets):
            return {n.id for n in ast.walk(node.value)
                    if isinstance(n, ast.Name)
                    and n.id.startswith("HVD")}
    return set()


def check(project, config):
    findings = []
    env_module = _env_py(project)
    constants = _constants(env_module) if env_module else {}
    values = set(constants.values())

    for module in project.modules.values():
        if module is env_module:
            continue
        findings.extend(_check_reads(module, constants, values))

    if env_module is not None and not config.get("skip_tri_surface"):
        findings.extend(_check_tri_surface(
            project, config, env_module, constants,
            _contract(env_module)))
    return findings


def _is_env_getter(module, callee):
    """True when ``callee`` denotes a utils/env.py typed getter —
    through a module alias (``env_util.get_int``) or a bare from-import
    (``from horovod_tpu.utils.env import get_int``), resolved via the
    module's import map so neither spelling escapes the literal-key
    rule."""
    if "." in callee:
        base, meth = callee.rsplit(".", 1)
        if not meth.startswith("get_"):
            return False
        if base.rsplit(".", 1)[-1] in _GETTER_BASES:
            return True
        dotted = module.imports.get(base, "")
        return dotted.endswith("utils.env")
    if not callee.startswith("get_"):
        return False
    return module.imports.get(callee, "").endswith(
        f"utils.env.{callee}")


def _key_env_name(node, constants):
    """The HVD_* env-var name an expression denotes, or None: a string
    literal, or an (aliased) env_util constant attribute/name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value.startswith("HVD") else None
    text = model.expr_text(node)
    if text is None:
        return None
    tail = text.rsplit(".", 1)[-1]
    return constants.get(tail)


def _contexts(module):
    """(start, end, ctx) spans for every function, innermost last —
    finding keys must name the enclosing function, or one baselined
    read would suppress every later read of the same var in the file."""
    spans = []
    for ctx, _cls, funcdef in model.iter_functions(module):
        spans.append((funcdef.lineno,
                      funcdef.end_lineno or funcdef.lineno, ctx))
    spans.sort(key=lambda s: (s[0], -s[1]))
    return spans


def _context_at(spans, lineno):
    best = "<module>"
    for start, end, ctx in spans:
        if start <= lineno <= end:
            best = ctx  # spans are outermost-first at equal starts
    return best


def _check_reads(module, constants, values):
    findings = []
    spans = _contexts(module)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            callee = model.expr_text(node.func) or ""
            if callee in _ENV_READ_FUNCS and node.args:
                name = _key_env_name(node.args[0], constants)
                if name and not module.has_ignore(node.lineno, NAME):
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno,
                        _context_at(spans, node.lineno), name,
                        f"raw os.environ read of {name} — use the "
                        f"utils/env.py constant + typed getter"))
            elif (_is_env_getter(module, callee)
                  and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)
                  and node.args[0].value.startswith("HVD")
                  and not module.has_ignore(node.lineno, NAME)):
                literal = node.args[0].value
                declared = literal in values
                findings.append(Finding(
                    NAME, module.relpath, node.lineno,
                    _context_at(spans, node.lineno), literal,
                    f"env getter called with the string literal "
                    f"{literal!r} — "
                    + ("use the utils/env.py constant"
                       if declared else
                       "declare a constant for it in utils/env.py")))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            base = model.expr_text(node.value)
            if base in _ENV_SUBSCRIPTS:
                name = _key_env_name(node.slice, constants)
                if name and not module.has_ignore(node.lineno, NAME):
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno,
                        _context_at(spans, node.lineno), name,
                        f"raw os.environ[{name}] read — use "
                        f"env_util.get_required/get_str"))
    return findings


def _parse_params(config_module):
    """{env_py_name: arg_name} from _PARAMS plus the set of env py
    names covered by _NEGATIONS."""
    params, negations = {}, set()
    for node in config_module.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        target_names = {t.id for t in node.targets
                        if isinstance(t, ast.Name)}
        if "_PARAMS" in target_names \
                and isinstance(node.value, ast.Dict):
            for key, value in zip(node.value.keys, node.value.values):
                if not (isinstance(key, ast.Constant)
                        and isinstance(value, ast.Tuple)
                        and value.elts):
                    continue
                env_text = model.expr_text(value.elts[0]) or ""
                params[env_text.rsplit(".", 1)[-1]] = key.value
        elif "_NEGATIONS" in target_names \
                and isinstance(node.value, ast.Dict):
            for value in node.value.values:
                env_text = model.expr_text(value) or ""
                negations.add(env_text.rsplit(".", 1)[-1])
    return params, negations


def _docs_mentions(docs_dir):
    corpus = []
    if docs_dir and os.path.isdir(docs_dir):
        for name in sorted(os.listdir(docs_dir)):
            if name.endswith(".md"):
                try:
                    with open(os.path.join(docs_dir, name),
                              encoding="utf-8") as f:
                        corpus.append(f.read())
                except OSError:
                    continue
    return "\n".join(corpus)


def _check_tri_surface(project, config, env_module, constants,
                       contract):
    findings = []
    config_module = project.find_module("run/config_parser.py")
    runner_module = project.find_module("run/runner.py")
    if config_module is None or runner_module is None:
        return findings  # partial scan: the project rule needs both
    params, negations = _parse_params(config_module)
    docs = _docs_mentions(config.get("docs_dir"))

    for py_name, env_name in sorted(constants.items()):
        if py_name in contract:
            continue
        if module_ignores(env_module, py_name):
            continue
        if py_name not in params and py_name not in negations:
            findings.append(Finding(
                NAME, config_module.relpath, 1, "tri-surface",
                f"{env_name}:params",
                f"knob {env_name} has no _PARAMS/_NEGATIONS mapping in "
                f"run/config_parser.py (YAML + flag surface missing)"))
            continue
        arg = params.get(py_name)
        if arg is not None:
            flag = "--" + arg.replace("_", "-")
            if flag not in runner_module.source:
                findings.append(Finding(
                    NAME, runner_module.relpath, 1, "tri-surface",
                    f"{env_name}:flag",
                    f"knob {env_name} maps to arg {arg!r} but hvdrun "
                    f"defines no {flag} flag"))
        if docs and env_name not in docs:
            findings.append(Finding(
                NAME, env_module.relpath, 1, "tri-surface",
                f"{env_name}:docs",
                f"knob {env_name} is mentioned nowhere under docs/"))
    return findings


def module_ignores(env_module, py_name):
    """An ignore comment on the constant's declaration line exempts it
    from the tri-surface rule (used for internal/experimental knobs)."""
    for node in env_module.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == py_name
                for t in node.targets):
            return env_module.has_ignore(node.lineno, NAME)
    return False
