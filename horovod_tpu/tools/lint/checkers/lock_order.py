"""Checker 2 — lock ordering.

Builds the cross-module lock-acquisition graph: an edge A -> B means
some function acquires B (``with self._b:``) while lexically holding A,
or calls — while holding A — a resolvable method/function that acquires
B anywhere in its body (one level of call expansion; enough for the
``_locked``-helper idiom without a full interprocedural analysis).

Findings:

- **cycle**: a strongly-connected component in the graph — two threads
  taking the locks in opposite orders can deadlock;
- **reacquire**: an edge A -> A on a non-reentrant primitive (a plain
  ``Lock``/``Condition`` taken again while held deadlocks immediately);
- **foreign-wait**: ``cv.wait()`` while holding a lock other than the
  condition's own — the wait releases only the condition's lock, so the
  foreign lock stays held for the whole sleep and anything that needs
  it to produce the wakeup deadlocks.  ``Event.wait`` under any held
  lock is flagged the same way.

Lock identity: ``self._x`` (or a ``service = self`` alias) resolves to
``(Class, attr)``; module-level locks to ``(module, name)``; locals
(e.g. a per-connection ``write_lock``) to ``(function, name)``.
"""

import ast

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding
from horovod_tpu.tools.lint.checkers.lock_discipline import _self_aliases

NAME = "lock-order"


class _FuncInfo:
    __slots__ = ("module", "cls", "name", "acquired", "edges", "calls",
                 "lock_kinds")

    def __init__(self, module, cls, name):
        self.module = module
        self.cls = cls
        self.name = name
        self.acquired = set()     # every lock id taken anywhere inside
        self.edges = []           # (held_id, taken_id, lineno)
        self.calls = []           # (callee_text, held_ids, lineno)
        self.lock_kinds = {}      # lock id -> kind (when resolvable)


def check(project, config):
    findings = []
    funcs = {}

    for module in project.modules.values():
        for ctx, cls, funcdef in model.iter_functions(module):
            info = _scan_function(project, module, cls, ctx, funcdef,
                                  findings)
            funcs[(module.dotted, cls.name if cls else None,
                   funcdef.name)] = info

    edges = {}   # (a, b) -> (relpath, lineno)
    kinds = {}
    for info in funcs.values():
        kinds.update(info.lock_kinds)
        for held, taken, lineno in info.edges:
            edges.setdefault((held, taken),
                             (info.module.relpath, lineno))
        for callee, held_ids, lineno in info.calls:
            target = _resolve_call(project, funcs, info, callee)
            if target is None:
                continue
            for held in held_ids:
                for taken in target.acquired:
                    edges.setdefault((held, taken),
                                     (info.module.relpath, lineno))

    for (a, b), (relpath, lineno) in sorted(edges.items()):
        # RLock is reentrant by definition; so is threading.Condition,
        # whose default inner lock is an RLock (nested acquisition runs
        # fine — only wait() semantics differ, covered by foreign-wait)
        if a == b and kinds.get(a) not in ("rlock", "condition"):
            findings.append(Finding(
                NAME, relpath, lineno, _pretty(a),
                f"reacquire:{_pretty(a)}",
                f"non-reentrant lock {_pretty(a)} taken again while "
                f"already held (deadlock)"))

    for cycle in _cycles({(a, b) for a, b in edges if a != b}):
        names = [_pretty(n) for n in cycle]
        members = set(cycle)
        evidence = sorted(e for e in edges
                          if e[0] in members and e[1] in members)
        relpath, lineno = edges[evidence[0]]
        findings.append(Finding(
            NAME, relpath, lineno, "lock-graph",
            "cycle:" + "->".join(names),
            f"lock-order cycle {' -> '.join(names + [names[0]])}: "
            f"threads taking these locks in different orders can "
            f"deadlock"))
    return findings


def _scan_function(project, module, cls, ctx, funcdef, findings):
    info = _FuncInfo(module, cls, funcdef.name)
    known = (project.class_lock_attrs(cls) if cls
             else dict(module.module_locks))
    aliases = _self_aliases(cls) if cls else {"self"}

    def lock_id(text):
        head, _, rest = text.partition(".")
        attr = text.rsplit(".", 1)[-1]
        if cls and head in aliases and rest:
            # resolve to the class that DECLARES the lock, module-
            # qualified: a lock inherited from a base must be one node
            # whether it's taken in base or subclass methods, and two
            # unrelated same-named classes in different modules must
            # never merge (that would fabricate cycles)
            owner = cls
            if attr not in cls.lock_attrs:
                for ancestor in project.ancestors(cls):
                    if attr in ancestor.lock_attrs:
                        owner = ancestor
                        break
            return ("cls", owner.module.dotted, owner.name, attr)
        if not rest and text in module.module_locks:
            return ("mod", module.dotted, text)
        return ("loc", module.dotted, ctx, attr)

    def visit(node, stack, acquiring=None):
        if acquiring is not None:
            taken = lock_id(acquiring.text)
            info.acquired.add(taken)
            kind = known.get(acquiring.attr)
            if kind:
                info.lock_kinds[taken] = kind
            for held in stack:
                info.edges.append((lock_id(held.text), taken,
                                   node.lineno))
            return
        if not isinstance(node, ast.Call):
            return
        callee = model.expr_text(node.func)
        if callee is None:
            return
        if stack:
            info.calls.append(
                (callee, [lock_id(h.text) for h in stack],
                 node.lineno))
        if callee.endswith(".wait") and stack \
                and not module.has_ignore(node.lineno, NAME):
            base = callee[:-len(".wait")]
            base_attr = base.rsplit(".", 1)[-1]
            kind = known.get(base_attr)
            if kind is None and (base_attr.endswith("_cv")
                                 or base_attr == "cv"):
                kind = "condition"
            if kind == "condition":
                foreign = [h for h in stack if h.attr != base_attr]
                if foreign:
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno, ctx,
                        f"foreign-wait:{base_attr}",
                        f"{base}.wait() while holding "
                        f"{[h.text for h in foreign]} — the wait only "
                        f"releases the condition's own lock"))
            elif kind == "event":
                findings.append(Finding(
                    NAME, module.relpath, node.lineno, ctx,
                    f"foreign-wait:{base_attr}",
                    f"{base}.wait() (an Event) while holding "
                    f"{[h.text for h in stack]} — the held lock stays "
                    f"taken for the whole wait"))

    model.walk_with_locks(funcdef, visit, known_attrs=known)
    return info


def _resolve_call(project, funcs, info, callee):
    parts = callee.split(".")
    if len(parts) == 2 and parts[0] == "self" and info.cls:
        key = (info.module.dotted, info.cls.name, parts[1])
        if key in funcs:
            return funcs[key]
        for ancestor in project.ancestors(info.cls):
            key = (ancestor.module.dotted, ancestor.name, parts[1])
            if key in funcs:
                return funcs[key]
        return None
    if len(parts) == 1:
        return funcs.get((info.module.dotted, None, parts[0]))
    return None


def _cycles(edge_set):
    """Strongly-connected components with >= 2 nodes, as ordered node
    lists (iterative Tarjan — the graph is tiny but recursion depth is
    not worth the risk)."""
    graph = {}
    for a, b in edge_set:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index, low, on_stack = {}, {}, set()
    stack, out, counter = [], [], [0]

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(graph[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(graph[nxt])))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    scc.append(member)
                    if member == node:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))
    return out


def _pretty(lock_id):
    if lock_id[0] == "cls":
        return f"{lock_id[2]}.{lock_id[3]}"
    if lock_id[0] == "mod":
        return f"{lock_id[1]}:{lock_id[2]}"
    return f"{lock_id[2]}:{lock_id[3]}"
