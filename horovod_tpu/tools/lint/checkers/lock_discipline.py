"""Checker 1 — lock discipline.

The concurrency convention (docs/linting.md): a class that runs code on
more than one thread declares which lock owns each shared mutable
attribute with a ``# guarded by self._lock`` comment on the attribute's
``__init__`` assignment.  This checker then enforces the declaration:
every read/write of a guarded attribute anywhere in the class must
happen lexically inside ``with self._lock:`` (or in a method annotated
``# holds: self._lock``, the convention for ``*_locked`` helpers whose
caller owns the lock).

It also enforces adoption: inside the scoped modules, a class that both
spawns a thread (itself or via a resolvable base class) and creates
Lock/RLock/Condition attributes in ``__init__`` must declare at least
one guarded attribute — the state it synchronizes cannot be entirely
private to one thread, or it would not need the lock.

Accesses through a ``handler = self``-style alias (the nested request
handler closures in run/service/network.py) are resolved through the
alias and checked the same way.
"""

import ast

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "lock-discipline"


def _self_aliases(cls):
    """Names assigned from bare ``self`` anywhere in the class — the
    closure-capture idiom (``service = self``) used by handler
    factories."""
    aliases = {"self"}
    for method in cls.methods.values():
        for node in ast.walk(method):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        aliases.add(target.id)
    return aliases


def check(project, config):
    findings = []
    scope = config.get("lock_modules")
    for module in project.modules.values():
        if not model.in_scope(module, scope):
            continue
        for cls in module.classes.values():
            findings.extend(_check_class(project, module, cls))
    return findings


def _check_class(project, module, cls):
    findings = []
    guarded = project.class_guarded(cls)
    lock_attrs = project.class_lock_attrs(cls)
    own_locks = {a for a, kind in cls.lock_attrs.items()
                 if kind in ("lock", "rlock", "condition")}
    # adoption rule: the locks THIS class creates must guard something
    # it declares — inherited declarations cover only inherited locks
    own_declared = any(owner in own_locks
                       for owner in cls.guarded.values())
    if (own_locks and not own_declared
            and project.class_spawns_thread(cls)
            and not module.has_ignore(cls.node.lineno, NAME)):
        findings.append(Finding(
            NAME, module.relpath, cls.node.lineno, cls.name,
            "undeclared-guards",
            f"class {cls.name} spawns threads and creates lock(s) "
            f"{sorted(own_locks)} but declares no '# guarded by "
            f"self._lock' attributes for them (docs/linting.md)"))
    if not guarded:
        return findings

    aliases = _self_aliases(cls)
    for ctx_name, _cls, funcdef in model.iter_functions(module):
        # only functions lexically inside this class
        if _cls is not cls or funcdef.name == "__init__":
            continue
        held_annot = cls.holds.get(funcdef.name, set()) \
            | module.scan_holds(funcdef)

        def visit(node, stack, acquiring=None, _ctx=ctx_name,
                  _held=held_annot):
            if acquiring is not None or not isinstance(
                    node, ast.Attribute):
                return
            if not (isinstance(node.value, ast.Name)
                    and node.value.id in aliases):
                return
            attr = node.attr
            owner = guarded.get(attr)
            if owner is None:
                return
            if any(ref.attr == owner for ref in stack):
                return
            if owner in _held:
                return
            if module.has_ignore(node.lineno, NAME):
                return
            findings.append(Finding(
                NAME, module.relpath, node.lineno, _ctx, attr,
                f"'{attr}' is guarded by self.{owner} but accessed "
                f"without it (annotate '# holds: self.{owner}' if the "
                f"caller owns the lock)"))

        model.walk_with_locks(funcdef, visit, known_attrs=lock_attrs)
    return findings
