"""Checker 5 — wire safety.

The transport's security model (run/service/network.py docstring): an
unauthenticated peer must never reach the unpickler, and every frame
that leaves a socket is HMAC-signed.  Statically:

- **pickle-loads**: ``pickle.loads``/``load`` (and the cloudpickle /
  ``_pickler`` aliases) is allowed only (a) inside the allowlisted
  verified-transport modules, (b) in a function that also calls
  ``secret.check``/``check_parts`` (the verify-then-deserialize idiom
  of run/api.py and run/task_runner.py), or (c) under a
  ``# wire-safe: <why>`` annotation for payloads that arrived through
  an already-authenticated channel.
- **raw-send**: direct ``sock.sendall``/``sendmsg`` outside the
  transport module — frames must funnel through
  ``network.write_message``/``write_bulk_message`` so they are signed.
- **unsigned-send**: inside the transport module, a frame-emitting
  function that never calls ``secret.sign``/``sign_parts`` (annotate
  helpers that only forward pre-signed bytes).
"""

import ast

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "wire-safety"

_PICKLE_BASES = {"pickle", "cloudpickle", "_pickler"}


def _function_calls(funcdef):
    """Call nodes lexically in this function (nested defs excluded —
    they are scanned as their own functions)."""
    out = []
    stack = list(funcdef.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def check(project, config):
    findings = []
    allowlist = config.get("wire_pickle_allowlist") or []
    for module in project.modules.values():
        allowlisted = any(module.relpath.endswith(s) for s in allowlist)
        for ctx, _cls, funcdef in model.iter_functions(module):
            calls = _function_calls(funcdef)
            names = [(model.expr_text(c.func) or "", c) for c in calls]
            has_check = any(
                t.rsplit(".", 1)[-1] in ("check", "check_parts")
                and ("secret" in t or "." not in t)
                for t, _ in names)
            has_sign = any(
                t.rsplit(".", 1)[-1] in ("sign", "sign_parts")
                and ("secret" in t or "." not in t)
                for t, _ in names)
            for text, call in names:
                parts = text.rsplit(".", 1)
                if len(parts) != 2:
                    continue
                base, meth = parts
                if meth in ("loads", "load") \
                        and base.rsplit(".", 1)[-1] in _PICKLE_BASES:
                    if allowlisted or has_check:
                        continue
                    if module.is_wire_safe_annotated(call.lineno) \
                            or module.has_ignore(call.lineno, NAME):
                        continue
                    findings.append(Finding(
                        NAME, module.relpath, call.lineno, ctx,
                        "pickle-loads",
                        f"{text}() outside the HMAC-verified transport "
                        f"with no secret.check in the same function — "
                        f"an unauthenticated peer must never reach the "
                        f"unpickler"))
                elif meth in ("sendall", "sendmsg"):
                    if module.is_wire_safe_annotated(call.lineno) \
                            or module.has_ignore(call.lineno, NAME):
                        continue
                    if not allowlisted:
                        findings.append(Finding(
                            NAME, module.relpath, call.lineno, ctx,
                            "raw-send",
                            f"direct {text}() outside the signed "
                            f"transport — emit frames through "
                            f"network.write_message/write_bulk_message "
                            f"so they are HMAC-signed"))
                    elif not has_sign:
                        findings.append(Finding(
                            NAME, module.relpath, call.lineno, ctx,
                            "unsigned-send",
                            f"frame-emitting {text}() in a function "
                            f"that never signs — every emitted frame "
                            f"must carry an HMAC (annotate "
                            f"'# wire-safe:' if it forwards pre-signed "
                            f"bytes)"))
    return findings
