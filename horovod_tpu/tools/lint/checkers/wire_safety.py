"""Checker 5 — wire safety.

The transport's security model (run/service/network.py docstring): an
unauthenticated peer must never reach the unpickler, and every frame
that leaves a socket is HMAC-signed.  Statically:

- **pickle-loads**: ``pickle.loads``/``load`` (and the cloudpickle /
  ``_pickler`` aliases) is allowed only (a) inside the allowlisted
  verified-transport modules, (b) in a function that also calls
  ``secret.check``/``check_parts`` (the verify-then-deserialize idiom
  of run/api.py and run/task_runner.py), or (c) under a
  ``# wire-safe: <why>`` annotation for payloads that arrived through
  an already-authenticated channel.
- **raw-send**: direct ``sock.sendall``/``sendmsg`` outside the
  transport module — frames must funnel through
  ``network.write_message``/``write_bulk_message`` so they are signed.
- **unsigned-send**: inside the transport module, a frame-emitting
  function that never calls ``secret.sign``/``sign_parts`` (annotate
  helpers that only forward pre-signed bytes).

Session-layer rules (the self-healing transport's resume handshake,
docs/fault_tolerance.md):

- **unfenced-resume**: a function that constructs a ``SessionWelcome``
  admits a resuming connection — it must fence the hello against the
  service epoch (call ``session_epoch`` or compare an ``.epoch``
  attribute), or a post-reconfiguration straggler resumes into the new
  world.
- **unchecked-replay**: ``replayable_from`` returns ``None`` when the
  replay buffer no longer holds a frame the service needs — a caller
  that never does an ``is None`` / ``is not None`` check would iterate
  the sentinel or, worse, treat the gap as "nothing to replay" and
  silently skip frames.
"""

import ast

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "wire-safety"

_PICKLE_BASES = {"pickle", "cloudpickle", "_pickler"}


def _function_calls(funcdef):
    """Call nodes lexically in this function (nested defs excluded —
    they are scanned as their own functions)."""
    out = []
    stack = list(funcdef.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _has_epoch_fence(names):
    """Whether the function touches the resume fence: a session_epoch
    call, or a comparison reading an ``.epoch`` attribute (checked by
    the caller over Compare nodes)."""
    return any(t.rsplit(".", 1)[-1] == "session_epoch" for t, _ in names)


def _compares_epoch_attr(funcdef):
    for node in ast.walk(funcdef):
        if isinstance(node, ast.Compare):
            for operand in [node.left] + list(node.comparators):
                for sub in ast.walk(operand):
                    if isinstance(sub, ast.Attribute) \
                            and sub.attr == "epoch":
                        return True
    return False


def _has_none_check(funcdef):
    for node in ast.walk(funcdef):
        if isinstance(node, ast.Compare) \
                and any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
            for operand in [node.left] + list(node.comparators):
                if isinstance(operand, ast.Constant) \
                        and operand.value is None:
                    return True
    return False


def check(project, config):
    findings = []
    allowlist = config.get("wire_pickle_allowlist") or []
    for module in project.modules.values():
        allowlisted = any(module.relpath.endswith(s) for s in allowlist)
        for ctx, _cls, funcdef in model.iter_functions(module):
            calls = _function_calls(funcdef)
            names = [(model.expr_text(c.func) or "", c) for c in calls]

            welcomes = [c for t, c in names
                        if t.rsplit(".", 1)[-1] == "SessionWelcome"]
            if welcomes and not _has_epoch_fence(names) \
                    and not _compares_epoch_attr(funcdef):
                call = welcomes[0]
                if not (module.is_wire_safe_annotated(call.lineno)
                        or module.has_ignore(call.lineno, NAME)):
                    findings.append(Finding(
                        NAME, module.relpath, call.lineno, ctx,
                        "unfenced-resume",
                        "SessionWelcome constructed with no epoch fence "
                        "in the function (no session_epoch call, no "
                        ".epoch comparison) — a post-reconfiguration "
                        "straggler could resume into the new world "
                        "(docs/fault_tolerance.md)"))

            replays = [c for t, c in names
                       if t.rsplit(".", 1)[-1] == "replayable_from"]
            if replays and not _has_none_check(funcdef):
                call = replays[0]
                if not (module.is_wire_safe_annotated(call.lineno)
                        or module.has_ignore(call.lineno, NAME)):
                    findings.append(Finding(
                        NAME, module.relpath, call.lineno, ctx,
                        "unchecked-replay",
                        "replayable_from() result never is-None "
                        "checked — a replay-buffer gap returns the "
                        "None sentinel and must refuse the resume, "
                        "not be treated as an empty replay"))
            has_check = any(
                t.rsplit(".", 1)[-1] in ("check", "check_parts")
                and ("secret" in t or "." not in t)
                for t, _ in names)
            has_sign = any(
                t.rsplit(".", 1)[-1] in ("sign", "sign_parts")
                and ("secret" in t or "." not in t)
                for t, _ in names)
            for text, call in names:
                parts = text.rsplit(".", 1)
                if len(parts) != 2:
                    continue
                base, meth = parts
                if meth in ("loads", "load") \
                        and base.rsplit(".", 1)[-1] in _PICKLE_BASES:
                    if allowlisted or has_check:
                        continue
                    if module.is_wire_safe_annotated(call.lineno) \
                            or module.has_ignore(call.lineno, NAME):
                        continue
                    findings.append(Finding(
                        NAME, module.relpath, call.lineno, ctx,
                        "pickle-loads",
                        f"{text}() outside the HMAC-verified transport "
                        f"with no secret.check in the same function — "
                        f"an unauthenticated peer must never reach the "
                        f"unpickler"))
                elif meth in ("sendall", "sendmsg"):
                    if module.is_wire_safe_annotated(call.lineno) \
                            or module.has_ignore(call.lineno, NAME):
                        continue
                    if not allowlisted:
                        findings.append(Finding(
                            NAME, module.relpath, call.lineno, ctx,
                            "raw-send",
                            f"direct {text}() outside the signed "
                            f"transport — emit frames through "
                            f"network.write_message/write_bulk_message "
                            f"so they are HMAC-signed"))
                    elif not has_sign:
                        findings.append(Finding(
                            NAME, module.relpath, call.lineno, ctx,
                            "unsigned-send",
                            f"frame-emitting {text}() in a function "
                            f"that never signs — every emitted frame "
                            f"must carry an HMAC (annotate "
                            f"'# wire-safe:' if it forwards pre-signed "
                            f"bytes)"))
    return findings
