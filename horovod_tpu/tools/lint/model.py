"""Shared analysis core for the hvd-lint checkers.

One pass builds, for every ``.py`` file under the scanned paths:

- the AST plus a ``{lineno: comment}`` map (tokenize-based, so the
  annotation conventions — ``guarded by self._lock``, ``holds:``,
  ``wakeable:``, ``wire-safe:``, ``hvd-lint: ignore[...]`` — are read
  from real comments, never from string literals);
- an import-alias map (``from horovod_tpu.run.service import network``
  makes ``network.MuxService`` resolvable to the loaded class model);
- a class model per class: attributes assigned in ``__init__`` with the
  synchronization primitive that created them (Lock / RLock / Condition
  / Event / queue.Queue), the ``# guarded by self._X`` declarations,
  whether the class (or any resolvable ancestor) spawns a
  ``threading.Thread``, and per-method ``# holds: self._X``
  caller-holds-the-lock annotations.

Checkers consume this through :class:`Project` plus the CFG-lite
:func:`walk_with_locks` walker, which visits every node of a function
carrying the stack of ``with``-acquired locks lexically active there.
A ``with`` context expression counts as a lock acquisition when it is a
plain name/attribute chain (never a call) whose final component is a
known synchronization attribute of the enclosing class or matches the
naming convention (contains ``lock`` or ``cv``) — ``with sock:`` and
``with open(...)`` never pollute the lock graph.
"""

import ast
import io
import os
import re
import tokenize

_THREADING_LOCK_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Event": "event",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
}

_GUARDED_RE = re.compile(r"guarded by self\.(\w+)")
_HOLDS_RE = re.compile(r"holds:\s*self\.(\w+)")
_IGNORE_RE = re.compile(r"hvd-lint:\s*ignore\[([\w,\- ]+)\]")
_WAKEABLE_RE = re.compile(r"wakeable:")
_WIRE_SAFE_RE = re.compile(r"wire-safe:")


def expr_text(node):
    """Render a Name/Attribute chain ('self._cv', 'network.MuxService');
    None for anything else (calls, subscripts...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_text(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _is_sync_ctor(node):
    """'lock'/'rlock'/'condition'/'event'/'queue' when ``node`` is a
    call to a synchronization-primitive constructor, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = None
    if isinstance(node.func, ast.Attribute):
        name = node.func.attr
    elif isinstance(node.func, ast.Name):
        name = node.func.id
    if name in _THREADING_LOCK_KINDS:
        return _THREADING_LOCK_KINDS[name]
    if name in ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"):
        return "queue"
    return None


def _spawns_thread(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else None)
            if name == "Thread":
                return True
    return False


class ClassModel:
    def __init__(self, module, node):
        self.module = module
        self.node = node
        self.name = node.name
        self.bases = [expr_text(b) for b in node.bases]
        self.methods = {}          # name -> FunctionDef
        self.lock_attrs = {}       # attr -> kind
        self.guarded = {}          # attr -> owning lock attr
        self.holds = {}            # method name -> set of lock attrs
        self.spawns_thread = False

        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = child
                holds = module.scan_holds(child)
                if holds:
                    self.holds[child.name] = holds
        init = self.methods.get("__init__")
        if init is not None:
            self._scan_init(init)
        self.spawns_thread = any(
            _spawns_thread(m) for m in self.methods.values())

    def _scan_init(self, init):
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    kind = _is_sync_ctor(node.value)
                    if kind is not None:
                        self.lock_attrs[target.attr] = kind
                    # the annotation may sit on any line of the (possibly
                    # multi-line) assignment, or on the contiguous block
                    # of PURE comment lines directly above it (an inline
                    # comment of the previous assignment must not leak
                    # onto this one) — same semantics as annotated()
                    parts = [self.module.comment(ln) for ln in
                             range(node.lineno,
                                   (node.end_lineno or node.lineno) + 1)]
                    above = node.lineno - 1
                    while 1 <= above <= len(self.module.lines) \
                            and self.module.lines[above - 1].lstrip() \
                            .startswith("#"):
                        parts.append(self.module.comment(above))
                        above -= 1
                    match = _GUARDED_RE.search(" ".join(parts))
                    if match and match.group(1) != target.attr:
                        self.guarded[target.attr] = match.group(1)


class SourceModule:
    def __init__(self, path, relpath, source):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.comments = self._scan_comments(source)
        self.dotted = relpath[:-3].replace("/", ".").replace("\\", ".")
        self.imports = self._scan_imports()
        self.classes = {n.name: ClassModel(self, n)
                        for n in self.tree.body
                        if isinstance(n, ast.ClassDef)}
        # module-level lock assignments (e.g. _config_lock = Lock())
        self.module_locks = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                kind = _is_sync_ctor(node.value)
                if kind is not None:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            self.module_locks[target.id] = kind

    @staticmethod
    def _scan_comments(source):
        comments = {}
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        return comments

    def _scan_imports(self):
        out = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    out[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
        return out

    def comment(self, lineno):
        return self.comments.get(lineno, "")

    def annotated(self, lineno, regex):
        """True when the line — or the contiguous block of pure comment
        lines directly above it — carries a matching comment:
        annotations routinely head a multi-line explanation of HOW the
        invariant is satisfied."""
        if regex.search(self.comment(lineno)):
            return True
        line = lineno - 1
        while 1 <= line <= len(self.lines) \
                and self.lines[line - 1].lstrip().startswith("#"):
            if regex.search(self.comment(line)):
                return True
            line -= 1
        return False

    def has_ignore(self, lineno, checker):
        # the line itself, or the line above ONLY when it is a pure
        # comment line — an inline ignore on the previous code line
        # must not leak onto the statement below it
        lines = [lineno]
        above = lineno - 1
        if 1 <= above <= len(self.lines) \
                and self.lines[above - 1].lstrip().startswith("#"):
            lines.append(above)
        for line in lines:
            match = _IGNORE_RE.search(self.comment(line))
            if match:
                names = [c.strip() for c in match.group(1).split(",")]
                if checker in names or "all" in names:
                    return True
        return False

    def is_wakeable_annotated(self, lineno):
        return self.annotated(lineno, _WAKEABLE_RE)

    def is_wire_safe_annotated(self, lineno):
        return self.annotated(lineno, _WIRE_SAFE_RE)

    def scan_holds(self, funcdef):
        """# holds: self._x annotations between the def line and the
        first body statement (inclusive of the def line itself)."""
        first = funcdef.body[0].lineno if funcdef.body else funcdef.lineno
        held = set()
        for line in range(funcdef.lineno, first + 1):
            for match in _HOLDS_RE.finditer(self.comment(line)):
                held.add(match.group(1))
        return held


class Project:
    """All loaded modules plus cross-module class resolution."""

    def __init__(self, modules):
        self.modules = modules                    # relpath -> SourceModule
        self._by_dotted = {m.dotted: m for m in modules.values()}

    def find_module(self, suffix):
        """The loaded module whose relpath ends with ``suffix``."""
        for relpath, module in self.modules.items():
            if relpath.endswith(suffix):
                return module
        return None

    def resolve_class(self, module, base_text):
        """ClassModel for a base-class expression seen in ``module``
        ('MuxService' or 'network.MuxService'); None if unresolvable."""
        if base_text is None:
            return None
        parts = base_text.split(".")
        if len(parts) == 1:
            found = module.classes.get(parts[0])
            if found is not None:
                return found
            dotted = module.imports.get(parts[0])
            if dotted and "." in dotted:
                owner, cls = dotted.rsplit(".", 1)
                target = self._by_dotted.get(owner)
                if target:
                    return target.classes.get(cls)
            return None
        alias, cls = parts[0], parts[-1]
        dotted = module.imports.get(alias)
        target = self._by_dotted.get(dotted) if dotted else None
        if target is None:
            # fall back on suffix match ('network' -> .../network.py)
            for mod in self.modules.values():
                if mod.dotted.endswith(f".{alias}") or mod.dotted == alias:
                    target = mod
                    break
        return target.classes.get(cls) if target else None

    def ancestors(self, cls):
        """Resolvable ancestor ClassModels (closest first, cycles cut)."""
        out, queue, seen = [], list(cls.bases), {cls.name}
        while queue:
            base = self.resolve_class(cls.module, queue.pop(0))
            if base is None or base.name in seen:
                continue
            seen.add(base.name)
            out.append(base)
            queue.extend(base.bases)
        return out

    def class_spawns_thread(self, cls):
        return cls.spawns_thread or any(
            a.spawns_thread for a in self.ancestors(cls))

    def class_lock_attrs(self, cls):
        merged = {}
        for ancestor in reversed(self.ancestors(cls)):
            merged.update(ancestor.lock_attrs)
        merged.update(cls.lock_attrs)
        return merged

    def class_guarded(self, cls):
        merged = {}
        for ancestor in reversed(self.ancestors(cls)):
            merged.update(ancestor.guarded)
        merged.update(cls.guarded)
        return merged


class LockRef:
    __slots__ = ("text", "attr", "on_self")

    def __init__(self, text):
        self.text = text
        self.attr = text.rsplit(".", 1)[-1]
        self.on_self = text.startswith("self.")

    def __repr__(self):
        return f"LockRef({self.text})"


def looks_like_lock(text, known_attrs):
    """The with-expression heuristic (module docstring): a known sync
    attribute of the class, or a name matching the lock/cv convention."""
    attr = text.rsplit(".", 1)[-1]
    if attr in known_attrs:
        return known_attrs[attr] not in ("event", "queue")
    low = attr.lower()
    return "lock" in low or low.endswith("_cv") or low == "cv"


def walk_with_locks(funcdef, callback, known_attrs=None):
    """Visit every node of ``funcdef`` (skipping nested function/class
    definitions, which run on other call stacks) calling
    ``callback(node, lock_stack)`` where ``lock_stack`` is the tuple of
    :class:`LockRef` for lexically-enclosing ``with`` lock acquisitions.
    """
    known_attrs = known_attrs or {}

    def visit(node, stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return  # nested defs run on their own call stack
        if isinstance(node, ast.With):
            added = list(stack)
            for item in node.items:
                text = expr_text(item.context_expr)
                if text and looks_like_lock(text, known_attrs):
                    ref = LockRef(text)
                    callback(item.context_expr, tuple(added),
                             acquiring=ref)
                    added.append(ref)
                else:
                    # a non-lock context manager (file, socket,
                    # connect(...)) is ordinary code: visit it so
                    # checkers see calls/accesses inside it
                    visit(item.context_expr, tuple(added))
            for child in node.body:
                visit(child, tuple(added))
            return
        callback(node, stack, acquiring=None)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            visit(child, stack)

    for stmt in funcdef.body:
        visit(stmt, ())


def in_scope(module, suffixes):
    """Module-scope filter: ``suffixes`` is a list of relpath suffixes
    (None = every module, which is what the fixture tests use)."""
    if suffixes is None:
        return True
    return any(module.relpath.endswith(s) for s in suffixes)


def iter_functions(module):
    """(context_name, ClassModel | None, FunctionDef) for every function
    in the module: methods with their class, plus module-level functions
    (including the reference's nested handler factories — nested defs
    are yielded with a dotted context so findings stay addressable)."""
    def walk_body(body, prefix, cls):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}{node.name}"
                yield name, cls, node
                yield from walk_body(node.body, f"{name}.", cls)
            elif isinstance(node, ast.ClassDef):
                inner_cls = module.classes.get(node.name, cls)
                yield from walk_body(node.body, f"{prefix}{node.name}.",
                                     inner_cls)

    yield from walk_body(module.tree.body, "", None)


def load_project(paths, exclude=()):
    """Parse every .py under ``paths`` (files or directories) into a
    :class:`Project`.  ``relpath`` is relative to the deepest common
    root so finding keys are stable however the CLI is invoked."""
    files = []
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            files.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and d not in exclude]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    files.append(os.path.join(dirpath, name))
    modules = {}
    root = _repo_root(files)
    for path in files:
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules[relpath] = SourceModule(path, relpath, source)
        except (OSError, SyntaxError, ValueError):
            continue  # unreadable/unparsable files are not lint input
    return Project(modules)


def _repo_root(files):
    """The repo root: the nearest ancestor of the first scanned file
    that contains the horovod_tpu package (falls back to the common
    prefix) — keys in the checked-in baseline are relative to it."""
    if not files:
        return os.getcwd()
    probe = os.path.dirname(files[0])
    while True:
        if os.path.isdir(os.path.join(probe, "horovod_tpu")) \
                or os.path.isdir(os.path.join(probe, ".git")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return os.path.dirname(files[0])
        probe = parent
