"""hvd-lint: AST-based enforcement of the project's concurrency,
configuration and wire-safety invariants (docs/linting.md).

Public surface: :func:`run_lint` (used by tests/test_lint.py and
``bin/hvd-lint``), :class:`Finding`, and the checker registry.
"""

from horovod_tpu.tools.lint.findings import Finding  # noqa: F401


def run_lint(paths, config=None, checkers=None):
    # lazy: importing the package must not drag argparse/checker deps
    # into runtime imports of horovod_tpu.tools
    from horovod_tpu.tools.lint.cli import run_lint as _run
    return _run(paths, config=config, checkers=checkers)
