"""``bin/hvd-proto`` — the distributed-protocol analysis gate.

Usage::

    bin/hvd-proto horovod_tpu/                 # the tier-1 gate run
    bin/hvd-proto --format json horovod_tpu/   # machine-readable
    bin/hvd-proto --checkers epoch-fencing horovod_tpu/ops/
    bin/hvd-proto --checkers model-check --depth 12 --seed 7 .
    bin/hvd-proto --write-baseline horovod_tpu/   # refresh suppressions

Exit codes: 0 = clean (baselined findings included), 1 = active
findings, 2 = usage error — exact parity with ``bin/hvd-lint``.  The
baseline lives at ``.hvd-proto-baseline.json`` in the repo root; the
tier-1 gate (tests/test_proto.py) keeps it small and justified.
Determinism: the same ``--seed`` and ``--depth`` produce a
byte-identical report (docs/protocol_checking.md).
"""

import argparse
import json
import os
import sys

from horovod_tpu.tools.lint import findings as findings_mod
from horovod_tpu.tools.lint import model
from horovod_tpu.tools.proto.checkers import ALL_CHECKERS

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, ".hvd-proto-baseline.json")

# The project policy: the protocol surfaces each checker encodes.
# epoch-fencing scans the wire-message modules of the reconfigurable
# planes; signature-parity diffs the four signature/cache-key surfaces
# (three Python planes + the native response cache); request-
# exhaustiveness holds every dispatch plane to the shared ops_enum
# vocabularies; collective-divergence walks the rank-conditional code
# of the op layers (docs/protocol_checking.md).
PROJECT_CONFIG = {
    "msg_modules": [
        "ops/tcp_controller.py",
        "ops/tcp_dataplane.py",
        "ops/global_controller.py",
        "run/service/network.py",
    ],
    "parity_surfaces": [
        {"plane": "tcp", "module": "ops/tcp_controller.py",
         "function": "_signature",
         "subjects": ["msg"]},
        {"plane": "python", "module": "ops/python_controller.py",
         "function": "EagerRequest.signature",
         "subjects": ["self"]},
        {"plane": "gmesh", "module": "ops/global_controller.py",
         "function": "MetaCoordinatorService._validate",
         "subjects": ["r", "first"]},
    ],
    "native_signature": os.path.join(REPO_ROOT, "csrc", "hvd",
                                     "core.cc"),
    "native_signature_relpath": "csrc/hvd/core.cc",
    "exhaustive_surfaces": [
        {"plane": "tcp", "module": "ops/tcp_controller.py",
         "enum": "RequestType"},
        {"plane": "python", "module": "ops/python_controller.py",
         "enum": "RequestType"},
        {"plane": "gmesh", "module": "ops/global_controller.py",
         "enum": "RequestType"},
        {"plane": "native-apply", "module": "ops/native_controller.py",
         "enum": "ResponseType"},
    ],
    "enum_module": "common/ops_enum.py",
    "native_dispatch": os.path.join(REPO_ROOT, "csrc", "hvd",
                                    "core.cc"),
    "native_dispatch_relpath": "csrc/hvd/core.cc",
    "divergence_modules": [
        "ops/tcp_controller.py",
        "ops/tcp_dataplane.py",
        "ops/global_controller.py",
        "ops/python_controller.py",
        "ops/native_controller.py",
        "run/service/network.py",
    ],
    "repo_root": REPO_ROOT,
}


def run_proto(paths, config=None, checkers=None, depth=None, seed=None,
              _return_project=False):
    """Programmatic entry: returns the list of findings (pre-baseline).
    ``config=None`` applies the project policy; tests pass their own."""
    project = model.load_project(paths)
    cfg = dict(PROJECT_CONFIG if config is None else config)
    if depth is not None:
        cfg["proto_depth"] = depth
    if seed is not None:
        cfg["proto_seed"] = seed
    out = []
    for name, checker in ALL_CHECKERS.items():
        if checkers is not None and name not in checkers:
            continue
        out.extend(checker.check(project, cfg))
    out.sort(key=lambda f: (f.path, f.line, f.checker, f.detail))
    if _return_project:
        return out, project
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-proto",
        description="Distributed-protocol static analysis and bounded "
                    "model checking for horovod_tpu "
                    "(docs/protocol_checking.md).")
    parser.add_argument("paths", nargs="*",
                        default=[os.path.join(REPO_ROOT, "horovod_tpu")],
                        help="Files or directories to scan "
                             "(default: the horovod_tpu package).")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="Baseline JSON of suppressed finding keys "
                             "(default: .hvd-proto-baseline.json in "
                             "the repo root).")
    parser.add_argument("--no-baseline", action="store_true",
                        help="Report every finding, suppressing "
                             "nothing.")
    parser.add_argument("--write-baseline", action="store_true",
                        help="Rewrite the baseline from the current "
                             "findings (existing justifications are "
                             "kept; new entries get a TODO the gate "
                             "test rejects until justified).")
    parser.add_argument("--checkers", default=None,
                        help="Comma-separated checker subset "
                             f"(available: {', '.join(ALL_CHECKERS)}).")
    parser.add_argument("--depth", type=int, default=None,
                        help="Model-checker exploration bound in steps "
                             "(default: HVD_TPU_PROTO_DEPTH, else "
                             "10).")
    parser.add_argument("--seed", type=int, default=None,
                        help="Exploration tie-break seed; the same "
                             "seed and depth give a byte-identical "
                             "report (default: HVD_TPU_PROTO_SEED, "
                             "else 0).")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text")
    args = parser.parse_args(argv)

    selected = None
    if args.checkers:
        selected = [c.strip() for c in args.checkers.split(",")]
        unknown = [c for c in selected if c not in ALL_CHECKERS]
        if unknown:
            parser.error(f"unknown checker(s): {', '.join(unknown)}")

    all_findings, project = run_proto(args.paths, checkers=selected,
                                      depth=args.depth, seed=args.seed,
                                      _return_project=True)

    baseline = {} if args.no_baseline else \
        findings_mod.load_baseline(args.baseline)
    if args.write_baseline:
        # previous entries this run could not have re-observed — an
        # unselected checker, or a path outside the scan — carry over
        # verbatim: a scoped --write-baseline must never delete other
        # scopes' justifications
        scanned = set(project.modules)

        def out_of_scope(key):
            checker, _, rest = key.partition(":")
            relpath = rest.partition(":")[0]
            if selected is not None and checker not in selected:
                return True
            # model-check and the native planes anchor findings outside
            # the scanned Python module set — always in scope for a
            # full-checker rewrite, carried over for a scoped one
            if checker == "model-check" or relpath.startswith("csrc/"):
                return False
            return relpath not in scanned

        previous = findings_mod.load_baseline(args.baseline)
        findings_mod.write_baseline(args.baseline, all_findings,
                                    previous=previous,
                                    out_of_scope=out_of_scope)
        written = len(findings_mod.load_baseline(args.baseline))
        print(f"wrote {written} suppression(s) to {args.baseline}")
        return 0
    active, suppressed, stale = findings_mod.split_baselined(
        all_findings, baseline)

    if args.format == "json":
        json.dump({
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline_keys": stale,
        }, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for finding in active:
            print(finding.render())
        summary = (f"hvd-proto: {len(active)} finding(s), "
                   f"{len(suppressed)} baselined")
        if stale:
            summary += (f", {len(stale)} stale baseline key(s) — "
                        f"run --write-baseline to prune")
        print(summary)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
