"""The five control-plane protocols as message-passing transition
systems (the ``mc.py`` model contract).

Each model is the *intended* protocol as documented — abort fan-out
(docs/fault_tolerance.md), elastic reconfiguration with epoch fencing
(docs/elastic.md), coordinator leader election (coordinator fail-over),
graceful drain, and the sequence-numbered session/replay layer
(self-healing transport).  The checker proves the documented design
safe within bounds; the seeded-bug fixtures under
``tests/proto_fixtures/`` subclass these models with one transition
broken the way the corresponding real-world bug breaks it, and must be
caught.

States are tuples of ints / frozensets only (hashable, canonical);
action labels follow the fault-spec grammar so counterexamples replay
(see ``mc.to_fault_spec``).
"""


class AbortFanout:
    """Coordinated abort: a worker crash is detected by the coordinator
    liveness monitor, latched as a sticky abort verdict, and pulled by
    every surviving rank over the heartbeat channel.

    Safety: no rank aborts before the coordinator latched the verdict.
    Bounded liveness: once a crash happened, every live rank (the
    coordinator included) eventually learns the abort.
    """

    name = "abort-fanout"
    ns = (2, 3, 4)

    # state: (crashed ranks, coordinator latched abort?, aborted ranks)

    def initial(self, n):
        return (frozenset(), False, frozenset())

    def actions(self, state, n):
        crashed, latched, aborted = state
        out = []
        if not crashed:   # single-crash bound
            for i in range(1, n):
                out.append((f"rank{i}:allreduce:1:crash",
                            (frozenset({i}), latched, aborted)))
        if crashed and not latched:
            out.append(("rank0:heartbeat:2:latch-abort",
                        (crashed, True, aborted)))
        if latched:
            for i in range(n):
                if i in crashed or i in aborted:
                    continue
                out.append((self._deliver_label(i),
                            self._deliver(state, n, i)))
        return out

    def _deliver_label(self, i):
        return f"rank{i}:heartbeat:3:abort"

    def _deliver(self, state, n, i):
        crashed, latched, aborted = state
        return (crashed, latched, aborted | {i})

    def invariant(self, state, n):
        crashed, latched, aborted = state
        if aborted and not latched:
            return "abort-without-verdict"
        return None

    def terminal_check(self, state, n):
        crashed, latched, aborted = state
        if not crashed:
            return None
        live = set(range(n)) - crashed
        if live - aborted:
            return "abort-not-delivered"
        return None


class ElasticReconfig:
    """Elastic reconfiguration with epoch fencing: the coordinator
    advances the world epoch, ranks adopt asynchronously, and every
    delivered collective frame is fenced against the coordinator's
    current epoch — a straggler frame from a torn-down epoch is
    rejected, never applied (docs/elastic.md).

    Safety: no frame is applied whose epoch differs from the
    coordinator epoch at apply time.
    """

    name = "elastic-reconfig"
    ns = (2, 3, 4)

    _MAX_EPOCH = 2

    # state: (coord_epoch, per-rank epochs, sent ranks, inflight
    #         (rank, epoch) frames, stale frame applied?)

    def initial(self, n):
        return (0, (0,) * n, frozenset(), frozenset(), False)

    def actions(self, state, n):
        coord, epochs, sent, inflight, bad = state
        out = []
        for i in range(n):
            if i not in sent:   # one frame per rank, at its own epoch
                out.append((
                    f"rank{i}:send:1:collective-e{epochs[i]}",
                    (coord, epochs, sent | {i},
                     inflight | {(i, epochs[i])}, bad)))
        if coord < self._MAX_EPOCH:
            out.append(("rank0:reconfig:2:advance",
                        (coord + 1, epochs, sent, inflight, bad)))
        for i in range(n):
            if epochs[i] < coord:
                adopted = epochs[:i] + (epochs[i] + 1,) + epochs[i + 1:]
                out.append((f"rank{i}:reconfig:3:adopt",
                            (coord, adopted, sent, inflight, bad)))
        for frame in inflight:
            out.append((self._deliver_label(state, frame),
                        self._deliver(state, n, frame)))
            i, e = frame
            out.append((f"rank{i}:send:4:drop",
                        (coord, epochs, sent, inflight - {frame}, bad)))
        return out

    def _deliver_label(self, state, frame):
        coord = state[0]
        i, e = frame
        verdict = "apply" if e == coord else "reject"
        return f"rank0:recv:5:{verdict}-r{i}e{e}"

    def _deliver(self, state, n, frame):
        coord, epochs, sent, inflight, bad = state
        i, e = frame
        # the fence: stale-epoch frames are rejected, not applied
        return (coord, epochs, sent, inflight - {frame}, bad)

    def invariant(self, state, n):
        if state[4]:
            return "stale-epoch-apply"
        return None

    def terminal_check(self, state, n):
        return None


class LeaderElection:
    """Coordinator fail-over: rank 0 is gone; the survivors race a
    compare-and-swap on the durable election slot.  The CAS is atomic —
    exactly one proposer wins, everyone else adopts the winner.  The
    seated winner cannot crash (its loss starts the *next* election
    instance); one additional survivor crash is in scope.

    Safety: at most one live rank believes itself leader (no
    split-brain).  Bounded liveness: the survivors end up with a live
    leader.
    """

    name = "leader-election"
    ns = (2, 3, 4)

    # state: (cas slot winner | -1, per-rank believed leader (-1 =
    #         undecided; index 0 unused), crashed ranks)

    def initial(self, n):
        return (-1, (-1,) * n, frozenset())

    def actions(self, state, n):
        cas, leaders, crashed = state
        out = []
        for i in range(1, n):
            if i in crashed or leaders[i] >= 0:   # already decided
                continue
            out.extend(self._decide(state, n, i))
        if len(crashed) < 1:
            for i in range(1, n):
                if i in crashed or i == cas:
                    continue
                out.append((f"rank{i}:link:2:crash",
                            (cas, leaders, crashed | {i})))
        return out

    def _decide(self, state, n, i):
        cas, leaders, crashed = state
        if cas == -1:   # atomic CAS: first writer wins
            won = leaders[:i] + (i,) + leaders[i + 1:]
            return [(f"rank{i}:connect:1:cas-win",
                     (i, won, crashed))]
        adopted = leaders[:i] + (cas,) + leaders[i + 1:]
        return [(f"rank{i}:connect:1:adopt", (cas, adopted, crashed))]

    def invariant(self, state, n):
        cas, leaders, crashed = state
        winners = [i for i in range(1, n)
                   if i not in crashed and leaders[i] == i]
        if len(winners) > 1:
            return "split-brain"
        return None

    def terminal_check(self, state, n):
        cas, leaders, crashed = state
        live = [i for i in range(1, n) if i not in crashed]
        if not live:
            return None
        if cas == -1:
            return "no-leader-elected"
        if any(leaders[i] != cas for i in live):
            return "divergent-adoption"
        return None


class GracefulDrain:
    """Graceful drain: a preempted worker announces its departure, the
    coordinator forms a new membership plan excluding it, and every
    surviving rank receives the directive before the old world tears
    down.  The drain channel is the reliable in-order control
    connection, so loss is out of scope (a crash is AbortFanout's job).

    Safety: the draining rank is never part of the new plan.  Bounded
    liveness: every planned survivor receives the directive.
    """

    name = "graceful-drain"
    ns = (2, 3, 4)

    # state: (preempted rank | -1, drain announced?, plan | None,
    #         survivors holding the directive)

    def initial(self, n):
        return (-1, False, None, frozenset())

    def actions(self, state, n):
        preempted, announced, plan, delivered = state
        out = []
        if preempted == -1:
            for i in range(1, n):
                out.append((f"rank{i}:allreduce:1:preempt",
                            (i, announced, plan, delivered)))
        if preempted != -1 and not announced:
            out.append((f"rank{preempted}:send:2:drain",
                        (preempted, True, plan, delivered)))
        if announced and plan is None:
            out.append(("rank0:plan:3:exclude",
                        (preempted, announced,
                         self._plan(state, n), delivered)))
        if plan is not None:
            for i in sorted(plan - delivered):
                out.append((f"rank{i}:recv:4:directive",
                            (preempted, announced, plan,
                             delivered | {i})))
        return out

    def _plan(self, state, n):
        preempted = state[0]
        return frozenset(i for i in range(n) if i != preempted)

    def invariant(self, state, n):
        preempted, announced, plan, delivered = state
        if plan is not None and preempted in plan:
            return "drainer-in-plan"
        return None

    def terminal_check(self, state, n):
        preempted, announced, plan, delivered = state
        if preempted == -1:
            return None
        if plan is None or plan - delivered:
            return "drain-directive-lost"
        return None


class SessionReplay:
    """The sequence-numbered session layer (self-healing transport):
    the sender retains unacked frames, the receiver applies strictly
    in order (duplicates dropped, gaps sever the connection), and a
    reconnect replays the retained tail from the receiver's reported
    high-water mark.  A replay gap (needed frame already evicted)
    refuses the resume — the session escalates to a fresh join rather
    than guess.

    Here ``n`` is the frame count, not a world size.

    Safety: the applied stream is exactly 1..k — contiguous, in order,
    no duplicates (exactly-once delivery).
    """

    name = "session-replay"
    ns = (2, 3, 4)

    # state: (frames sent, retained buffer, inflight frames, applied
    #         stream, receiver high-water mark, acked mark, evictions,
    #         connection drops, severed?, resume refused?)

    def initial(self, n):
        return (0, frozenset(), frozenset(), (), 0, 0, 0, 0, False,
                False)

    def actions(self, state, n):
        (sent, buf, inflight, applied, seen, acked, evicts, drops,
         severed, refused) = state
        out = []
        if refused:
            return out   # session escalated to a fresh join
        if sent < n and not severed:
            seq = sent + 1
            out.append((f"rank0:send:1:frame-{seq}",
                        (seq, buf | {seq}, inflight | {seq}, applied,
                         seen, acked, evicts, drops, severed, refused)))
        if drops < 1 and inflight and not severed:
            out.append(("rank0:link:2:drop",
                        (sent, buf, frozenset(), applied, seen, acked,
                         evicts, drops + 1, True, refused)))
        if not severed:
            for seq in sorted(inflight):
                out.append((f"rank1:recv:3:frame-{seq}",
                            self._deliver(state, n, seq)))
        if not severed and seen > acked:
            out.append((f"rank1:send:4:ack-{seen}",
                        (sent, frozenset(s for s in buf if s > seen),
                         inflight, applied, seen, seen, evicts, drops,
                         severed, refused)))
        if evicts < 1 and buf:
            out.append(("rank0:buffer:5:evict",
                        (sent, buf - {min(buf)}, inflight, applied,
                         seen, acked, evicts + 1, drops, severed,
                         refused)))
        if severed:
            out.append(self._heal(state, n))
        return out

    def _deliver(self, state, n, seq):
        (sent, buf, inflight, applied, seen, acked, evicts, drops,
         severed, refused) = state
        inflight = inflight - {seq}
        if seq <= seen:
            pass                      # duplicate: dropped
        elif seq == seen + 1:
            applied = applied + (seq,)
            seen = seq
        else:                         # gap: sever, await replay
            inflight = frozenset()
            severed = True
        return (sent, buf, inflight, applied, seen, acked, evicts,
                drops, severed, refused)

    def _heal(self, state, n):
        (sent, buf, inflight, applied, seen, acked, evicts, drops,
         severed, refused) = state
        # the receiver reports its high-water mark; the sender replays
        # the retained tail above it — a hole in that tail is a replay
        # gap and the resume is refused (escalate, never guess)
        replay = sorted(s for s in buf if s > seen)
        if replay and replay[0] != seen + 1:
            return ("rank0:connect:6:refuse",
                    (sent, buf, inflight, applied, seen, acked, evicts,
                     drops, severed, True))
        return ("rank0:connect:6:heal",
                (sent, buf, frozenset(replay), applied, seen, acked,
                 evicts, drops, False, refused))

    def invariant(self, state, n):
        applied = state[3]
        if applied != tuple(range(1, len(applied) + 1)):
            return "non-exactly-once-delivery"
        return None

    def terminal_check(self, state, n):
        return None


REAL_MODELS = [
    AbortFanout(),
    ElasticReconfig(),
    LeaderElection(),
    GracefulDrain(),
    SessionReplay(),
]
