"""hvd-proto — distributed-protocol static analysis + bounded model
checking for the control plane (docs/protocol_checking.md).

Two halves behind one CLI (``bin/hvd-proto``), riding hvd-lint's
findings/baseline machinery verbatim:

1. **Protocol-invariant checkers** over the real source
   (``tools/proto/checkers/``): epoch-fencing, signature-parity,
   request-exhaustiveness, collective-divergence.  Each consumes the
   shared AST core (``tools/lint/model.py``) and emits
   :class:`~horovod_tpu.tools.lint.findings.Finding` objects whose keys
   feed the same baseline-suppression workflow as hvd-lint.

2. **A bounded explicit-state model checker** (``tools/proto/mc.py``)
   over the five hand-maintained distributed protocols written as small
   message-passing transition systems (``tools/proto/protocols.py``):
   abort fan-out, elastic reconfiguration with epoch fencing, the
   leader-election CAS, graceful drain, and the sequence-numbered
   session/replay layer.  Exhaustive exploration at N=2..4 with
   crash/loss/reorder events; counterexamples render as
   ``HVD_TPU_FAULT_SPEC``-style schedules (common/faults.py grammar).

Determinism contract (same as hvd-race): the same seed and flags
produce a byte-identical report — ``HVD_TPU_PROTO_SEED`` orders the
exploration frontier, ``HVD_TPU_PROTO_DEPTH`` bounds it.
"""
