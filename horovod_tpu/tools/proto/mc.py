"""Bounded explicit-state model checker for the control-plane protocols.

Each protocol (``protocols.py``) is a message-passing transition system:

    name: str                     # protocol id shown in findings
    ns: tuple[int, ...]           # world sizes / frame counts to explore
    initial(n) -> state           # hashable (tuples / frozensets only)
    actions(state, n) -> [(label, next_state)]
    invariant(state, n) -> None | str    # safety property id on violation
    terminal_check(state, n) -> None | str
        # bounded-liveness property id, asked only on action-free states

States are explored breadth-first up to ``HVD_TPU_PROTO_DEPTH`` steps,
so the first violation found is a minimal-length counterexample.  Action
labels follow the ``HVD_TPU_FAULT_SPEC`` grammar
(``<target>:<point>:<step>:<action>``, docs/fault_injection.md) so a
counterexample trace renders directly as a fault schedule —
``to_fault_spec`` projects the fault-grammar steps (crash / drop /
refuse / preempt) out of a trace for replay on the real runtime.

Determinism contract (mirrors hvd-race): exploration order is fixed by
sorting each state's actions by label and then shuffling with a
``random.Random`` seeded from ``HVD_TPU_PROTO_SEED`` + protocol + n.
BFS still guarantees minimal counterexample length; the seed only
tie-breaks among equal-length traces.  Same seed + same depth ->
byte-identical report.
"""

import inspect
import os
import random

from horovod_tpu.tools.lint.findings import Finding

NAME = "model-check"

DEFAULT_DEPTH = 10
DEFAULT_SEED = 0


def _env_int(name, default):
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


class Violation:
    """A property violation with its minimal counterexample trace."""

    def __init__(self, model, n, prop, trace):
        self.model = model
        self.n = n
        self.prop = prop
        self.trace = tuple(trace)

    def schedule(self):
        return ",".join(self.trace) if self.trace else "<initial-state>"


def to_fault_spec(trace):
    """Project the fault-grammar steps out of a counterexample trace:
    the result is a valid ``HVD_TPU_FAULT_SPEC`` value reproducing the
    environment (crashes, losses, preemptions) the trace needs."""
    faults = []
    for label in trace:
        parts = label.split(":")
        if len(parts) >= 4 and parts[3] in ("crash", "drop", "refuse",
                                            "preempt"):
            faults.append(label)
    return ",".join(faults)


def _trace(visited, state):
    labels = []
    while visited[state] is not None:
        parent, label = visited[state]
        labels.append(label)
        state = parent
    return tuple(reversed(labels))


def check_model(model, n, depth=None, seed=None):
    """Explore ``model`` at world size ``n``; return the first (hence
    minimal) Violation, or None if every reachable state within
    ``depth`` steps satisfies the invariant and every action-free state
    passes the bounded-liveness check."""
    if depth is None:
        depth = _env_int("HVD_TPU_PROTO_DEPTH", DEFAULT_DEPTH)
    if seed is None:
        seed = _env_int("HVD_TPU_PROTO_SEED", DEFAULT_SEED)
    rng = random.Random(f"{seed}:{model.name}:{n}")

    init = model.initial(n)
    visited = {init: None}
    prop = model.invariant(init, n)
    if prop:
        return Violation(model, n, prop, ())

    frontier = [init]
    for _level in range(depth):
        nxt = []
        for state in frontier:
            acts = sorted(model.actions(state, n), key=lambda a: a[0])
            rng.shuffle(acts)
            if not acts:
                prop = model.terminal_check(state, n)
                if prop:
                    return Violation(model, n, prop,
                                     _trace(visited, state))
                continue
            for label, succ in acts:
                if succ in visited:
                    continue
                visited[succ] = (state, label)
                prop = model.invariant(succ, n)
                if prop:
                    return Violation(model, n, prop,
                                     _trace(visited, succ))
                nxt.append(succ)
        frontier = nxt
        if not frontier:
            break
    # action-free states first reached on the last explored level still
    # owe their bounded-liveness check
    for state in frontier:
        if not model.actions(state, n):
            prop = model.terminal_check(state, n)
            if prop:
                return Violation(model, n, prop, _trace(visited, state))
    return None


def _model_anchor(model, repo_root):
    """(relpath, line) of the model class definition, so a violation is
    attributed to the file encoding the buggy protocol."""
    cls = type(model)
    try:
        path = inspect.getsourcefile(cls)
        _src, line = inspect.getsourcelines(cls)
    except (OSError, TypeError):
        return getattr(cls, "__module__", "<model>"), 1
    if repo_root:
        try:
            path = os.path.relpath(path, repo_root)
        except ValueError:
            pass
    return path, line


def check(project, config):
    """Checker entry point (hvd-proto registry contract).  ``config``
    keys: ``models`` (defaults to protocols.REAL_MODELS), ``proto_depth``
    / ``proto_seed`` (default from HVD_TPU_PROTO_DEPTH / _SEED), and
    ``proto_ns`` overriding every model's ``ns``."""
    models = config.get("models")
    if models is None:
        from horovod_tpu.tools.proto import protocols
        models = protocols.REAL_MODELS
    depth = config.get("proto_depth")
    seed = config.get("proto_seed")
    ns_override = config.get("proto_ns")
    repo_root = config.get("repo_root") or os.getcwd()

    findings = []
    for model in models:
        for n in (ns_override or model.ns):
            violation = check_model(model, n, depth=depth, seed=seed)
            if violation is None:
                continue
            path, line = _model_anchor(model, repo_root)
            spec = to_fault_spec(violation.trace)
            findings.append(Finding(
                NAME, path, line, model.name,
                f"{violation.prop}:n={n}",
                f"protocol '{model.name}' violates '{violation.prop}' "
                f"at n={n}; minimal counterexample: "
                f"{violation.schedule()}"
                + (f" (fault schedule: HVD_TPU_FAULT_SPEC={spec})"
                   if spec else "")))
            break   # smallest violating n is the interesting one
    return findings
