"""Checker 1 — epoch fencing.

The elastic-membership contract (docs/elastic.md): after a
reconfiguration the world re-forms at epoch N+1, and a straggler frame
from the torn-down epoch must never act on the new world's state.  Any
wire-message class (the ``*Msg`` naming convention of the tcp, gmesh
and data-plane protocols) that crosses a reconfigurable boundary must
therefore

- carry an epoch field (``epoch`` or ``join_epoch``), AND
- have at least one dispatch site (an ``isinstance(req, XMsg)`` branch,
  or the handler method it delegates to) compare that field against the
  service's current epoch,

or be annotated ``# epoch-exempt: <why>`` at the class definition for
messages that are epoch-agnostic by design (responses riding the fenced
request's connection, the liveness/abort channel, messages that can
only reach a service through an epoch-suffixed rendezvous scope).

Findings:

- **missing-epoch**: a ``*Msg`` class with no epoch field and no
  exemption annotation;
- **no-dispatch-check**: an epoch-carrying class no scanned module ever
  dispatches on (dead fence — nothing reads the field);
- **unfenced-dispatch**: an epoch-carrying class whose dispatch sites
  never compare the field (the fence exists on the wire but not in the
  code).
"""

import ast
import re

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "epoch-fencing"

_EPOCH_FIELDS = ("epoch", "join_epoch")
_EXEMPT_RE = re.compile(r"epoch-exempt:")


def _epoch_field(cls):
    """The epoch attribute a message class carries, or None."""
    for node in cls.node.body:
        if isinstance(node, ast.Assign):  # __slots__ tuple
            for target in node.targets:
                if isinstance(target, ast.Name) \
                        and target.id == "__slots__":
                    for const in ast.walk(node.value):
                        if isinstance(const, ast.Constant) \
                                and const.value in _EPOCH_FIELDS:
                            return const.value
    init = cls.methods.get("__init__")
    if init is not None:
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                            and target.attr in _EPOCH_FIELDS):
                        return target.attr
    # dataclass-style annotated field
    for node in cls.node.body:
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id in _EPOCH_FIELDS:
            return node.target.id
    return None


def _compares_epoch(funcdef):
    """Whether the function fences: a comparison whose operand reads an
    epoch field — ``req.epoch != self._epoch``, ``msg.join_epoch ==
    self._join_epoch``, or the pre-field-tolerant ``getattr(req,
    "epoch", 0) != ...`` spelling."""
    def reads_epoch(node):
        if isinstance(node, ast.Attribute) and node.attr in _EPOCH_FIELDS:
            return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "getattr" \
                and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and node.args[1].value in _EPOCH_FIELDS:
            return True
        return False

    for node in ast.walk(funcdef):
        if isinstance(node, ast.Compare):
            for operand in [node.left] + list(node.comparators):
                for sub in ast.walk(operand):
                    if reads_epoch(sub):
                        return True
    return False


def _dispatch_sites(project, cls_name):
    """(module, context, funcdef) for every function containing an
    ``isinstance(x, cls_name)`` test (alias-qualified spellings
    included)."""
    out = []
    for module in project.modules.values():
        for ctx, owner, funcdef in model.iter_functions(module):
            for node in ast.walk(funcdef):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "isinstance"
                        and len(node.args) == 2):
                    continue
                targets = [node.args[1]]
                if isinstance(node.args[1], ast.Tuple):
                    targets = list(node.args[1].elts)
                for target in targets:
                    text = model.expr_text(target) or ""
                    if text.rsplit(".", 1)[-1] == cls_name:
                        out.append((module, ctx, owner, funcdef))
                        break
                else:
                    continue
                break
    return out


def _delegates(module, owner, funcdef):
    """The handler methods a dispatch function hands the message to:
    ``self.<method>(...)`` calls resolved in the same class (fences
    routinely live in the per-message ``_handle_x`` delegate, one hop
    from the ``isinstance`` chain)."""
    out = []
    if owner is None:
        return out
    for node in ast.walk(funcdef):
        if isinstance(node, ast.Call):
            text = model.expr_text(node.func) or ""
            if text.startswith("self."):
                method = owner.methods.get(text[len("self."):])
                if method is not None and method is not funcdef:
                    out.append(method)
    return out


def check(project, config):
    findings = []
    scope = config.get("msg_modules")
    for module in project.modules.values():
        if not model.in_scope(module, scope):
            continue
        for cls in module.classes.values():
            if not cls.name.endswith("Msg"):
                continue
            line = cls.node.lineno
            if module.annotated(line, _EXEMPT_RE):
                continue
            field = _epoch_field(cls)
            if field is None:
                findings.append(Finding(
                    NAME, module.relpath, line, cls.name,
                    "missing-epoch",
                    f"wire message {cls.name} carries no epoch field "
                    f"and no '# epoch-exempt:' annotation — a straggler "
                    f"frame from a torn-down epoch could act on the "
                    f"re-formed world (docs/elastic.md)"))
                continue
            sites = _dispatch_sites(project, cls.name)
            if not sites:
                findings.append(Finding(
                    NAME, module.relpath, line, cls.name,
                    "no-dispatch-check",
                    f"{cls.name}.{field} is never read at a dispatch "
                    f"site — no scanned module isinstance-dispatches "
                    f"this message, so the fence field is dead"))
                continue
            fenced = False
            for site_module, _ctx, owner, funcdef in sites:
                candidates = [funcdef] + _delegates(site_module, owner,
                                                   funcdef)
                if any(_compares_epoch(f) for f in candidates):
                    fenced = True
                    break
            if not fenced:
                findings.append(Finding(
                    NAME, module.relpath, line, cls.name,
                    "unfenced-dispatch",
                    f"{cls.name} carries '{field}' but no dispatch "
                    f"site ever compares it against the service's "
                    f"current epoch — the fence exists on the wire but "
                    f"not in the code"))
    return findings
