"""Checker 4 — collective divergence.

The classic cross-rank deadlock: a collective called under a
rank-conditional branch with no matching collective on the other arm —
rank 0 enters the allreduce, every other rank skips it, and the world
hangs at the negotiation barrier until the stall detector aborts the
job.  Statically: for every ``if`` whose test reads a rank (``rank ==
0``, ``hvd.rank() != root``, ``self._rank``...), the multiset of
collective invocations must match between the two arms.

A deliberate asymmetry (the coordinator-side bootstrap that only rank 0
runs BEFORE the world exists, a broadcast-from-root helper where the
non-root arm receives through the same collective) is annotated
``# divergence-ok: <why>`` on the ``if`` line (or the comment block
above it).

Uses the lint core's CFG-lite walk: nested function definitions run on
other call stacks and do not count as "the other arm executing the
collective".
"""

import ast
import re

from horovod_tpu.tools.lint import model
from horovod_tpu.tools.lint.findings import Finding

NAME = "collective-divergence"

_COLLECTIVES = {
    "allreduce", "allgather", "broadcast", "alltoall", "adasum",
    "reduce_scatter", "grouped_allreduce", "allreduce_async",
    "allgather_async", "broadcast_async", "alltoall_async",
    "reduce_scatter_async", "barrier", "join",
}
_OK_RE = re.compile(r"divergence-ok:")


def _reads_rank(test):
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and "rank" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) \
                and "rank" in node.attr.lower():
            return True
    return False


def _collectives_in(stmts):
    """Collective callee tails invoked in a statement list (nested defs
    excluded — they run on other call stacks)."""
    out = []
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            text = model.expr_text(node.func)
            if text is not None:
                tail = text.rsplit(".", 1)[-1]
                if tail in _COLLECTIVES:
                    out.append(tail)
        stack.extend(ast.iter_child_nodes(node))
    return out


def check(project, config):
    findings = []
    scope = config.get("divergence_modules")
    for module in project.modules.values():
        if not model.in_scope(module, scope):
            continue
        for ctx, _cls, funcdef in model.iter_functions(module):
            for node in ast.walk(funcdef):
                if not isinstance(node, ast.If):
                    continue
                if not _reads_rank(node.test):
                    continue
                if module.annotated(node.lineno, _OK_RE) \
                        or module.has_ignore(node.lineno, NAME):
                    continue
                body = _collectives_in(node.body)
                orelse = _collectives_in(node.orelse)
                only_body = sorted(set(body) - set(orelse))
                only_else = sorted(set(orelse) - set(body))
                for name in only_body + only_else:
                    arm = "if" if name in only_body else "else"
                    other = "else" if arm == "if" else "if"
                    findings.append(Finding(
                        NAME, module.relpath, node.lineno, ctx,
                        f"{name}:{arm}-arm",
                        f"collective {name}() runs only on the {arm} "
                        f"arm of a rank-conditional branch — ranks "
                        f"taking the {other} arm never enter it and "
                        f"the world deadlocks at the negotiation "
                        f"barrier (annotate '# divergence-ok: <why>' "
                        f"for deliberate asymmetry)"))
    return findings
