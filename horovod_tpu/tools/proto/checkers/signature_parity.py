"""Checker 2 — signature parity.

Every controller plane keys its response cache / request validation /
fusion buckets on "everything that must agree across ranks": the tcp
coordinator's ``_signature``, the in-process ``EagerRequest.signature``,
the gmesh coordinator's ``_validate`` metadata, and the native C++
``ResponseCache``.  History shows fields get added to one plane and
missed on the others (schedule, group id and compression each arrived
that way) — a miss means a request that must NOT validate against a
cached round silently does on one plane only.

This checker extracts the field set each plane's surface actually reads
(attribute accesses on the request object, ``getattr`` spellings
included; ``sig.X == req.X`` comparisons for the C++ cache), normalizes
naming differences (``prescale_factor`` -> ``prescale``, ``type`` ->
``req_type``, ``tensor`` -> shape+dtype), and diffs each plane against
the union.  A field a plane deliberately lacks (the tcp transport-local
``ring`` flag; wire knobs the native in-process plane resolves before
dispatch) is exempted with a ``# sig-exempt: <field>[, <field>...] —
<why>`` annotation inside that plane's surface function (``//
sig-exempt:`` in the C++ source).

Finding detail: ``<plane>:<field>`` — the plane that is missing the
field.
"""

import ast
import os
import re

from horovod_tpu.tools.lint.findings import Finding

NAME = "signature-parity"

# naming differences between planes, folded to one vocabulary
_ALIASES = {
    "prescale_factor": "prescale",
    "postscale_factor": "postscale",
    "type": "req_type",
}
# request attributes that are identity/bookkeeping, not signature
# material (``name`` keys the cache slot itself; ``dims0`` is allgather
# shape plumbing already covered by ``shape``; ``epoch`` is the fencing
# checker's domain)
_IGNORE = {"name", "rank", "ranks", "error", "dims0", "payload", "sig",
           "epoch", "handle", "req_id", "bit"}
# reading ``self.tensor`` derives both wire facts the other planes read
# directly
_EXPAND = {"tensor": ("shape", "dtype")}

_EXEMPT_RE = re.compile(
    r"sig-exempt:\s*([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")
_CXX_PAIR_RE = re.compile(r"sig\.(\w+)\s*==\s*req\.(\w+)")


def _normalize(fields):
    out = set()
    for field in fields:
        field = _ALIASES.get(field, field)
        if field in _EXPAND:
            out.update(_EXPAND[field])
        elif field not in _IGNORE:
            out.add(field)
    return out


def _find_function(module, dotted):
    """('Class.method' | 'func') -> FunctionDef in ``module``."""
    if "." in dotted:
        cls_name, meth = dotted.split(".", 1)
        cls = module.classes.get(cls_name)
        return cls.methods.get(meth) if cls else None
    for node in module.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == dotted:
            return node
    return None


def _read_fields(funcdef, subjects):
    """Attribute names the function reads off its request subject(s)."""
    fields = set()
    for node in ast.walk(funcdef):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in subjects:
            fields.add(node.attr)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "getattr" \
                and len(node.args) >= 2 \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in subjects \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            fields.add(node.args[1].value)
    return fields


def _exempt_fields(module, funcdef):
    """Fields named by sig-exempt annotations anywhere in the surface
    function (or the comment block directly above its def line)."""
    out = set()
    lines = list(range(funcdef.lineno,
                       (funcdef.end_lineno or funcdef.lineno) + 1))
    above = funcdef.lineno - 1
    while 1 <= above <= len(module.lines) \
            and module.lines[above - 1].lstrip().startswith("#"):
        lines.append(above)
        above -= 1
    for line in lines:
        match = _EXEMPT_RE.search(module.comment(line))
        if match:
            out.update(f.strip() for f in match.group(1).split(","))
    return out


def _native_plane(path):
    """(fields, exempt, anchor_line) from the C++ response-cache source:
    the ``sig.X == req.X`` comparisons of ``ResponseCache::Matches`` are
    the native plane's signature surface."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    fields = set()
    for _sig_field, req_field in _CXX_PAIR_RE.findall(text):
        fields.add(req_field)
    exempt = set()
    for match in _EXEMPT_RE.finditer(text):
        exempt.update(f.strip() for f in match.group(1).split(","))
    anchor = 1
    for lineno, line in enumerate(text.splitlines(), 1):
        if "::Matches" in line:
            anchor = lineno
            break
    return _normalize(fields), exempt, anchor


def check(project, config):
    surfaces = config.get("parity_surfaces") or []
    planes = []   # (plane, relpath, line, context, fields, exempt)
    for spec in surfaces:
        module = project.find_module(spec["module"])
        if module is None:
            continue
        funcdef = _find_function(module, spec["function"])
        if funcdef is None:
            continue
        fields = _normalize(_read_fields(funcdef, set(spec["subjects"])))
        exempt = _exempt_fields(module, funcdef)
        planes.append((spec["plane"], module.relpath, funcdef.lineno,
                       spec["function"], fields, exempt))

    native = config.get("native_signature")
    if native and os.path.isfile(native):
        fields, exempt, anchor = _native_plane(native)
        rel = config.get("native_signature_relpath") or \
            os.path.basename(native)
        planes.append(("native", rel, anchor, "ResponseCache::Matches",
                       fields, exempt))

    if len(planes) < 2:
        return []   # nothing to diff against

    universe = set()
    for _plane, _path, _line, _ctx, fields, _exempt in planes:
        universe |= fields

    findings = []
    for plane, path, line, ctx, fields, exempt in planes:
        for field in sorted(universe - fields - exempt):
            others = sorted(p for p, *_rest in planes
                            if p != plane and field in _rest[3])
            findings.append(Finding(
                NAME, path, line, ctx, f"{plane}:{field}",
                f"signature field '{field}' (present on plane(s) "
                f"{', '.join(others) or 'other'}) is missing from the "
                f"{plane} plane's signature surface — a request "
                f"differing only in '{field}' would falsely validate "
                f"or cache-hit there (annotate '# sig-exempt: {field} "
                f"— <why>' if the plane cannot carry it)"))
    return findings
