"""Checker 3 — request exhaustiveness.

Every controller plane dispatches on the shared ``ops_enum``
vocabularies (``RequestType`` on the request path, ``ResponseType`` on
the apply path).  A new member added to the enum but not to one plane's
dispatch is a silent drop — the collective hangs on exactly one
controller while the others negotiate it fine (REDUCE_SCATTER's rollout
is the cautionary tale).

For each configured surface this checker collects every
``<Enum>.<MEMBER>`` reference in the dispatch module and diffs it
against the enum's declared members.  A member a plane deliberately
routes elsewhere (JOIN travels as ``JoinMsg`` / joined-rank reports on
every plane, never through the collective dispatch) is exempted with a
``# req-exempt: <MEMBER>[, <MEMBER>...] — <why>`` annotation anywhere
in the module (``// req-exempt:`` in the C++ source, whose
``EnumType::kCamelCase`` spellings are folded to the Python member
names).

Finding detail: ``<plane>:<Enum>.<MEMBER>``.
"""

import ast
import os
import re

from horovod_tpu.tools.lint.findings import Finding

NAME = "request-exhaustiveness"

_EXEMPT_RE = re.compile(
    r"req-exempt:\s*([A-Z0-9_]+(?:\s*,\s*[A-Z0-9_]+)*)")
_CXX_MEMBER_RE = re.compile(r"(RequestType|ResponseType)::k([A-Za-z]+)")


def _enum_members(project, config, enum_name):
    """Declared member names of ``enum_name`` from the configured enum
    module (the first loaded module defining a class of that name when
    unconfigured — the fixture path)."""
    suffix = config.get("enum_module")
    modules = ([project.find_module(suffix)] if suffix
               else list(project.modules.values()))
    for module in modules:
        if module is None:
            continue
        cls = module.classes.get(enum_name)
        if cls is None:
            continue
        members = []
        for node in cls.node.body:
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name) \
                            and not target.id.startswith("_"):
                        members.append(target.id)
        return members
    return []


def _referenced(module, enum_name):
    out = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == enum_name:
            out.add(node.attr)
    return out


def _exempt(source_text):
    out = set()
    for match in _EXEMPT_RE.finditer(source_text):
        out.update(m.strip() for m in match.group(1).split(","))
    return out


def _camel_to_member(name):
    """kReduceScatter -> REDUCE_SCATTER."""
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).upper()


def check(project, config):
    findings = []
    for spec in config.get("exhaustive_surfaces") or []:
        module = project.find_module(spec["module"])
        if module is None:
            continue
        enum_name = spec["enum"]
        members = _enum_members(project, config, enum_name)
        if not members:
            continue
        seen = _referenced(module, enum_name)
        exempt = _exempt(module.source)
        for member in members:
            if member in seen or member in exempt:
                continue
            findings.append(Finding(
                NAME, module.relpath, 1, "<module>",
                f"{spec['plane']}:{enum_name}.{member}",
                f"{enum_name}.{member} is never referenced in the "
                f"{spec['plane']} plane's dispatch — a request of that "
                f"type would be silently dropped there (annotate "
                f"'# req-exempt: {member} — <why>' if it is routed "
                f"through a dedicated message instead)"))

    native = config.get("native_dispatch")
    if native and os.path.isfile(native):
        with open(native, encoding="utf-8") as f:
            text = f.read()
        exempt = _exempt(text)
        seen = {}
        for enum_name, camel in _CXX_MEMBER_RE.findall(text):
            seen.setdefault(enum_name, set()).add(_camel_to_member(camel))
        rel = config.get("native_dispatch_relpath") or \
            os.path.basename(native)
        for enum_name in ("RequestType", "ResponseType"):
            members = _enum_members(project, config, enum_name)
            for member in members:
                if member in seen.get(enum_name, set()) \
                        or member in exempt:
                    continue
                findings.append(Finding(
                    NAME, rel, 1, "<module>",
                    f"native:{enum_name}.{member}",
                    f"{enum_name}::k{member.title().replace('_', '')} "
                    f"is never referenced in the native dispatch — a "
                    f"request of that type would be silently dropped "
                    f"(annotate '// req-exempt: {member} — <why>' if "
                    f"routed elsewhere)"))
    return findings
