"""hvd-proto checker registry.  Same contract as the hvd-lint registry
(``tools/lint/checkers``): every checker module exposes

    NAME: str                      # the id used in annotations/--checkers
    check(project, config) -> [Finding]

``config`` keys (all optional — a missing key means the fixture-test
default of "every loaded module"; the project policy in ``cli.py``
narrows each checker to the protocol surfaces it encodes):

- ``msg_modules``: relpath suffixes scanned for ``*Msg`` wire classes
  (epoch-fencing)
- ``parity_surfaces``: the per-plane signature/cache-key extraction
  functions (signature-parity); ``native_signature``: the C++ response
  cache source diffed alongside them
- ``exhaustive_surfaces``: per-plane dispatch modules and the enum each
  must cover (request-exhaustiveness); ``enum_module``: where the enum
  classes are defined; ``native_dispatch``: the C++ dispatch source
- ``divergence_modules``: relpath suffixes scanned for rank-conditional
  collective divergence
- ``proto_depth`` / ``proto_seed`` / ``proto_ns``: model-checker bounds
  (model-check)
"""

from horovod_tpu.tools.proto.checkers import (
    collective_divergence,
    epoch_fencing,
    request_exhaustiveness,
    signature_parity,
)
from horovod_tpu.tools.proto import mc

ALL_CHECKERS = {
    epoch_fencing.NAME: epoch_fencing,
    signature_parity.NAME: signature_parity,
    request_exhaustiveness.NAME: request_exhaustiveness,
    collective_divergence.NAME: collective_divergence,
    mc.NAME: mc,
}
