"""Fuzzing engine: the deterministic mutate/execute/observe loop plus
the harness pieces every target shares (in-memory sockets, the
verify-before-unpickle probe, the branch-coverage tracer).

Determinism is load-bearing (docs/fuzzing.md): per-target RNGs are
seeded from ``crc32(target_name) ^ seed`` — never ``hash()``, which is
salted per process — every iteration draws only from that RNG, and
finding messages are scrubbed of addresses/paths/ports, so the same
seed + iters give a byte-identical run summary across processes.
"""

import base64
import json
import os
import random
import re
import sys
import zlib

from horovod_tpu.tools.lint.findings import Finding

# corpus growth bound: coverage-steered additions stop here so a run's
# memory stays flat and the summary's corpus count is meaningful
MAX_CORPUS = 256

# an execution that reads more than this off a fake socket in one
# request has trusted a length field it should have bounds-checked
ALLOC_CAP = 1 << 22


# ------------------------------------------------------------ fake sockets
class FakeSock:
    """In-memory socket serving a fixed byte buffer to ``recv`` /
    ``recv_into`` and capturing writes — parser targets execute
    syscall-free, which keeps 2000-iteration runs fast and the engine
    deterministic.  ``max_requested`` records the largest single read
    request: a parser asking for more than :data:`ALLOC_CAP` at once
    trusted an unchecked length field (the unbounded-allocation
    oracle)."""

    def __init__(self, data=b""):
        self._data = memoryview(bytes(data))
        self._pos = 0
        self.sent = bytearray()
        self.max_requested = 0
        self._timeout = None

    def recv(self, n):
        self.max_requested = max(self.max_requested, n)
        chunk = self._data[self._pos:self._pos + n]
        self._pos += len(chunk)
        return bytes(chunk)

    def recv_into(self, view, n=0):
        n = n or len(view)
        self.max_requested = max(self.max_requested, n)
        chunk = self._data[self._pos:self._pos + n]
        view[:len(chunk)] = chunk
        self._pos += len(chunk)
        return len(chunk)

    def sendall(self, data):
        self.sent += bytes(data)

    def sendmsg(self, buffers):
        total = 0
        for b in buffers:
            b = memoryview(b).cast("B")
            self.sent += bytes(b)
            total += b.nbytes
        return total

    def settimeout(self, value):
        self._timeout = value

    def fileno(self):
        return -1   # reads as "no live fd" (session eviction checks this)

    def gettimeout(self):
        return self._timeout

    def close(self):
        pass


def capture_frame(write, *args, **kwargs):
    """Run a frame-writing function against a capture sock and return
    the exact bytes it would put on the wire."""
    sock = FakeSock()
    write(sock, *args, **kwargs)
    return bytes(sock.sent)


# ------------------------------------------------- verify-before-unpickle
class PickleProbe:
    """Context manager asserting the transport's central security
    invariant while a parser runs: ``pickle.loads`` is reached only
    AFTER an HMAC verification returned True.  Patches the ``pickle``
    and ``secret`` references inside ``run/service/network.py`` (the
    only untrusted-bytes unpickler) for the duration; single-threaded
    targets only."""

    def __init__(self):
        from horovod_tpu.run.service import network
        self._network = network
        self.violation = None
        self._verified = False

    def __enter__(self):
        net, probe = self._network, self
        real_pickle, real_secret = net.pickle, net.secret

        class _Pickle:
            dumps = staticmethod(real_pickle.dumps)

            @staticmethod
            def loads(data):
                if not probe._verified:
                    probe.violation = "unpickle-before-verify"
                return real_pickle.loads(data)

        class _Secret:
            DIGEST_LEN = real_secret.DIGEST_LEN
            sign = staticmethod(real_secret.sign)
            sign_parts = staticmethod(real_secret.sign_parts)
            make_secret_key = staticmethod(real_secret.make_secret_key)

            @staticmethod
            def check(key, payload, digest):
                ok = real_secret.check(key, payload, digest)
                probe._verified = probe._verified or ok
                return ok

            @staticmethod
            def check_parts(key, digest, *parts):
                ok = real_secret.check_parts(key, digest, *parts)
                probe._verified = probe._verified or ok
                return ok

        self._saved = (real_pickle, real_secret)
        net.pickle, net.secret = _Pickle, _Secret
        return self

    def __exit__(self, *exc):
        self._network.pickle, self._network.secret = self._saved
        return False


# ------------------------------------------------------- coverage tracing
class ArcTracer:
    """Line-arc coverage on a fixed file set via ``sys.settrace``:
    records ``(code_name, prev_line, line)`` triples, the branch-ish
    signal that steers mutation (a mutant reaching a new arc joins the
    corpus).  Single-threaded executions only — settrace is
    per-thread, which is exactly the scope the deterministic targets
    need."""

    def __init__(self, files):
        self._files = {os.path.abspath(f) for f in files}
        self.arcs = set()
        self._prev = {}

    def _local(self, frame, event, arg):
        if event == "line":
            key = id(frame)
            self.arcs.add((frame.f_code.co_name,
                           self._prev.get(key, 0), frame.f_lineno))
            self._prev[key] = frame.f_lineno
        elif event == "return":
            self._prev.pop(id(frame), None)
        return self._local

    def _global(self, frame, event, arg):
        if event == "call" and frame.f_code.co_filename in self._files:
            return self._local
        return None

    def run(self, fn):
        """Execute ``fn()`` under tracing; returns (result, new_arc_count)."""
        before = len(self.arcs)
        old = sys.gettrace()
        sys.settrace(self._global)
        try:
            result = fn()
        finally:
            sys.settrace(old)
            self._prev.clear()
        return result, len(self.arcs) - before


# ----------------------------------------------------------- sanitization
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
_PATH_RE = re.compile(r"(/[\w.\-]+)+")
_PORT_RE = re.compile(r"port \d+|:\d{4,5}\b")


def sanitize(text):
    """Strip the nondeterministic parts of an exception message —
    object addresses, tmp paths, ephemeral ports — so a finding's text
    is byte-identical across runs and processes."""
    text = str(text)
    text = _ADDR_RE.sub("0x…", text)
    text = _PATH_RE.sub("<path>", text)
    text = _PORT_RE.sub("<port>", text)
    return text[:200]


# ------------------------------------------------------------ target base
class FuzzTarget:
    """One untrusted-input parser under test.

    Subclasses define ``name``/``path``, produce the seed corpus
    (valid, structure-correct inputs), a structure-aware ``mutate``,
    and ``execute`` returning ``None`` for an in-contract outcome
    (typed rejection or success) or ``(detail, message)`` for an
    invariant violation.  ``trace_files`` scopes the coverage tracer;
    empty disables steering (the threaded service leg must stay
    untraced)."""

    name = ""
    path = ""            # repo-relative module findings anchor to
    trace_files = ()

    def setup(self):
        """Build fixtures; returns the seed corpus (list of entries)."""
        raise NotImplementedError

    def teardown(self):
        pass

    def mutate(self, rng, entry):
        raise NotImplementedError

    def execute(self, entry):
        raise NotImplementedError

    # corpus entries are JSON files; bytes entries travel base64
    def encode_entry(self, entry):
        if isinstance(entry, bytes):
            return {"encoding": "base64",
                    "data": base64.b64encode(entry).decode()}
        if isinstance(entry, str):
            return {"encoding": "text", "data": entry}
        return {"encoding": "json", "data": entry}

    def decode_entry(self, blob):
        if blob["encoding"] == "base64":
            return base64.b64decode(blob["data"])
        return blob["data"]


def guard_execute(target, entry):
    """Run one input through the target's parser, converting the
    never-process-death oracle into a finding: SystemExit or any
    BaseException escaping a parser is a violation regardless of the
    target's own allowed-exception policy."""
    try:
        return target.execute(entry)
    except SystemExit:
        return ("process-exit", "parser raised SystemExit on fuzzed input")
    except KeyboardInterrupt:
        raise
    except BaseException as exc:  # noqa: BLE001 — the oracle itself
        return (f"engine-escape:{type(exc).__name__}",
                f"exception escaped the target harness: "
                f"{type(exc).__name__}: {sanitize(exc)}")


# -------------------------------------------------------------- run loop
def target_rng(name, seed):
    return random.Random(zlib.crc32(name.encode()) ^ (seed & 0xFFFFFFFF))


def run_target(target, seed, iters):
    """The deterministic fuzz loop for one target: execute the seed
    corpus, then ``iters`` mutants of rng-chosen corpus entries; a
    mutant that reaches a new coverage arc joins the corpus (bounded at
    :data:`MAX_CORPUS`).  Returns ``(stats, findings)`` — findings are
    deduplicated by detail so the summary doesn't scale with how often
    one bug fires."""
    corpus = list(target.setup())
    try:
        tracer = ArcTracer(target.trace_files) if target.trace_files \
            else None
        rng = target_rng(target.name, seed)
        seen = {}
        corpus0 = len(corpus)

        def observe(entry):
            if tracer is not None:
                violation, new_arcs = tracer.run(
                    lambda: guard_execute(target, entry))
            else:
                violation, new_arcs = guard_execute(target, entry), 0
            if violation is not None:
                detail, message = violation
                if detail not in seen:
                    seen[detail] = Finding(
                        checker=f"fuzz-{target.name}", path=target.path,
                        line=0, context="<fuzz>", detail=detail,
                        message=message)
            return new_arcs

        for entry in list(corpus):
            observe(entry)
        for _ in range(max(0, iters)):
            base = corpus[rng.randrange(len(corpus))]
            mutant = target.mutate(rng, base)
            if observe(mutant) and len(corpus) < MAX_CORPUS:
                corpus.append(mutant)
    finally:
        target.teardown()
    stats = {"target": target.name, "iters": max(0, iters),
             "corpus_seed": corpus0, "corpus": len(corpus),
             "arcs": len(tracer.arcs) if tracer is not None else 0,
             "findings": len(seen)}
    return stats, [seen[k] for k in sorted(seen)]


# --------------------------------------------------------- corpus replay
def load_corpus_entries(corpus_dir):
    """``[(relname, target_name, entry_blob, note)]`` sorted by file
    name — the distilled regressions under ``tests/fuzz_corpus/``."""
    out = []
    for root, _dirs, names in sorted(os.walk(corpus_dir)):
        for name in sorted(names):
            if not name.endswith(".json"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                blob = json.load(f)
            out.append((os.path.relpath(path, corpus_dir),
                        blob["target"], blob, blob.get("note", "")))
    return out


def replay_corpus(corpus_dir, targets):
    """Re-run every distilled corpus entry through its target's oracle.
    Returns ``(count, findings)`` — a finding here means a previously
    fixed parser bug regressed."""
    by_name = {t.name: t for t in targets}
    findings = []
    count = 0
    entries = load_corpus_entries(corpus_dir)
    needed = {target_name for _, target_name, _, _ in entries}
    live = {}
    for name in sorted(needed):
        if name in by_name:
            live[name] = by_name[name]
            live[name].setup()
    try:
        for relname, target_name, blob, note in entries:
            target = live.get(target_name)
            if target is None:
                findings.append(Finding(
                    checker="fuzz-corpus", path=f"tests/fuzz_corpus/{relname}",
                    line=0, context="<corpus>",
                    detail=f"unknown-target:{target_name}",
                    message=f"corpus entry names unknown target "
                            f"{target_name!r}"))
                continue
            count += 1
            violation = guard_execute(target,
                                      target.decode_entry(blob))
            if violation is not None:
                detail, message = violation
                findings.append(Finding(
                    checker=f"fuzz-{target_name}", path=target.path,
                    line=0, context="<corpus>",
                    detail=f"{relname}:{detail}",
                    message=f"corpus regression ({note or relname}): "
                            f"{message}"))
    finally:
        for target in live.values():
            target.teardown()
    return count, findings
