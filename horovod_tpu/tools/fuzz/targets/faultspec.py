"""Fuzz target 4: the ``HVD_TPU_FAULT_SPEC`` grammar
(``common/faults.py``).

Oracle: ``parse_fault_spec`` either returns a spec list or raises
``ValueError`` naming the offending fragment — never any other
exception (a typo'd chaos spec must fail the job at init with a
readable message).  Accepted specs must additionally round-trip:
``repr(spec)`` is itself a valid spec parsing to the same repr, so what
hvd-chaos logs can be pasted back into the env var."""

from horovod_tpu.common import faults
from horovod_tpu.tools.fuzz import engine

TARGETS = ("*", "rank0", "rank1", "rank12", "rank-1", "rank", "rankx",
           "Rank1", "node1", "")
POINTS = ("allreduce", "broadcast", "allgather", "alltoall", "adasum",
          "ring", "send", "recv", "connect", "link", "", "LINK", "x" * 40)
STEPS = ("1", "2", "3", "100", "*", "0", "-1", "1.5", "x", "")
ACTIONS = ("crash", "drop", "refuse", "preempt", "delay", "jitter",
           "throttle", "flaky", "partition", "reset", "blip", "", "boom")
PARAMS = ("0", "1", "0.5", "200", "1e9", "-1", "nan", "inf", "-inf",
          "1e400", "0-3", "3-0", "0-", "-", "a-b", "x", "")
DURATIONS = ("1", "30", "0", "-1", "nan", "inf", "x", "")


class Target(engine.FuzzTarget):
    name = "faultspec"
    path = "horovod_tpu/common/faults.py"

    def setup(self):
        self.trace_files = (faults.__file__,)
        return [
            "rank1:allreduce:2:crash",
            "*:connect:1:refuse",
            "rank1:link:1:delay:200:30,*:allreduce:3:flaky:0.2",
            "rank2:link:*:reset:0.3,rank1:link:5:blip:3000",
            "rank0:ring:4:preempt",
            "*:link:2:partition:0-3:10",
            "",
        ]

    def mutate(self, rng, entry):
        kind = rng.randrange(6)
        if kind == 0:
            # fresh spec from the token pools (grammar-shaped chaos)
            fields = [rng.choice(TARGETS), rng.choice(POINTS),
                      rng.choice(STEPS), rng.choice(ACTIONS)]
            for pool in (PARAMS, DURATIONS):
                if rng.randrange(2):
                    fields.append(rng.choice(pool))
            return ":".join(fields)
        if kind == 1:
            # splice token into an existing spec
            fields = entry.split(":")
            if fields:
                pool = (TARGETS, POINTS, STEPS, ACTIONS, PARAMS,
                        DURATIONS)[min(rng.randrange(len(fields)), 5)]
                fields[rng.randrange(len(fields))] = rng.choice(pool)
            return ":".join(fields)
        if kind == 2:
            # comma-list surgery: join, duplicate, empty segments
            parts = entry.split(",") if entry else []
            parts.append(rng.choice([
                "", " ", "rank1:link:1:delay:5",
                ":::", "a:b:c:d:e:f:g", ","]))
            rng.shuffle(parts)
            return ",".join(parts)
        if kind == 3:
            # character-level noise
            chars = list(entry or "x")
            pos = rng.randrange(len(chars))
            chars[pos] = chr(rng.choice([0, 9, 10, 32, 37, 42, 44, 45,
                                         46, 58, 92, 120, 0x130, 0xFF]))
            return "".join(chars)
        if kind == 4:
            return entry + ":" + rng.choice(PARAMS)
        return entry[:rng.randrange(len(entry) + 1)]

    def execute(self, entry):
        try:
            specs = faults.parse_fault_spec(entry)
        except ValueError:
            return None   # the typed rejection the grammar promises
        except Exception as exc:  # noqa: BLE001 — the oracle itself
            return (f"untyped-rejection:{type(exc).__name__}",
                    f"fault spec escaped as {type(exc).__name__}: "
                    f"{engine.sanitize(exc)}")
        # accepted specs round-trip through their logged repr
        for spec in specs:
            text = repr(spec)
            try:
                again = faults.parse_fault_spec(text)
            except Exception as exc:  # noqa: BLE001 — the oracle itself
                return ("repr-not-reparseable",
                        f"accepted spec's repr {engine.sanitize(text)} "
                        f"failed to reparse: {type(exc).__name__}")
            if len(again) != 1 or repr(again[0]) != text:
                return ("repr-not-idempotent",
                        f"spec repr {engine.sanitize(text)} reparses "
                        f"to something else")
        return None
