"""Fuzz target 6: the launcher config file (``run/config_parser.py``).

Both parsers are on the hook — ``load_config_file`` (PyYAML when
present) and the ``_parse_simple_yaml`` fallback subset parser — and
both promise the same contract: a flat/nested dict back, or a
``ValueError`` naming the file.  A config typo must fail the launcher
with a message, never a raw ScannerError/AttributeError traceback."""

import os
import shutil
import tempfile

from horovod_tpu.run import config_parser
from horovod_tpu.tools.fuzz import engine

LINES = (
    "fuzz:", "proto:", "race:", "elastic:", "checkpoint:", "network:",
    "  seed: 7", "  iters: 300", "  budget: 1.5", "  dir: /tmp/x",
    "  name: \"quoted # hash\"", "  name: 'sq # uoted'",
    "  flag: true", "  flag: off", "  deep:", "    deeper: 1",
    "key: value", "just-a-scalar", "- item", "- item2", "42",
    "key: [1, 2, 3]", "key: {a: 1}", "key: !!python/none",
    "\tkey: tab-indent", "  key # comment", "a: b: c", ":", "::",
    "  empty:", "key: nbsp", "---", "...", "key: &anchor val",
    "other: *anchor", "other: *missing",
)


class Target(engine.FuzzTarget):
    name = "config-yaml"
    path = "horovod_tpu/run/config_parser.py"

    def setup(self):
        self.trace_files = (config_parser.__file__,)
        self.dir = tempfile.mkdtemp(prefix="hvd-fuzz-cfg-")
        self.path = os.path.join(self.dir, "config.yaml")
        return [
            "fuzz:\n  seed: 7\n  iters: 300\nproto:\n  depth: 3\n",
            "network:\n  reconnect_budget: 2.5\n"
            "checkpoint:\n  dir: '/tmp/ck # pt'\n",
            "",
            "just-a-scalar\n",
            "- a\n- b\n",
        ]

    def teardown(self):
        if getattr(self, "dir", None):
            shutil.rmtree(self.dir, ignore_errors=True)
            self.dir = None

    def mutate(self, rng, entry):
        lines = entry.split("\n")
        kind = rng.randrange(5)
        if kind == 0:
            lines.insert(rng.randrange(len(lines) + 1),
                         rng.choice(LINES))
        elif kind == 1 and lines:
            del lines[rng.randrange(len(lines))]
        elif kind == 2:
            # character noise (kept to encodable codepoints)
            text = "\n".join(lines) or "x"
            pos = rng.randrange(len(text))
            ch = chr(rng.choice([0, 9, 10, 13, 32, 34, 35, 39, 45, 58,
                                 91, 92, 123, 0x130, 0x2028, 0xFF]))
            return text[:pos] + ch + text[pos + 1:]
        elif kind == 3 and lines:
            # indentation surgery on one line
            i = rng.randrange(len(lines))
            lines[i] = " " * rng.randrange(7) + lines[i].lstrip()
        else:
            text = "\n".join(lines)
            return text[:rng.randrange(len(text) + 1)]
        return "\n".join(lines)

    def execute(self, entry):
        with open(self.path, "w", encoding="utf-8") as f:
            f.write(entry)
        for parse in (config_parser.load_config_file,
                      config_parser._parse_simple_yaml):
            try:
                result = parse(self.path)
            except ValueError:
                continue   # the typed rejection the launcher reports
            except Exception as exc:  # noqa: BLE001 — the oracle itself
                return (f"untyped-rejection:{type(exc).__name__}",
                        f"{parse.__name__} escaped as "
                        f"{type(exc).__name__}: {engine.sanitize(exc)}")
            if not isinstance(result, dict):
                return ("config-shape",
                        f"{parse.__name__} returned "
                        f"{type(result).__name__}, expected dict")
        return None
