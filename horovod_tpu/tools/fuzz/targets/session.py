"""Fuzz target 3: session-layer records — the :class:`SessionWelcome`
the client handshake trusts, and the hello/seq/ack stream the service's
session admission and frame pump parse.

Two legs share one target (entries are JSON dicts tagged ``leg``):

* ``client`` — a welcome frame (possibly hostile ``rx_seen``, wrong
  object shape, or byte-mutated) fed to ``_session_handshake_client``
  with a real ``_SessionSender``; the replay-buffer arithmetic behind a
  successful handshake runs too.
* ``service`` — a frame stream (hello + session frames, with mutated
  session ids / epochs / request ids / seq patterns) fed to a real
  :class:`MuxService` session via an in-memory socket, followed by a
  LIVENESS PROBE: a fresh known-good session must still get its welcome
  and its response — hostile input may sever its own connection, never
  the service."""

import base64
import threading

from horovod_tpu.run.service import network
from horovod_tpu.tools.fuzz import engine
from horovod_tpu.tools.fuzz.targets import framed

# in-contract outcomes for both legs (the read loops catch exactly these)
ALLOWED = framed.ALLOWED

# hostile values for seq-shaped fields (rx_seen, ack seen, rid seq):
# JSON-able so corpus entries replay byte-identically
SEQ_POOL = (0, 1, -1, 1 << 40, 1 << 62, 1 << 70, "boom", None, True,
            3.5, [], {"a": 1})

SESSION_ID_POOL = ("", "x", "deadbeefdeadbeef", "x" * 64, "x" * 65,
                   "x" * 4096, 17, None, 3.5, True)


def welcome_frame(rx_seen, refused=False):
    # a HOSTILE welcome by design — the epoch fence under test lives on
    # the parsing side, not in this frame factory
    return engine.capture_frame(
        network.write_message, framed.FUZZ_KEY,
        (None, network.SessionWelcome(  # hvd-lint: ignore[wire-safety]
            rx_seen, refused=refused)), "r")


def shape_frame(tag):
    """Response frames that are NOT a proper welcome envelope."""
    objs = {
        "ack": (None, network.SessionAck(3)),
        # hostile by design (see welcome_frame)
        "bare": network.SessionWelcome(0),  # hvd-lint: ignore[wire-safety]
        "triple": (1, 2, 3),
        "str": "welcome",
        "none": None,
        "ping": (None, network.PingResponse("svc")),
    }
    return engine.capture_frame(network.write_message, framed.FUZZ_KEY,
                                objs[tag], "r")


def hello_frame(session_id, epoch, rx_seen=0):
    hello = network.SessionHello.__new__(network.SessionHello)
    hello.session_id = session_id
    hello.epoch = epoch
    hello.rx_seen = rx_seen
    return engine.capture_frame(network.write_message, framed.FUZZ_KEY,
                                (None, hello), "q")


def session_frame(rid, req=None):
    return engine.capture_frame(
        network.write_message, framed.FUZZ_KEY,
        (rid, req if req is not None else network.PingRequest()), "q")


def _b64(data):
    return base64.b64encode(data).decode()


def _client_entry(frame):
    return {"leg": "client", "frame": _b64(frame)}


def _service_entry(*frames):
    return {"leg": "service", "stream": _b64(b"".join(frames))}


class Target(engine.FuzzTarget):
    name = "session"
    path = "horovod_tpu/run/service/network.py"

    def setup(self):
        self.trace_files = (network.__file__,)
        # a MuxService WITHOUT its TCP listener: sessions are served
        # straight off in-memory sockets, so the loop stays in-process
        # and (on the pump thread) deterministic
        svc = network.MuxService.__new__(network.MuxService)
        svc._name = "fuzz"
        svc._key = framed.FUZZ_KEY
        svc._inflight = 0
        svc._inflight_cv = threading.Condition()
        svc._sessions = {}
        svc._sessions_lock = threading.Lock()
        svc.sessions_resumed = 0
        svc.session_dup_drops = 0
        self.svc = svc
        return [
            _client_entry(welcome_frame(0)),
            _client_entry(welcome_frame(5)),
            _client_entry(welcome_frame(0, refused=True)),
            _service_entry(hello_frame("deadbeefdeadbeef", 0),
                           session_frame(("sq", 1)),
                           session_frame(("sq", 2, 7))),
            _service_entry(hello_frame("cafecafecafecafe", 0),
                           *[session_frame(("sq", i))
                             for i in range(1, 21)]),
        ]

    def teardown(self):
        self.svc = None

    # ------------------------------------------------------------ mutate
    def mutate(self, rng, entry):
        if entry["leg"] == "client":
            kind = rng.randrange(4)
            if kind == 0:
                return _client_entry(welcome_frame(
                    rng.choice(SEQ_POOL),
                    refused=rng.randrange(4) == 0))
            if kind == 1:
                return _client_entry(shape_frame(rng.choice(
                    ["ack", "bare", "triple", "str", "none", "ping"])))
            raw = base64.b64decode(entry["frame"])
            return _client_entry(framed.clamp_lengths(
                framed.mutate_bytes(rng, raw)))
        kind = rng.randrange(5)
        if kind == 0:
            return _service_entry(
                hello_frame(rng.choice(SESSION_ID_POOL),
                            rng.choice([0, 1, -1, "x", None, 3.5])),
                session_frame(("sq", 1)))
        if kind == 1:
            rid = rng.choice([
                ("sq", 0), ("sq", -1), ("sq", True), ("sq", 3.5),
                ("sq", "1"), ("sq", 1 << 70), ("sq", None),
                ("qq", 1), ("sq",), ("sq", 1, 2, 3), "sq", 1, None,
                ("sq", [2]), ("sq", 2, {}),
            ])
            return _service_entry(hello_frame("deadbeefdeadbeef", 0),
                                  session_frame(("sq", 1)),
                                  session_frame(rid))
        if kind == 2:
            # seq patterns: dups, gaps, interleavings
            seqs = [rng.choice([1, 1, 2, 2, 3, 5, 9, 1 << 40])
                    for _ in range(rng.randrange(1, 6))]
            return _service_entry(
                hello_frame(f"seed{rng.randrange(8):012d}", 0),
                *[session_frame(("sq", s)) for s in seqs])
        raw = base64.b64decode(entry["stream"])
        return _service_entry(framed.clamp_stream(
            framed.mutate_bytes(rng, raw)))

    # ----------------------------------------------------------- execute
    def execute(self, entry):
        if entry["leg"] == "client":
            return self._run_client(base64.b64decode(entry["frame"]))
        violation = self._run_service(base64.b64decode(entry["stream"]))
        if violation is not None:
            return violation
        return self._probe_liveness()

    def _run_client(self, frame):
        sock = engine.FakeSock(frame)
        sender = network._SessionSender(epoch=0, replay_bytes=4096)
        try:
            welcome = network._session_handshake_client(
                sock, framed.FUZZ_KEY, sender, timeout=5)
            if not welcome.refused:
                # the caller immediately runs replay arithmetic on the
                # welcome's rx_seen — part of the parsing contract (the
                # harness only cares that it doesn't throw, so the gap
                # sentinel is deliberately not consulted here)
                sender.append(lambda seq: (("sq", seq), None),
                              network._CTRL_FRAME_EST)
                sender.replayable_from(  # hvd-lint: ignore[wire-safety]
                    welcome.rx_seen)
        except ALLOWED:
            pass
        except Exception as exc:  # noqa: BLE001 — the oracle itself
            return (f"untyped-rejection:{type(exc).__name__}",
                    f"session welcome escaped as {type(exc).__name__}: "
                    f"{engine.sanitize(exc)}")
        if sock.max_requested > engine.ALLOC_CAP:
            return ("unbounded-read",
                    f"handshake requested a {sock.max_requested}-byte "
                    f"read from an unchecked length field")
        return None

    def _serve_stream(self, stream):
        """The handler-loop prologue (first frame decides session-ness)
        + ``_session_serve``, against an in-memory socket; returns the
        sock or an (allowed-rejection) None."""
        sock = engine.FakeSock(stream)
        try:
            frame = network.read_message(sock, framed.FUZZ_KEY, "q")
        except ALLOWED:
            return sock
        if not (isinstance(frame, tuple) and len(frame) == 2):
            return sock
        _rid, req = frame
        if isinstance(req, network.SessionHello):
            self.svc._session_serve(sock, threading.Lock(), req,
                                    ("127.0.0.1", 0))
        return sock

    def _run_service(self, stream):
        try:
            sock = self._serve_stream(stream)
        except ALLOWED:
            return None
        except Exception as exc:  # noqa: BLE001 — the oracle itself
            return (f"untyped-rejection:{type(exc).__name__}",
                    f"session admission escaped as "
                    f"{type(exc).__name__}: {engine.sanitize(exc)}")
        if sock.max_requested > engine.ALLOC_CAP:
            return ("unbounded-read",
                    f"session pump requested a {sock.max_requested}-"
                    f"byte read from an unchecked length field")
        return None

    def _probe_liveness(self):
        """A known-good session must still be served after whatever the
        mutant did: welcome granted, response delivered (fresh session)
        or redelivered from the retained-responses stash (resume)."""
        stream = (hello_frame("probe-session-00", 0)
                  + session_frame(("sq", 1, 42)))
        try:
            sock = self._serve_stream(stream)
        except Exception as exc:  # noqa: BLE001 — liveness oracle
            return ("liveness-lost",
                    f"known-good probe session raised "
                    f"{type(exc).__name__}: {engine.sanitize(exc)}")
        # response frames are written by a handler thread; drain behind
        # the service's own in-flight barrier before reading them
        deadline_ok = True
        with self.svc._inflight_cv:
            deadline_ok = self.svc._inflight_cv.wait_for(
                lambda: self.svc._inflight == 0, timeout=10)
        if not deadline_ok:
            return ("liveness-lost",
                    "probe session's handler never completed")
        welcomed = answered = False
        reply = engine.FakeSock(bytes(sock.sent))
        while True:
            try:
                frame = network.read_message(reply, framed.FUZZ_KEY, "r")
            except ALLOWED:
                break
            if not (isinstance(frame, tuple) and len(frame) == 2):
                continue
            rid, obj = frame
            if isinstance(obj, network.SessionWelcome) \
                    and not obj.refused:
                welcomed = True
            if rid == 42 and isinstance(obj, network.PingResponse):
                answered = True
        if not (welcomed and answered):
            return ("liveness-lost",
                    f"probe session got welcome={welcomed} "
                    f"response={answered} after hostile input")
        return None
