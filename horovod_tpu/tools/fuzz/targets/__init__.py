"""The six untrusted-input parser targets (docs/fuzzing.md).

Each module exposes a :class:`~horovod_tpu.tools.fuzz.engine.FuzzTarget`
subclass named ``Target``; ``ALL_TARGETS`` maps target name to class in
a fixed order (the report iterates it sorted, so the registry order is
cosmetic)."""

from horovod_tpu.tools.fuzz.targets import (
    bulk,
    checkpoint,
    config_yaml,
    faultspec,
    framed,
    session,
)

ALL_TARGETS = {
    framed.Target.name: framed.Target,
    bulk.Target.name: bulk.Target,
    session.Target.name: session.Target,
    faultspec.Target.name: faultspec.Target,
    checkpoint.Target.name: checkpoint.Target,
    config_yaml.Target.name: config_yaml.Target,
}
