"""Fuzz target 5: checkpoint manifest + shard sidecar parsing
(``checkpoint/store.py`` + the ``restore_latest`` fallback walk).

Setup writes one COMPLETE two-rank checkpoint into a scratch
directory; each entry overwrites (or deletes) exactly one of its four
files and runs the full read stack.  Oracle: ``read_shard`` returns a
dict or raises ``CorruptShardError``; ``read_manifest`` returns a dict
or raises ``ValueError``/``OSError``; ``restore_latest`` NEVER raises —
a torn file means "fall back", not a crash — and with any file DELETED
it must return None (an incomplete world is never loaded)."""

import base64
import json
import logging
import os
import shutil
import tempfile

import numpy as np

from horovod_tpu.checkpoint import manager, store
from horovod_tpu.tools.fuzz import engine

STEP, EPOCH, WORLD = 5, 0, 2

# wrong-typed JSON values torn writes can leave behind
JSON_POOL = (None, True, False, 0, -1, 1 << 70, 3.5, "x", "",
             [], [1, 2], {}, {"a": None})


class _StubState:
    """The slice of ``elastic.State`` the resume path touches."""

    params = None
    optimizer_state = None

    def __init__(self):
        self._committed = None
        self._opt_full = False

    def restore(self):
        pass


class Target(engine.FuzzTarget):
    name = "checkpoint"
    path = "horovod_tpu/checkpoint/store.py"

    FILES = ("shard", "meta", "manifest", "shard1")

    def setup(self):
        self.trace_files = (store.__file__, manager.__file__)
        self.dir = tempfile.mkdtemp(prefix="hvd-fuzz-ckpt-")
        payload = {"params": np.zeros((0,), np.float32)}
        for rank in range(WORLD):
            store.write_shard(self.dir, STEP, EPOCH, WORLD, rank,
                              payload)
        store.write_manifest(self.dir, STEP, EPOCH, WORLD,
                             extra={"n_params": 0, "opt_kind": "none",
                                    "opt_num_leaves": 0, "root_wid": 0})
        self.mgr = manager.CheckpointManager(self.dir, keep=0)
        # the fallback walk warns per corrupt manifest — thousands of
        # iterations of expected-corruption log lines help nobody
        quiet = logging.getLogger("horovod_tpu.fuzz.quiet")
        quiet.disabled = True
        self.mgr._log = quiet
        shard0 = store.shard_name(STEP, EPOCH, WORLD, 0)
        self.paths = {
            "shard": os.path.join(self.dir, shard0),
            "meta": os.path.join(self.dir, f"{shard0}.meta.json"),
            "manifest": os.path.join(
                self.dir, store.manifest_name(STEP, EPOCH, WORLD)),
            "shard1": os.path.join(
                self.dir, store.shard_name(STEP, EPOCH, WORLD, 1)),
        }
        self.originals = {}
        for kind, path in self.paths.items():
            with open(path, "rb") as f:
                self.originals[kind] = f.read()
        seeds = [{"file": kind,
                  "data": base64.b64encode(
                      self.originals[kind]).decode()}
                 for kind in self.FILES]
        seeds += [{"file": kind, "data": None} for kind in self.FILES]
        return seeds

    def teardown(self):
        if getattr(self, "mgr", None) is not None:
            self.mgr.close()
            self.mgr = None
        if getattr(self, "dir", None):
            shutil.rmtree(self.dir, ignore_errors=True)
            self.dir = None

    # ------------------------------------------------------------ mutate
    def mutate(self, rng, entry):
        kind = entry["file"]
        original = self.originals[kind]
        if entry["data"] is None or rng.randrange(8) == 0:
            # deletions never mutate further; occasionally re-derive one
            return {"file": rng.choice(self.FILES), "data": None}
        data = base64.b64decode(entry["data"])
        if kind in ("meta", "manifest") and rng.randrange(2):
            # torn-but-valid-JSON: keep the body parseable, break shape
            try:
                body = json.loads(original.decode())
            except ValueError:
                body = {}
            roll = rng.randrange(4)
            if roll == 0 and body:
                body.pop(rng.choice(sorted(body)), None)
            elif roll == 1 and body:
                body[rng.choice(sorted(body))] = rng.choice(JSON_POOL)
            elif roll == 2:
                body = rng.choice(JSON_POOL)
            else:
                body[f"extra{rng.randrange(4)}"] = rng.choice(JSON_POOL)
            data = json.dumps(body).encode()
        else:
            buf = bytearray(data)
            roll = rng.randrange(4)
            if not buf:
                roll = 3
            if roll == 0:
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            elif roll == 1:
                buf = buf[:rng.randrange(len(buf))]   # torn write
            elif roll == 2:
                buf[rng.randrange(len(buf))] = rng.randrange(256)
            else:
                buf += bytes(rng.randrange(256)
                             for _ in range(rng.randrange(1, 9)))
            data = bytes(buf)
        return {"file": kind, "data": base64.b64encode(data).decode()}

    # ----------------------------------------------------------- execute
    def execute(self, entry):
        kind = entry["file"]
        path = self.paths[kind]
        try:
            if entry["data"] is None:
                os.remove(path)
            else:
                with open(path, "wb") as f:
                    f.write(base64.b64decode(entry["data"]))
            return self._oracle(deleted=entry["data"] is None)
        finally:
            with open(path, "wb") as f:
                f.write(self.originals[kind])

    def _oracle(self, deleted):
        for rank in range(WORLD):
            try:
                result = store.read_shard(self.dir, STEP, EPOCH, WORLD,
                                          rank)
                if not isinstance(result, dict):
                    return ("shard-shape",
                            f"read_shard returned "
                            f"{type(result).__name__}, expected dict")
            except store.CorruptShardError:
                pass
            except Exception as exc:  # noqa: BLE001 — the oracle itself
                return (f"untyped-rejection:{type(exc).__name__}",
                        f"read_shard escaped as {type(exc).__name__}: "
                        f"{engine.sanitize(exc)}")
        try:
            body = store.read_manifest(self.dir, STEP, EPOCH, WORLD)
            if not isinstance(body, dict):
                return ("manifest-shape",
                        f"read_manifest returned "
                        f"{type(body).__name__}, expected dict")
        except (ValueError, OSError):
            pass
        except Exception as exc:  # noqa: BLE001 — the oracle itself
            return (f"untyped-rejection:{type(exc).__name__}",
                    f"read_manifest escaped as {type(exc).__name__}: "
                    f"{engine.sanitize(exc)}")
        state = _StubState()
        try:
            resumed = self.mgr.restore_latest(state)
        except Exception as exc:  # noqa: BLE001 — the oracle itself
            return (f"untyped-rejection:{type(exc).__name__}",
                    f"restore_latest escaped on a corrupt checkpoint "
                    f"as {type(exc).__name__}: {engine.sanitize(exc)}")
        if deleted and resumed is not None:
            return ("partial-world-load",
                    f"restore_latest loaded step {resumed[0]} with a "
                    f"checkpoint file missing")
        return None
