"""Fuzz target 2: raw-bulk frames (``_read_bulk`` via ``read_message``)
— the header/payload length-word binding, truncation, scatter-gather
boundaries, and the payload-injection step.

Beyond byte-level chaos (which the HMAC converts into the typed
verification failure), the structure-aware mutations re-SIGN hostile
frames with the fuzz key — a keyed-but-buggy peer — so the
behind-the-verification-gate paths run: carriers that can't accept a
payload, shifted length-word bindings, header/payload boundary
moves."""

import pickle
import struct

from horovod_tpu.run.service import network, secret
from horovod_tpu.tools.fuzz import engine
from horovod_tpu.tools.fuzz.targets import framed


class Hdr:
    """The bulk header carrier shape the data plane uses: a ``payload``
    slot the receiver injects into."""

    def __init__(self, tag="seg", rank=0):
        self.tag = tag
        self.rank = rank
        self.payload = None


class FrozenHdr:
    """A carrier that REFUSES payload injection (slots, no ``payload``)
    — the malformed-carrier shape the typed-rejection fix pins."""

    __slots__ = ("tag",)

    def __init__(self, tag="seg"):
        self.tag = tag


def build_bulk(obj, payload, direction="q", key=framed.FUZZ_KEY):
    return engine.capture_frame(network.write_bulk_message, key, obj,
                                payload, direction)


def resign_bulk(hdr_obj, payload, direction="q", key=framed.FUZZ_KEY,
                hdr_len=None, payload_len=None):
    """Assemble a bulk frame BY HAND with a valid HMAC over possibly
    hostile pieces: arbitrary pickled header object, and length words
    that may disagree with the actual byte layout (the signature binds
    whatever words we claim — the parser must still reject the
    mismatch via truncation/verification, never misparse)."""
    hdr = pickle.dumps((direction, hdr_obj))
    payload = bytes(payload)
    h_len = len(hdr) if hdr_len is None else hdr_len
    p_len = len(payload) if payload_len is None else payload_len
    lengths = struct.pack(">II", h_len, p_len)
    digest = secret.sign_parts(key, lengths, hdr, payload)
    return (struct.pack(">I", network.RAW_FRAME_FLAG | h_len) + digest +
            struct.pack(">I", p_len) + hdr + payload)


class Target(engine.FuzzTarget):
    name = "bulk"
    path = "horovod_tpu/run/service/network.py"

    def setup(self):
        self.trace_files = (network.__file__,)
        seeds = []
        for obj, payload in (
                ((None, Hdr()), b""),
                ((None, Hdr()), b"x" * 100),
                ((("sq", 2), Hdr("chunk", 3)), b"\x00" * 1024),
                (Hdr("bare"), b"abc"),
                ((7, Hdr("resp", 1)), bytes(range(256)))):
            seeds.append(build_bulk(obj, payload))
        return seeds

    def mutate(self, rng, entry):
        kind = rng.randrange(12)
        if kind == 0:
            # non-injectable carrier, correctly signed
            bad = rng.choice([7, "seg", (), None, FrozenHdr()])
            shape = rng.choice([lambda c: (None, c), lambda c: c,
                                lambda c: (("sq", 2), c)])
            return resign_bulk(shape(bad), b"payload")
        if kind == 1:
            # length words that lie about the layout, signed as claimed
            payload = b"y" * rng.randrange(64)
            delta = rng.choice([-8, -1, 1, 8, 1024])
            if rng.randrange(2):
                return resign_bulk((None, Hdr()), payload,
                                   payload_len=max(0, len(payload)
                                                   + delta))
            return resign_bulk((None, Hdr()), payload,
                               hdr_len=max(0, 40 + delta))
        if kind == 2:
            # valid HMAC over a non-pickle header
            garbage = bytes(rng.randrange(256)
                            for _ in range(rng.randrange(1, 32)))
            lengths = struct.pack(">II", len(garbage), 4)
            digest = secret.sign_parts(framed.FUZZ_KEY, lengths,
                                       garbage, b"pppp")
            return (struct.pack(">I",
                                network.RAW_FRAME_FLAG | len(garbage))
                    + digest + struct.pack(">I", 4) + garbage + b"pppp")
        return framed.clamp_lengths(framed.mutate_bytes(rng, entry))

    def execute(self, entry):
        return framed.wire_execute(entry)
