"""Fuzz target 1: framed control messages (``read_message`` in
``run/service/network.py``) — the length word, the HMAC digest, the
pickled envelope, the ``MAX_FRAME_BYTES`` bound.

Oracle: every outcome is either a parsed object or one of the typed
rejections the read loops catch (PermissionError / ConnectionError /
EOFError / OSError); ``pickle.loads`` is never reached before a
successful HMAC check; no single socket read trusts an unchecked
length.  The fuzz key is FIXED (not random) so frame bytes — and with
them the whole run — are identical across processes."""

import hashlib
import struct

from horovod_tpu.run.service import network, secret
from horovod_tpu.tools.fuzz import engine

FUZZ_KEY = hashlib.sha256(b"hvd-fuzz-wire-key").digest()

# typed rejections the service/client read loops already catch — the
# in-contract ways a parser may refuse bytes
ALLOWED = (PermissionError, ConnectionError, EOFError, OSError)

# structure-aware 32-bit values for length words: small (real frames),
# boundary, over-cap, and flag-bit patterns — deliberately NOTHING in
# the (4 MB, 1 GB] gap, where a claimed length passes the transport cap
# but buys a pointless transient allocation per iteration
INTERESTING_U32 = (
    0, 1, 2, 3, 4, 7, 8, 36, 255, 256, 65535, 65536, 1 << 20,
    network.MAX_FRAME_BYTES + 1, (1 << 31) - 1, 1 << 31,
    network.RAW_FRAME_FLAG | 1, network.RAW_FRAME_FLAG | 65536,
    network.RAW_FRAME_FLAG | 65537, (1 << 32) - 1,
)

# the gap described above: mutated length fields landing here are
# rewritten over-cap so the typed-rejection branch is what runs
_CLAMP_LO = 1 << 22


def mutate_bytes(rng, data):
    """One shared byte-level mutation: bit flip, byte set, truncate,
    extend, interesting-u32 splice, or slice duplication."""
    buf = bytearray(data)
    choice = rng.randrange(6)
    if not buf:
        choice = 3
    if choice == 0:
        pos = rng.randrange(len(buf))
        buf[pos] ^= 1 << rng.randrange(8)
    elif choice == 1:
        buf[rng.randrange(len(buf))] = rng.randrange(256)
    elif choice == 2:
        buf = buf[:rng.randrange(len(buf))]
    elif choice == 3:
        pos = rng.randrange(len(buf) + 1)
        extra = bytes(rng.randrange(256)
                      for _ in range(rng.randrange(1, 9)))
        buf = buf[:pos] + extra + buf[pos:]
    elif choice == 4:
        value = INTERESTING_U32[rng.randrange(len(INTERESTING_U32))]
        pos = rng.randrange(max(1, len(buf) - 3))
        buf[pos:pos + 4] = struct.pack(">I", value)
    else:
        a = rng.randrange(len(buf))
        b = rng.randrange(a, min(len(buf), a + 16) + 1)
        buf = buf[:a] + buf[a:b] + buf[a:]
    return bytes(buf)


def clamp_lengths(data):
    """Rewrite any mutated length field in the useless-allocation gap
    to an over-cap value (see ``_CLAMP_LO``); structure-aware, applied
    after every byte-level mutation."""
    buf = bytearray(data)
    if len(buf) >= 4:
        (word,) = struct.unpack(">I", buf[:4])
        if word & network.RAW_FRAME_FLAG:
            if len(buf) >= 40:
                (plen,) = struct.unpack(">I", buf[36:40])
                if _CLAMP_LO < plen <= network.MAX_FRAME_BYTES:
                    buf[36:40] = struct.pack(
                        ">I", network.MAX_FRAME_BYTES + 1)
        elif _CLAMP_LO < word <= network.MAX_FRAME_BYTES:
            buf[:4] = struct.pack(">I", network.MAX_FRAME_BYTES + 1)
    return bytes(buf)


def clamp_stream(data):
    """:func:`clamp_lengths` generalized to a CONCATENATION of frames
    (the session target's streams): walk frame boundaries and rewrite
    the first gap-range length word met — the parser severs there, so
    nothing after it is reached anyway."""
    buf = bytearray(data)
    off = 0
    while off + 4 <= len(buf):
        (word,) = struct.unpack_from(">I", buf, off)
        if word & network.RAW_FRAME_FLAG:
            hdr_len = word & ~network.RAW_FRAME_FLAG
            p_off = off + 4 + secret.DIGEST_LEN
            if p_off + 4 > len(buf):
                break
            (plen,) = struct.unpack_from(">I", buf, p_off)
            if _CLAMP_LO < plen <= network.MAX_FRAME_BYTES:
                struct.pack_into(">I", buf, p_off,
                                 network.MAX_FRAME_BYTES + 1)
                break
            off = p_off + 4 + hdr_len + plen
        else:
            if _CLAMP_LO < word <= network.MAX_FRAME_BYTES:
                struct.pack_into(">I", buf, off,
                                 network.MAX_FRAME_BYTES + 1)
                break
            off += 4 + secret.DIGEST_LEN + word
    return bytes(buf)


def signed_frame(payload, key=FUZZ_KEY):
    """A control frame whose HMAC is VALID over arbitrary payload bytes
    — the keyed-but-hostile-peer shape byte flips can't reach (they
    break the digest first)."""
    return struct.pack(">I", len(payload)) + secret.sign(key, payload) \
        + payload


def wire_execute(data, key=FUZZ_KEY, direction="q"):
    """Shared framed/bulk execution under the full oracle set; returns
    a violation tuple or None."""
    sock = engine.FakeSock(data)
    failure = None
    with engine.PickleProbe() as probe:
        try:
            network.read_message(sock, key, direction)
        except ALLOWED:
            pass
        except Exception as exc:  # noqa: BLE001 — the oracle itself
            failure = (f"untyped-rejection:{type(exc).__name__}",
                       f"malformed frame escaped as "
                       f"{type(exc).__name__}: {engine.sanitize(exc)}")
    if probe.violation:
        return (probe.violation,
                "pickle.loads reached before a successful HMAC check")
    if sock.max_requested > engine.ALLOC_CAP:
        return ("unbounded-read",
                f"parser requested a {sock.max_requested}-byte read "
                f"from an unchecked length field")
    return failure


class Target(engine.FuzzTarget):
    name = "framed"
    path = "horovod_tpu/run/service/network.py"

    def setup(self):
        self.trace_files = (network.__file__,)
        seeds = []
        for obj in (network.PingRequest(),
                    (7, network.PingRequest()),
                    (None, network.SessionAck(3)),
                    (("sq", 1, 99), network.PingRequest()),
                    (None, network.SessionHello("cafe", 0, 0)),
                    # a seed frame, not a resume admission — the fence
                    # under test is in the parser, not this builder
                    (None, network.SessionWelcome(5)),  # hvd-lint: ignore[wire-safety]
                    (2, network.HeartbeatMsg(1, busy=True, rtt=0.25)),
                    (3, network.AbortMsg(2, "fuzz"))):
            seeds.append(engine.capture_frame(
                network.write_message, FUZZ_KEY, obj, "q"))
        # a response-direction frame: the direction oracle's seed
        seeds.append(engine.capture_frame(
            network.write_message, FUZZ_KEY, (7, network.AckResponse()),
            "r"))
        return seeds

    def mutate(self, rng, entry):
        kind = rng.randrange(10)
        if kind == 0:
            # valid HMAC over non-pickle garbage: exercises the typed
            # decode-failure path behind the verification gate
            return signed_frame(bytes(
                rng.randrange(256) for _ in range(rng.randrange(64))))
        if kind == 1:
            # valid HMAC over a pickled non-envelope (wrong shape)
            import pickle
            obj = rng.choice([42, "q", (1, 2, 3), ("r",), [], None])
            return signed_frame(pickle.dumps(obj))
        return clamp_lengths(mutate_bytes(rng, entry))

    def execute(self, entry):
        return wire_execute(entry)
