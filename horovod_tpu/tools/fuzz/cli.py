"""``bin/hvd-fuzz`` — deterministic structure-aware parser fuzzing
(docs/fuzzing.md).

Usage::

    bin/hvd-fuzz                             # all six targets + corpus
    bin/hvd-fuzz --seed 7 --iters 2000       # a deeper, pinned run
    bin/hvd-fuzz --targets framed,bulk       # subset
    bin/hvd-fuzz --corpus-only               # just replay the corpus
    bin/hvd-fuzz --format json               # machine-readable
    bin/hvd-fuzz --write-baseline            # refresh suppressions

Exit codes: 0 = clean (baselined findings included), 1 = active
findings, 2 = usage error — exact parity with ``bin/hvd-lint`` /
``bin/hvd-race`` / ``bin/hvd-proto``.  The baseline lives at
``.hvd-fuzz-baseline.json`` in the repo root and the tier-1 gate
(tests/test_fuzz.py) keeps it empty: a parser bug gets FIXED and a
distilled corpus entry, not a suppression.  Determinism: the same
``--seed`` and ``--iters`` produce a byte-identical report across
processes (the hvd-race/hvd-proto contract)."""

import argparse
import json
import os
import sys

from horovod_tpu.tools.fuzz import engine
from horovod_tpu.tools.fuzz.targets import ALL_TARGETS
from horovod_tpu.tools.lint import findings as findings_mod
from horovod_tpu.utils import env as env_util

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, ".hvd-fuzz-baseline.json")
DEFAULT_CORPUS = os.path.join(REPO_ROOT, "tests", "fuzz_corpus")

DEFAULT_ITERS = 300


def run_fuzz(targets=None, seed=0, iters=DEFAULT_ITERS,
             corpus_dir=DEFAULT_CORPUS, corpus_only=False):
    """Programmatic entry: ``(stats_list, findings, corpus_count)`` —
    findings are pre-baseline, sorted for byte-identical reports."""
    names = sorted(ALL_TARGETS) if targets is None else list(targets)
    stats_list = []
    findings = []
    if not corpus_only:
        for name in names:
            target = ALL_TARGETS[name]()
            stats, found = engine.run_target(target, seed, iters)
            stats_list.append(stats)
            findings.extend(found)
    corpus_count = 0
    if os.path.isdir(corpus_dir):
        corpus_count, corpus_findings = engine.replay_corpus(
            corpus_dir, [ALL_TARGETS[name]() for name in names])
        findings.extend(corpus_findings)
    findings.sort(key=lambda f: (f.checker, f.path, f.detail))
    return stats_list, findings, corpus_count


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-fuzz",
        description="Deterministic structure-aware fuzzing of every "
                    "untrusted-input parser (docs/fuzzing.md).")
    parser.add_argument("--targets", default=None,
                        help="Comma-separated target subset "
                             f"(available: {', '.join(ALL_TARGETS)}).")
    parser.add_argument("--seed", type=int, default=None,
                        help="Mutation seed (default: "
                             "HVD_TPU_FUZZ_SEED, else 0); the same "
                             "seed and iters give a byte-identical "
                             "report.")
    parser.add_argument("--iters", type=int, default=None,
                        help="Mutation iterations per target "
                             "(default: HVD_TPU_FUZZ_ITERS, else "
                             f"{DEFAULT_ITERS}).")
    parser.add_argument("--corpus", default=DEFAULT_CORPUS,
                        help="Distilled regression corpus to replay "
                             "(default: tests/fuzz_corpus).")
    parser.add_argument("--corpus-only", action="store_true",
                        help="Skip mutation runs; only replay the "
                             "corpus (the fast tier-1 regression "
                             "check).")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="Baseline JSON of suppressed finding keys "
                             "(default: .hvd-fuzz-baseline.json in the "
                             "repo root).")
    parser.add_argument("--no-baseline", action="store_true",
                        help="Report every finding, suppressing "
                             "nothing.")
    parser.add_argument("--write-baseline", action="store_true",
                        help="Rewrite the baseline from the current "
                             "findings (existing justifications are "
                             "kept; new entries get a TODO the gate "
                             "test rejects until justified).")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text")
    args = parser.parse_args(argv)

    selected = None
    if args.targets:
        selected = [t.strip() for t in args.targets.split(",")]
        unknown = [t for t in selected if t not in ALL_TARGETS]
        if unknown:
            parser.error(f"unknown target(s): {', '.join(unknown)}")
        selected = sorted(selected)

    seed = args.seed if args.seed is not None else \
        env_util.get_int(env_util.HVD_TPU_FUZZ_SEED, 0)
    iters = args.iters if args.iters is not None else \
        env_util.get_int(env_util.HVD_TPU_FUZZ_ITERS, DEFAULT_ITERS)

    stats_list, all_findings, corpus_count = run_fuzz(
        targets=selected, seed=seed, iters=iters,
        corpus_dir=args.corpus, corpus_only=args.corpus_only)

    baseline = {} if args.no_baseline else \
        findings_mod.load_baseline(args.baseline)
    if args.write_baseline:
        # suppressions for targets this run didn't execute carry over
        # verbatim — a scoped rewrite must never delete other scopes'
        # justifications
        run_checkers = {f"fuzz-{name}" for name in
                        (selected or sorted(ALL_TARGETS))}
        run_checkers.add("fuzz-corpus")

        def out_of_scope(key):
            return key.partition(":")[0] not in run_checkers

        previous = findings_mod.load_baseline(args.baseline)
        findings_mod.write_baseline(args.baseline, all_findings,
                                    previous=previous,
                                    out_of_scope=out_of_scope)
        written = len(findings_mod.load_baseline(args.baseline))
        print(f"wrote {written} suppression(s) to {args.baseline}")
        return 0
    active, suppressed, stale = findings_mod.split_baselined(
        all_findings, baseline)

    if args.format == "json":
        json.dump({
            "seed": seed, "iters": iters, "stats": stats_list,
            "corpus_replayed": corpus_count,
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline_keys": stale,
        }, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for stats in stats_list:
            print(f"fuzz {stats['target']}: iters={stats['iters']} "
                  f"corpus={stats['corpus_seed']}->{stats['corpus']} "
                  f"arcs={stats['arcs']} findings={stats['findings']}")
        if corpus_count or not args.corpus_only:
            print(f"corpus: {corpus_count} distilled entr"
                  f"{'y' if corpus_count == 1 else 'ies'} replayed")
        for finding in active:
            print(finding.render())
        summary = (f"hvd-fuzz: {len(active)} finding(s), "
                   f"{len(suppressed)} baselined")
        if stale:
            summary += (f", {len(stale)} stale baseline key(s) — "
                        f"run --write-baseline to prune")
        print(summary)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
