"""hvd-fuzz — deterministic structure-aware fuzzing of every
untrusted-input parser (docs/fuzzing.md).

The correctness-tooling ladder's fourth rung: hvd-lint checks
invariants in code we wrote, hvd-race checks interleavings, hvd-proto
checks the protocols — hvd-fuzz checks the BYTES WE RECEIVE.  Six
parser targets (framed control messages, raw-bulk frames, session
records, the fault-spec grammar, checkpoint manifests/sidecars, config
YAML) are driven with seeded structure-aware mutations; each target
carries an invariant oracle (typed rejection, verify-before-unpickle,
bounded allocation, connection survives — never process death) and
findings ride hvd-lint's baseline machinery.

Determinism contract (shared with hvd-race/hvd-proto): the same
``HVD_TPU_FUZZ_SEED`` and ``HVD_TPU_FUZZ_ITERS`` produce a
byte-identical run summary.
"""
