"""Seeded schedule fuzzing for the race shim.

At instrumentation points (lock acquire, attribute write, queue ops)
the shim asks the fuzzer whether to inject a short preemption — a GIL
yield or a sub-millisecond sleep — so a narrow interleaving that hides
on an idle machine is forced open, and forced open THE SAME WAY on
every run.

Determinism contract (the ``HVD_TPU_FAULT_SPEC`` contract): the
decision at the N-th instrumentation point of a given thread is a pure
function of ``(seed, thread key, N)``.  The thread key is a CRC of the
thread's *name* (thread names are assigned in creation order, which the
program controls), never of the OS ident, so a rerun with the same seed
makes identical preemption decisions even though the kernel schedules
the threads differently.  The OS still owns true interleaving — the
contract is that the *perturbation* is reproducible, which in practice
pins the detector's report (tests/test_race.py asserts the identical
report twice under a fixed seed).
"""

import time
import zlib


def thread_key(name):
    """Deterministic per-thread fuzz key (see module docstring)."""
    return zlib.crc32(name.encode("utf-8", "replace"))


def _mix(seed, key, counter):
    x = (seed * 1000003 ^ key * 0x9E3779B1 ^ counter * 0x85EBCA6B) \
        & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x45D9F3B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


class ScheduleFuzzer:
    """One instance per installed shim; stateless between calls apart
    from the per-thread counters the detector owns."""

    __slots__ = ("seed",)

    # out of 1024 draws: ~1% short sleep (forces a real preemption,
    # bounded so suites under the shim stay inside tier-1 budgets),
    # ~8% bare yield (releases the GIL at the instrumentation point)
    _SLEEP_BELOW = 10
    _YIELD_BELOW = 92

    def __init__(self, seed):
        self.seed = int(seed)

    def maybe_preempt(self, key, counter):
        r = _mix(self.seed, key, counter) & 1023
        if r < self._SLEEP_BELOW:
            time.sleep(0.0002)
        elif r < self._YIELD_BELOW:
            time.sleep(0)
