"""Runtime happens-before hooks for hvd-race.

The runtime has ordering channels the generic primitive shims cannot
see — most importantly the PeerService mailbox, where a chunk's
delivery (on a MuxService handler thread) must happen-before the
compute thread's ``recv`` that consumes it even on the no-wait fast
path (the chunk was already buffered, so the condition-variable edge
never fires).

The runtime calls these hooks behind an ``if race_hooks.active:`` guard
so the off-path cost is one module-attribute read; ``active`` flips to
True only when the shim installs (``HVD_TPU_RACE=1``).  This module
deliberately imports nothing from the race package at module level —
importing it must not pull the detector into an uninstrumented process.
"""

active = False
_detector = None


def attach(detector):
    """Called by the shim at install time."""
    global active, _detector
    _detector = detector
    active = True


def publish(channel):
    """Record: everything the calling thread did so far happens-before
    any later ``observe`` of the same channel."""
    det = _detector
    if det is not None:
        det.publish(("hook",) + tuple(channel))


def observe(channel):
    det = _detector
    if det is not None:
        det.observe(("hook",) + tuple(channel))
