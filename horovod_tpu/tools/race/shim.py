"""Installable instrumentation shim for the race detector.

``install()`` patches, process-wide:

- ``threading.Lock`` / ``RLock`` / ``Condition`` / ``Event`` — traced
  wrappers that keep per-thread locksets and publish the notify→wake /
  set→wait happens-before edges;
- ``queue.Queue.put`` / ``get`` — the put→get edge (FIFO-paired
  snapshots);
- ``threading.Thread.start`` / ``join`` — the fork and join edges,
  plus per-thread detector state bootstrap;

and instruments attribute access (``__getattribute__`` /
``__setattr__``) on every class of the concurrency-scoped modules
(``HVD_TPU_RACE_SCOPE``; default: the ring data plane, the tcp
controller, the python controller cycle loop and the mux transport),
via a sweep of already-imported modules plus an import hook for the
rest.

The shim is opt-in and absent by construction when off:
``horovod_tpu/__init__`` imports this module ONLY when ``HVD_TPU_RACE``
is set, so with the variable unset ``threading.Lock`` is the stock
factory and no wrapper exists anywhere in the process
(tests/test_race.py proves both directions).
"""

import _thread
import atexit
import importlib.abc
import importlib.machinery
import json
import os
import queue as _queue_mod
import sys
import threading as _t

from horovod_tpu.tools.race.detector import Detector
from horovod_tpu.utils import env as env_util

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# the concurrency-scoped modules instrumented by default — the same
# neighborhoods hvd-lint's lock checkers police (docs/race_detection.md)
DEFAULT_SCOPE = (
    "horovod_tpu/ops/tcp_dataplane.py",
    "horovod_tpu/ops/tcp_controller.py",
    "horovod_tpu/ops/python_controller.py",
    "horovod_tpu/run/service/network.py",
)

# saved stock primitives — everything the shim itself needs must come
# from here so detector internals never recurse through the wrappers
_real = {
    "Lock": _t.Lock,
    "RLock": _t.RLock,
    "Condition": _t.Condition,
    "Event": _t.Event,
    "Thread.start": _t.Thread.start,
    "Thread.join": _t.Thread.join,
    "Queue.put": _queue_mod.Queue.put,
    "Queue.get": _queue_mod.Queue.get,
}

_det = None             # the installed Detector (None = shim off)
_instrumented = set()   # classes carrying traced attribute access
_scope = ()


def is_installed():
    return _det is not None


def detector():
    return _det


# ------------------------------------------------------ traced primitives
class TracedLock:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = _real["Lock"]()

    def acquire(self, blocking=True, timeout=-1):
        _det.fuzz()
        got = self._lock.acquire(blocking, timeout)
        if got:
            _det.on_acquire(id(self))
        return got

    def release(self):
        _det.on_release(id(self))
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<TracedLock {self._lock!r}>"


class TracedRLock:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = _real["RLock"]()

    def acquire(self, blocking=True, timeout=-1):
        _det.fuzz()
        got = self._lock.acquire(blocking, timeout)
        if got:
            _det.on_acquire(id(self))
        return got

    def release(self):
        _det.on_release(id(self))
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<TracedRLock {self._lock!r}>"


def _raw(lock):
    """The stock lock under a traced wrapper (a stock lock passes
    through — code may hand ``Condition`` a pre-shim lock)."""
    return lock._lock if isinstance(lock, (TracedLock, TracedRLock)) \
        else lock


class TracedCondition:
    __slots__ = ("_wl", "_cond", "_key")

    def __init__(self, lock=None):
        self._wl = TracedRLock() if lock is None else lock
        self._cond = _real["Condition"](_raw(self._wl))
        # lockset identity is the (possibly shared) wrapper lock, so
        # ``with q.mutex`` and ``with q.not_empty`` intersect
        self._key = id(self._wl)

    def acquire(self, *args, **kwargs):
        return self._wl.acquire(*args, **kwargs)

    def release(self):
        self._wl.release()

    def __enter__(self):
        self._wl.acquire()
        return self

    def __exit__(self, *exc):
        self._wl.release()

    def wait(self, timeout=None):
        # the real wait releases the underlying lock for the duration:
        # mirror that in the lockset, then merge the notifier's clock
        # on wakeup (the notify→wake happens-before edge)
        depth = _det.suspend_lock(self._key)
        try:
            got = self._cond.wait(timeout)
        finally:
            _det.resume_lock(self._key, depth)
        _det.observe(("cv", id(self)))
        return got

    def wait_for(self, predicate, timeout=None):
        import time as _time

        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n=1):
        _det.publish(("cv", id(self)))
        self._cond.notify(n)

    def notify_all(self):
        _det.publish(("cv", id(self)))
        self._cond.notify_all()

    def __repr__(self):
        return f"<TracedCondition {self._cond!r}>"


class TracedEvent:
    __slots__ = ("_ev",)

    def __init__(self):
        self._ev = _real["Event"]()

    def set(self):
        _det.publish(("ev", id(self)))
        self._ev.set()

    def clear(self):
        self._ev.clear()

    def is_set(self):
        return self._ev.is_set()

    def wait(self, timeout=None):
        got = self._ev.wait(timeout)
        if got:
            # the set→wait-return happens-before edge
            _det.observe(("ev", id(self)))
        return got

    def __repr__(self):
        return f"<TracedEvent {self._ev!r}>"


# ---------------------------------------------------- thread + queue hooks
def _traced_start(self):
    if not getattr(self, "_hvd_race_wrapped", False):
        self._hvd_race_wrapped = True
        _det.on_thread_created(self)
        orig_run = self.run

        def run():
            _det.on_thread_begin(self)
            try:
                orig_run()
            finally:
                _det.on_thread_end(self)
                try:
                    del self.run  # break the wrapper's ref cycle
                except AttributeError:
                    pass

        self.run = run
    _real["Thread.start"](self)


def _traced_join(self, timeout=None):
    _real["Thread.join"](self, timeout)
    if not self.is_alive():
        # the child-exit→joiner happens-before edge
        _det.on_thread_joined(self)


def _traced_put(self, item, block=True, timeout=None):
    _det.fuzz()
    snap = _det.publish_fifo(("q", id(self)))
    try:
        _real["Queue.put"](self, item, block, timeout)
    except BaseException:
        _det.unpublish_fifo(("q", id(self)), snap)
        raise


def _traced_get(self, block=True, timeout=None):
    _det.fuzz()
    item = _real["Queue.get"](self, block, timeout)
    # the put→get happens-before edge (FIFO-paired with the producer)
    _det.observe_fifo(("q", id(self)))
    return item


# ------------------------------------------------- attribute instrumentation
def _should_instrument(cls):
    if cls in _instrumented or not isinstance(cls, type):
        return False
    if issubclass(cls, BaseException):
        return False  # raise/except machinery is not shared state
    # a base already carries the traced __getattribute__: the subclass
    # inherits it, and double wrapping would record every access twice
    return not any(base in _instrumented for base in cls.__mro__[1:])


def instrument_class(cls, relpath=None, guarded=None):
    """Wrap ``cls``'s attribute access with detector callbacks.  Safe
    to call at most once per class; subclasses of an instrumented base
    are covered through inheritance."""
    if not _should_instrument(cls):
        return
    _instrumented.add(cls)
    if relpath is None:
        relpath = _module_relpath(sys.modules.get(cls.__module__))
    _det.register_class(cls, relpath or "<unknown>", guarded=guarded)
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__

    def __getattribute__(self, name, _og=orig_get):
        value = _og(self, name)
        if name.startswith("_hvd") or name.startswith("__"):
            return value
        # data attributes only: methods (and other callables) are
        # immutable lookup traffic, not shared mutable state
        if not callable(value):
            _det.on_read(self, name)
        return value

    def __setattr__(self, name, value, _os=orig_set):
        if not name.startswith("_hvd") and not name.startswith("__"):
            if isinstance(value, (TracedLock, TracedRLock,
                                  TracedCondition, TracedEvent)):
                # the race just learned this lock's name: reports can
                # say "holding {RingPlane._lock}" instead of an id
                key = id(value._wl) if isinstance(
                    value, TracedCondition) else id(value)
                _det.register_lock_name(
                    key, f"{type(self).__name__}.{name}")
            elif not callable(value):
                _det.on_write(self, name)
            _os(self, name, value)
            return
        _os(self, name, value)

    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__


def _path_relpath(path):
    """Repo-relative forward-slash path (absolute when outside the
    repo) — finding keys and report paths both normalize through
    here."""
    path = os.path.abspath(path)
    rel = os.path.relpath(path, REPO_ROOT)
    if rel.startswith(".."):
        return path.replace(os.sep, "/")
    return rel.replace(os.sep, "/")


def _module_relpath(module):
    path = getattr(module, "__file__", None)
    return _path_relpath(path) if path else None


def _guarded_map(path):
    """{class name: {attr: owning lock}} — the lock-discipline
    declarations of the source file, reused from the hvd-lint model so
    a race report can name the annotation it contradicts."""
    try:
        from horovod_tpu.tools.lint.model import SourceModule

        with open(path, encoding="utf-8") as f:
            source = f.read()
        parsed = SourceModule(path, os.path.basename(path), source)
        return {name: cls.guarded
                for name, cls in parsed.classes.items() if cls.guarded}
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return {}


def _module_guarded(module):
    path = getattr(module, "__file__", None)
    return _guarded_map(path) if path else {}


def _in_scope(relpath):
    if not relpath:
        return False
    if "all" in _scope:
        return relpath.startswith("horovod_tpu/") \
            and not relpath.startswith("horovod_tpu/tools/")
    return any(relpath.endswith(suffix) for suffix in _scope)


def instrument_module(module):
    relpath = _module_relpath(module)
    guarded = _module_guarded(module)
    for value in list(vars(module).values()):
        if isinstance(value, type) \
                and value.__module__ == module.__name__:
            instrument_class(value, relpath=relpath,
                             guarded=guarded.get(value.__name__))


def instrument_namespace(namespace, path):
    """Instrument the classes a ``runpy``-loaded target script defined
    (``bin/hvd-race``'s fixture contract)."""
    relpath = _path_relpath(path)
    guarded = _guarded_map(path)
    for value in list(namespace.values()):
        if isinstance(value, type) and getattr(
                value, "__module__", "") in ("__main__",
                                             "__hvd_race_target__"):
            instrument_class(value, relpath=relpath,
                             guarded=guarded.get(value.__name__))


class _ScopeImportHook(importlib.abc.MetaPathFinder, importlib.abc.Loader):
    """Instruments scoped ``horovod_tpu`` modules as they import (the
    shim installs at package-import time, before the runtime modules
    load)."""

    def find_spec(self, fullname, path, target=None):
        if not fullname.startswith("horovod_tpu."):
            return None
        spec = importlib.machinery.PathFinder.find_spec(fullname, path)
        if spec is None or spec.loader is None:
            return None
        spec.loader = _LoaderProxy(spec.loader)
        return spec


class _LoaderProxy:
    def __init__(self, loader):
        self._loader = loader

    def create_module(self, spec):
        return self._loader.create_module(spec)

    def exec_module(self, module):
        self._loader.exec_module(module)
        if _det is not None and _in_scope(_module_relpath(module)):
            instrument_module(module)

    def __getattr__(self, name):
        return getattr(self._loader, name)


# ------------------------------------------------------------ installation
def install(scope=None, seed=None):
    """Patch the primitives and start detecting.  Idempotent."""
    global _det, _scope
    if _det is not None:
        return _det
    if seed is None:
        seed = env_util.get_int(env_util.HVD_TPU_RACE_SEED, 0)
    if scope is None:
        raw = env_util.get_str(env_util.HVD_TPU_RACE_SCOPE)
        scope = tuple(s.strip() for s in raw.split(",") if s.strip()) \
            if raw else DEFAULT_SCOPE
    _scope = tuple(scope)
    _det = Detector(REPO_ROOT, seed=seed)

    _t.Lock = TracedLock
    _t.RLock = TracedRLock
    _t.Condition = TracedCondition
    _t.Event = TracedEvent
    _t.Thread.start = _traced_start
    _t.Thread.join = _traced_join
    _queue_mod.Queue.put = _traced_put
    _queue_mod.Queue.get = _traced_get

    sys.meta_path.insert(0, _ScopeImportHook())
    for module in list(sys.modules.values()):
        if _in_scope(_module_relpath(module)):
            instrument_module(module)

    from horovod_tpu.tools.race import hooks
    hooks.attach(_det)

    report_path = env_util.get_str(env_util.HVD_TPU_RACE_REPORT)
    if report_path:
        atexit.register(_dump_report, report_path)
    return _det


def install_from_env():
    """``horovod_tpu/__init__`` entry: install iff HVD_TPU_RACE is on
    (the caller already checked, but double-gate so an accidental
    import of this module never arms the shim by itself)."""
    if env_util.get_bool(env_util.HVD_TPU_RACE):
        install()


def collect_findings():
    return _det.findings() if _det is not None else []


def _dump_report(prefix):
    """One JSON per process (``<prefix>.<pid>.json``): the suites spawn
    worker ranks that share the env contract, so every rank writes its
    own file and the gate test globs them up."""
    try:
        findings = collect_findings()
        with open(f"{prefix}.{os.getpid()}.json", "w") as f:
            json.dump({"findings": [x.as_dict() for x in findings]}, f,
                      indent=2)
    except Exception:  # noqa: BLE001 — report dump must never mask the
        pass           # process's own exit status


# the stock identities, exported so tests can prove neutrality against
# exactly what the shim would have replaced
STOCK = dict(_real)

# _thread is intentionally imported (and never patched): the detector's
# own lock comes from _thread.allocate_lock so shim internals cannot
# recurse through the traced wrappers
assert _thread.allocate_lock is not None
