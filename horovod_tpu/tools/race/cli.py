"""``bin/hvd-race`` — run a target under the race shim and report.

Usage::

    bin/hvd-race tests/race_fixtures/bad_unlocked_counter.py
    bin/hvd-race --seed 7 --format json target.py [args...]
    bin/hvd-race --write-baseline target.py     # refresh suppressions

Target contract: a Python script that defines its classes at module
level and exposes a ``main()`` — hvd-race loads the module, instruments
its classes (plus the scoped ``horovod_tpu`` runtime modules), then
calls ``main()`` and reports every race the run exposed.

Exit codes: 0 = clean (baselined findings included), 1 = active
findings, 2 = usage error, 3 = the target itself raised.  The baseline
lives at ``.hvd-race-baseline.json`` in the repo root and shares
hvd-lint's format and justification rules (docs/race_detection.md).
"""

import argparse
import json
import os
import runpy
import sys
import traceback

from horovod_tpu.tools.lint import findings as findings_mod
from horovod_tpu.tools.race import shim

DEFAULT_BASELINE = os.path.join(shim.REPO_ROOT,
                                ".hvd-race-baseline.json")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-race",
        description="Dynamic lockset + happens-before race detector "
                    "for the threaded runtime (docs/race_detection.md).")
    parser.add_argument("target", help="Python script to run under the "
                                       "shim (must define main()).")
    parser.add_argument("args", nargs=argparse.REMAINDER,
                        help="Arguments passed to the target's argv.")
    parser.add_argument("--seed", type=int, default=None,
                        help="Schedule-fuzz seed (default: "
                             "HVD_TPU_RACE_SEED, else 0); same seed -> "
                             "same preemption decisions -> same report.")
    parser.add_argument("--scope", default=None,
                        help="Comma-separated module relpath suffixes "
                             "to instrument ('all' = every horovod_tpu "
                             "module; default: the concurrency-scoped "
                             "runtime).")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--no-baseline", action="store_true",
                        help="Report every finding, suppressing "
                             "nothing.")
    parser.add_argument("--write-baseline", action="store_true",
                        help="Rewrite the baseline from this run's "
                             "findings (existing justifications kept; "
                             "new entries get a TODO the gate test "
                             "rejects until justified).")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text")
    args = parser.parse_args(argv)

    scope = None
    if args.scope:
        scope = tuple(s.strip() for s in args.scope.split(",")
                      if s.strip())
    shim.install(scope=scope, seed=args.seed)

    sys.argv = [args.target] + list(args.args)
    target_error = None
    try:
        namespace = runpy.run_path(args.target,
                                   run_name="__hvd_race_target__")
        shim.instrument_namespace(namespace, args.target)
        entry = namespace.get("main")
        if not callable(entry):
            parser.error(f"{args.target} defines no main()")
        entry()
    except SystemExit:
        raise
    except BaseException:  # noqa: BLE001 — report races seen so far,
        # then surface the crash distinctly from "active findings"
        target_error = traceback.format_exc()

    all_findings = shim.collect_findings()
    baseline = {} if args.no_baseline \
        else findings_mod.load_baseline(args.baseline)
    if args.write_baseline:
        if target_error is not None:
            # a truncated run observed only a prefix of the findings:
            # regenerating from it would silently prune every
            # justified suppression the crash prevented re-observing
            sys.stderr.write(target_error)
            sys.stderr.write("hvd-race: target crashed — baseline NOT "
                             "rewritten (a partial run must not prune "
                             "suppressions)\n")
            return 3
        previous = findings_mod.load_baseline(args.baseline)
        findings_mod.write_baseline(args.baseline, all_findings,
                                    previous=previous)
        written = len(findings_mod.load_baseline(args.baseline))
        print(f"wrote {written} suppression(s) to {args.baseline}")
        return 0
    active, suppressed, stale = findings_mod.split_baselined(
        all_findings, baseline)

    if args.format == "json":
        json.dump({
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline_keys": stale,
        }, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for finding in active:
            print(finding.render())
        summary = (f"hvd-race: {len(active)} finding(s), "
                   f"{len(suppressed)} baselined")
        if stale:
            summary += (f", {len(stale)} stale baseline key(s) — run "
                        f"--write-baseline to prune")
        print(summary)
    if target_error is not None:
        sys.stderr.write(target_error)
        return 3
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
