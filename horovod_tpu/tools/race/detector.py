"""Hybrid lockset / happens-before race detection.

The model (docs/race_detection.md):

- every traced thread carries a **vector clock** and a **lockset** (the
  traced locks it currently holds);
- **happens-before edges** come from thread start/join, ``queue.Queue``
  put→get, ``Condition`` notify→wake, ``Event`` set→wait, and explicit
  runtime channels (the PeerService mailbox deliver→recv hook in
  ``ops/tcp_dataplane.py``).  Plain lock acquire/release deliberately
  creates NO edge — that is the Eraser insight: two accesses that
  happen to be ordered by coincidental lock timing are still a race if
  no lock is *common* to both;
- every attribute read/write on an instrumented class records
  ``(epoch, lockset, site)`` per location ``(object, attr)``.  Two
  accesses to the same location by different threads **race** when at
  least one is a write, neither happens-before the other, and their
  locksets are disjoint.

Reports carry both racing access sites, the location's ownership
history, and the ``# guarded by self._X`` lock-discipline annotation
(if the owning class declares one) that the race contradicts.  They
are rendered as :class:`horovod_tpu.tools.lint.findings.Finding`
objects so the hvd-lint baseline machinery
(``.hvd-race-baseline.json``) applies unchanged.

Determinism: report keys and messages are built only from source
locations, attribute names, thread *names* and sorted participant
sets — never from object ids, clock values or timestamps — so a rerun
under the same ``HVD_TPU_RACE_SEED`` yields byte-identical findings.

Deliberate lock-free accesses are suppressed at the access site with
an ``# hvd-race: ok[reason]`` comment (the existing
``# hvd-lint: ignore[lock-discipline]`` annotations are honored too:
a read the static checker was told is deliberately lock-free is the
same statement to the dynamic checker).
"""

import _thread
import linecache
import os
import re
import sys
import threading as _threading_mod
import weakref

from horovod_tpu.tools.lint.findings import Finding
from horovod_tpu.tools.race.fuzz import ScheduleFuzzer, thread_key

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_RACE_OK_RE = re.compile(
    r"hvd-race:\s*ok|hvd-lint:\s*ignore\[[^\]]*lock-discipline")

# bounded side-channel storage: a long-running job must not grow the
# detector without bound however many mailbox chunks it moves
_MAX_CHANNELS = 8192
_MAX_LOCATIONS = 65536

# sentinel for objects that cannot be weakref'd (__slots__ without
# __weakref__): their identity over an id() can't be verified, so their
# locations reset on every __init__ write instead
_FRAGILE = object()
_MISSING = object()


class _ThreadState:
    __slots__ = ("tid", "name", "key", "clock", "lockset", "counter",
                 "busy")

    def __init__(self, tid, name):
        self.tid = tid
        self.name = name
        self.key = thread_key(name)
        self.clock = {tid: 1}
        self.lockset = {}        # lock key -> hold count
        self.counter = 0         # fuzz draw counter
        self.busy = False        # reentrancy guard


class _Access:
    __slots__ = ("tid", "epoch", "lockset", "site", "thread_name")

    def __init__(self, tid, epoch, lockset, site, thread_name):
        self.tid = tid
        self.epoch = epoch
        self.lockset = lockset
        self.site = site          # (relpath, line, func)
        self.thread_name = thread_name


class _Location:
    __slots__ = ("cls", "attr", "writes", "reads", "first_writer",
                 "participants")

    def __init__(self, cls, attr):
        self.cls = cls
        self.attr = attr
        self.writes = {}          # tid -> _Access
        self.reads = {}           # tid -> _Access
        self.first_writer = None  # thread name of the first write
        self.participants = set()  # thread names that touched it


# distinguishes "no report yet" from the None that marks a key
# suppressed by an ignored site
_UNSEEN = object()


class RaceReport:
    """One deduplicated race: a (class, attribute, kind) triple with
    the canonical (lowest-sorting) pair of racing accesses observed."""

    __slots__ = ("relpath", "cls_name", "attr", "kind", "access_a",
                 "access_b", "first_writer", "participants", "guarded_by")

    def __init__(self, relpath, cls_name, attr, kind, access_a,
                 access_b, first_writer, participants, guarded_by):
        self.relpath = relpath
        self.cls_name = cls_name
        self.attr = attr
        self.kind = kind          # "write-write" | "read-write"
        # canonical order: sorted by (site, thread name) so the same
        # race renders identically whichever access detected it
        self.access_a = access_a  # (role, site, thread_name, locknames)
        self.access_b = access_b
        self.first_writer = first_writer
        self.participants = participants  # sorted thread names
        self.guarded_by = guarded_by      # declared owning lock | None


class Detector:
    def __init__(self, repo_root, seed=0):
        self.repo_root = repo_root
        self.fuzzer = ScheduleFuzzer(seed)
        self._lock = _thread.allocate_lock()  # stock, never traced
        self._tls = _threading_mod.local()
        self._next_tid = 0
        self._locations = {}      # (objid, attr) -> _Location
        self._by_obj = {}         # objid -> set of attrs with locations
        self._live = {}           # objid -> weakref | _FRAGILE
        self._channels = {}       # channel key -> clock snapshot | list
        self._reports = {}        # dedup key -> RaceReport
        self._suppressed = 0      # annotation-suppressed race count
        self._class_info = {}     # cls -> (name, relpath) | None
        self._lock_names = {}     # lock key -> "Cls.attr"
        self._guarded = {}        # (relpath, clsname) -> {attr: lock}

    # ------------------------------------------------------------ threads
    def state(self):
        ts = getattr(self._tls, "state", None)
        if ts is None:
            with self._lock:
                self._next_tid += 1
                tid = self._next_tid
            # NEVER threading.current_thread() here: during thread
            # bootstrap (_started.set() fires before _active
            # registration) it would fabricate a _DummyThread whose
            # OWN _started event re-enters this path, recursing
            # forever.  Read the registry directly; threads traced by
            # the shim get their real name in on_thread_begin.
            thread = _threading_mod._active.get(_thread.get_ident())
            name = thread.name if thread is not None else "(bootstrap)"
            ts = _ThreadState(tid, name)
            self._tls.state = ts
        return ts

    def _tick_snapshot(self, ts):
        """Snapshot-then-increment: accesses made before this point are
        covered by the snapshot, accesses after it are not."""
        snap = dict(ts.clock)
        ts.clock[ts.tid] = ts.clock.get(ts.tid, 0) + 1
        return snap

    def _merge(self, ts, snap):
        if not snap:
            return
        clock = ts.clock
        for tid, c in snap.items():
            if clock.get(tid, 0) < c:
                clock[tid] = c

    def on_thread_created(self, thread):
        """Parent side of ``Thread.start``: the child inherits
        everything the parent did up to here."""
        ts = self.state()
        thread._hvd_race_parent_clock = self._tick_snapshot(ts)

    def on_thread_begin(self, thread):
        ts = self.state()
        ts.name = thread.name
        ts.key = thread_key(thread.name)
        self._merge(ts, getattr(thread, "_hvd_race_parent_clock", None))

    def on_thread_end(self, thread):
        ts = self.state()
        thread._hvd_race_final_clock = self._tick_snapshot(ts)

    def on_thread_joined(self, thread):
        self._merge(self.state(),
                    getattr(thread, "_hvd_race_final_clock", None))

    # -------------------------------------------------------------- locks
    def fuzz(self):
        ts = self.state()
        if ts.busy:
            return
        ts.counter += 1
        self.fuzzer.maybe_preempt(ts.key, ts.counter)

    def on_acquire(self, key):
        ls = self.state().lockset
        ls[key] = ls.get(key, 0) + 1

    def on_release(self, key):
        ls = self.state().lockset
        n = ls.get(key, 0) - 1
        if n <= 0:
            ls.pop(key, None)
        else:
            ls[key] = n

    def suspend_lock(self, key):
        """Condition.wait releases the underlying lock (all recursion
        levels): drop it from the lockset, remembering the depth."""
        return self.state().lockset.pop(key, 0)

    def resume_lock(self, key, count):
        if count:
            self.state().lockset[key] = count

    def register_lock_name(self, key, name):
        with self._lock:
            self._lock_names.setdefault(key, name)

    # -------------------------------------------- happens-before channels
    def publish(self, channel):
        """Single-slot channel: the latest publisher's clock is what an
        observer merges (condition notify, event set, mailbox deliver)."""
        ts = self.state()
        snap = self._tick_snapshot(ts)
        with self._lock:
            self._channels[("s", channel)] = snap
            self._trim_channels()

    def observe(self, channel):
        with self._lock:
            snap = self._channels.get(("s", channel))
        self._merge(self.state(), snap)

    def publish_fifo(self, channel):
        """FIFO channel (queue put→get): snapshots pair up in queue
        order.  Multi-producer pairing is approximate — a swapped pair
        merges a sibling producer's clock, which can only ever create
        an extra edge, never a false race."""
        ts = self.state()
        snap = self._tick_snapshot(ts)
        with self._lock:
            fifo = self._channels.setdefault(("f", channel), [])
            fifo.append(snap)
            self._trim_channels()
        return snap

    def unpublish_fifo(self, channel, snap):
        """Roll back a ``publish_fifo`` whose operation failed (a
        ``queue.Full`` put published nothing)."""
        with self._lock:
            fifo = self._channels.get(("f", channel))
            if fifo and snap in fifo:
                fifo.remove(snap)

    def observe_fifo(self, channel):
        with self._lock:
            fifo = self._channels.get(("f", channel))
            snap = fifo.pop(0) if fifo else None
        self._merge(self.state(), snap)

    def _trim_channels(self):  # holds: self._lock
        while len(self._channels) > _MAX_CHANNELS:
            self._channels.pop(next(iter(self._channels)))

    # ---------------------------------------------------------- classes
    def register_class(self, cls, relpath, guarded=None):
        with self._lock:
            self._class_info[cls] = (cls.__name__, relpath)
            if guarded:
                self._guarded[(relpath, cls.__name__)] = dict(guarded)

    def _info_for(self, cls):  # holds: self._lock
        info = self._class_info.get(cls)
        if info is None:
            # subclass of an instrumented base: attribute to the
            # nearest registered ancestor's module
            for base in cls.__mro__[1:]:
                base_info = self._class_info.get(base)
                if base_info is not None:
                    info = (cls.__name__, base_info[1])
                    break
            else:
                info = (cls.__name__, "<unknown>")
            self._class_info[cls] = info
        return info

    # ----------------------------------------------------------- accesses
    def on_read(self, obj, attr):
        self._on_access(obj, attr, is_write=False)

    def on_write(self, obj, attr):
        ts = self.state()
        if ts.busy:
            return
        ts.counter += 1
        self.fuzzer.maybe_preempt(ts.key, ts.counter)
        self._on_access(obj, attr, is_write=True)

    def _on_access(self, obj, attr, is_write):
        ts = self.state()
        if ts.busy:
            return
        ts.busy = True
        try:
            site = self._user_site()
            if site is None:
                return
            epoch = ts.clock.get(ts.tid, 1)
            lockset = frozenset(ts.lockset)
            cls = type(obj)
            objid = id(obj)
            lkey = (objid, attr)
            with self._lock:
                self._verify_identity(obj, objid)
                loc = self._locations.get(lkey)
                if loc is None:
                    if len(self._locations) >= _MAX_LOCATIONS:
                        old_key = next(iter(self._locations))
                        self._locations.pop(old_key)
                        attrs = self._by_obj.get(old_key[0])
                        if attrs is not None:
                            attrs.discard(old_key[1])
                    loc = self._locations[lkey] = _Location(cls, attr)
                    self._by_obj.setdefault(objid, set()).add(attr)
                if is_write and site[2] == "__init__":
                    # a constructor write marks a FRESH object: any
                    # history under this id belongs to a dead
                    # predecessor that recycled the address (short-
                    # lived message objects do this constantly) — a
                    # same-object __init__ racing another thread is
                    # not a pattern this runtime can produce
                    loc.writes.clear()
                    loc.reads.clear()
                    loc.participants.clear()
                    loc.first_writer = None
                    loc.cls = cls
                loc.participants.add(ts.name)
                racy = []
                for other in loc.writes.values():
                    # constructor writes count as publication (the
                    # Eraser initialization state): an object built by
                    # one thread and handed off read-only is the
                    # runtime's standard message pattern, not a race —
                    # the first POST-constructor write re-arms the
                    # location and races normally
                    if other.site[2] == "__init__":
                        continue
                    if self._races(ts, lockset, other):
                        racy.append((other, "w"))
                if is_write:
                    for other in loc.reads.values():
                        if self._races(ts, lockset, other):
                            racy.append((other, "r"))
                access = _Access(ts.tid, epoch, lockset, site, ts.name)
                if is_write:
                    if loc.first_writer is None:
                        loc.first_writer = ts.name
                    loc.writes[ts.tid] = access
                else:
                    self._covered_reads_prune(loc)
                    loc.reads[ts.tid] = access
                for other, other_role in racy:
                    self._report(loc, access,
                                 "w" if is_write else "r",
                                 other, other_role)
        finally:
            ts.busy = False

    def _verify_identity(self, obj, objid):  # holds: self._lock
        """CPython recycles addresses: an id() seen before may now name
        a different object, and inheriting the dead predecessor's
        access history fabricates races.  A liveness weakref per id
        catches the recycle and purges the stale locations; objects
        that cannot be weakref'd fall back to the __init__-reset rule
        in ``_on_access``."""
        live = self._live.get(objid, _MISSING)
        if live is not _MISSING:
            if live is _FRAGILE or live() is obj:
                return
            for attr in self._by_obj.pop(objid, ()):
                self._locations.pop((objid, attr), None)
        if len(self._live) >= _MAX_LOCATIONS:
            self._live.pop(next(iter(self._live)))
        try:
            self._live[objid] = weakref.ref(obj)
        except TypeError:
            self._live[objid] = _FRAGILE

    def _races(self, ts, lockset, other):  # holds: self._lock
        if other.tid == ts.tid:
            return False
        # happens-before: the other access is covered when this
        # thread's clock component for the accessing thread has reached
        # its epoch
        if ts.clock.get(other.tid, 0) >= other.epoch:
            return False
        return not (lockset & other.lockset)

    def _covered_reads_prune(self, loc):  # holds: self._lock
        if len(loc.reads) > 16:
            loc.reads.clear()

    # ------------------------------------------------------------ reports
    def _user_site(self, depth=2, frames=1):
        """(relpath, line, func) of the nearest frame outside this
        package; ``frames`` > 1 returns a tuple of up to that many."""
        out = []
        try:
            f = sys._getframe(depth)
        except ValueError:
            return None
        while f is not None and len(out) < frames:
            fn = f.f_code.co_filename
            if not fn.startswith(_PKG_DIR):
                out.append((self._rel(fn), f.f_lineno,
                            f.f_code.co_name))
            f = f.f_back
        if not out:
            return None
        return out[0] if frames == 1 else tuple(out)

    def _rel(self, path):
        try:
            rel = os.path.relpath(path, self.repo_root)
        except ValueError:
            return path.replace(os.sep, "/")
        if rel.startswith(".."):
            return path.replace(os.sep, "/")
        return rel.replace(os.sep, "/")

    def _site_ignored(self, site):
        """Honor ``# hvd-race: ok[...]`` (and the static checker's
        lock-discipline ignores) on either racing line or the
        contiguous block of pure comment lines directly above it —
        the same convention hvd-lint's ``annotated()`` applies."""
        relpath, line, _func = site
        path = os.path.join(self.repo_root, relpath) \
            if not os.path.isabs(relpath) else relpath
        if _RACE_OK_RE.search(linecache.getline(path, line)):
            return True
        ln = line - 1
        while ln >= 1:
            text = linecache.getline(path, ln)
            if not text.lstrip().startswith("#"):
                return False
            if _RACE_OK_RE.search(text):
                return True
            ln -= 1
        return False

    def _lock_label(self, lockset):  # holds: self._lock
        names = sorted(self._lock_names.get(k, "?") for k in lockset)
        if not names:
            return "no locks"
        return "holding {" + ", ".join(names) + "}"

    def _report(self, loc, access, role, other, other_role):
        # holds: self._lock
        kind = "write-write" if role == "w" and other_role == "w" \
            else "read-write"
        cls_name, relpath = self._info_for(loc.cls)
        key = (relpath, cls_name, loc.attr, kind)
        prev = self._reports.get(key, _UNSEEN)
        if prev is None:
            return  # an ignored pair pinned this key as suppressed
        if self._site_ignored(access.site) \
                or self._site_ignored(other.site):
            if prev is _UNSEEN:
                self._suppressed += 1
                self._reports[key] = None  # don't re-evaluate per access
            return
        sides = sorted([
            (role, access.site, access.thread_name,
             self._lock_label(access.lockset)),
            (other_role, other.site, other.thread_name,
             self._lock_label(other.lockset)),
        ], key=lambda s: (s[1], s[2], s[0]))
        # Keep the LOWEST-sorting racing pair seen for this key, not
        # the first-detected one: which symmetric pair fires first is
        # an OS-interleaving accident (it even shifts with the
        # interpreter's hash seed), while the minimum over the pairs a
        # run observes is stable — so the same-seed determinism
        # contract doesn't ride on detection order.
        if prev is not _UNSEEN \
                and (prev.access_a, prev.access_b) <= (sides[0], sides[1]):
            return
        guarded = self._guarded.get((relpath, cls_name), {})
        self._reports[key] = RaceReport(
            relpath, cls_name, loc.attr, kind, sides[0], sides[1],
            loc.first_writer, sorted(loc.participants),
            guarded.get(loc.attr))

    # ------------------------------------------------------------ results
    def findings(self):
        """Render the deduplicated reports as lint Findings (sorted, so
        the same run always serializes identically)."""
        with self._lock:
            reports = [r for r in self._reports.values()
                       if r is not None]
        out = []
        for r in reports:
            def fmt(side):
                role, site, tname, locks = side
                return (f"[{role}] {site[0]}:{site[1]} {site[2]} "
                        f"({tname}; {locks})")

            msg = (f"data race ({r.kind}) on {r.cls_name}.{r.attr}: "
                   f"{fmt(r.access_a)} <-> {fmt(r.access_b)}; "
                   f"first write by {r.first_writer or 'none'}, "
                   f"shared by {', '.join(r.participants)}")
            if r.guarded_by:
                msg += (f"; contradicts declared '# guarded by "
                        f"self.{r.guarded_by}'")
            out.append(Finding(
                checker="race", path=r.relpath,
                line=max(r.access_a[1][1], r.access_b[1][1]),
                context=r.cls_name, detail=f"{r.attr}:{r.kind}",
                message=msg))
        out.sort(key=lambda f: (f.path, f.context, f.detail))
        return out
