"""hvd-race — dynamic concurrency sanitizer for the threaded runtime.

The runtime reproduces the reference's background-thread architecture
(sender threads per RingPlane, MuxService reader loops, heartbeat and
stall-inspector threads, autotune publication) in Python.  hvd-lint
(docs/linting.md) enforces the *declared* lock discipline statically;
this package finds what static analysis cannot see — shared state
nobody annotated, and cross-thread ordering bugs — by watching the
program actually run:

- :mod:`shim` patches ``threading.Lock/RLock/Condition/Event``,
  ``queue.Queue`` and ``Thread`` start/join with traced wrappers, and
  instruments attribute access on the classes of the concurrency-scoped
  modules.  Installed only when ``HVD_TPU_RACE`` is set — with the
  variable unset the stock classes are untouched and this package is
  never imported.
- :mod:`detector` runs the hybrid analysis: per-location Eraser-style
  locksets refined by vector-clock happens-before edges (thread
  start/join, ``queue`` put→get, condition notify→wake, event
  set→wait, and the PeerService mailbox deliver→recv hook).  Two
  accesses to the same attribute race when they are concurrent (no
  happens-before path) and their locksets are disjoint.
- :mod:`fuzz` injects short, seeded preemptions at instrumentation
  points (``HVD_TPU_RACE_SEED``, same determinism contract as
  ``HVD_TPU_FAULT_SPEC``) so narrow interleavings reproduce
  run-to-run.
- :mod:`cli` is ``bin/hvd-race``: runs a target under the shim and
  reports findings through the same baseline machinery as hvd-lint
  (``.hvd-race-baseline.json``, justification-preserving regeneration,
  text/JSON output, exit 0/1).

Model, annotations and the baseline workflow: docs/race_detection.md.
"""
