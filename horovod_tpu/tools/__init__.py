"""Developer tooling that ships with the framework (not imported by the
runtime): ``tools.lint`` is the project-invariant static-analysis suite
behind ``bin/hvd-lint`` (docs/linting.md)."""
