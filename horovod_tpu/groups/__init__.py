"""Process groups: concurrent sub-communicators (docs/groups.md).

A :class:`ProcessGroup` is a named subset of the world that owns its
own negotiation namespace: the group id joins every request signature,
response-cache key, and fusion bucket key (the PR 1 bucket-key
separation and the PR 9 never-fuse rules are the template), so
collectives from different groups never fuse, never cache-collide, and
can be concurrently in flight on both data planes — per-group ring
planes and group-qualified ring-id namespaces on the TCP plane,
per-(group, signature) memoized sub-executors on the XLA plane.

Groups are a PURE FUNCTION of the membership and their rank-spec
(reference: Horovod process sets, arXiv:1802.05799 §4): the registry
records each group's member WORKER IDS at creation, and an elastic
reconfiguration re-forms every group at the new epoch by remapping
those ids onto the survivors' new ranks.  A grid re-plans from the
surviving membership; an explicit rank list that references a departed
worker becomes typed-unsatisfiable — using the handle raises
:class:`GroupUnsatisfiableError` instead of hanging a negotiation.

The handle is a stable key, not a snapshot: ``group.ranks`` /
``group.size`` / ``group.rank()`` always read the CURRENT incarnation
from the registry, so a handle created before a reconfiguration keeps
working after it (or fails typed, never stale)."""

import hashlib
import threading

import numpy as np

from horovod_tpu.common.handles import HvdError
from horovod_tpu.utils import env as env_util


class GroupUnsatisfiableError(HvdError):
    """An explicit-rank group references a departed worker: the spec
    cannot be satisfied by the surviving membership, so the group is
    dead — typed, so callers can tell "re-create me" from a hang."""

    def __init__(self, name, missing):
        self.group_name = name
        self.missing = tuple(sorted(missing))
        super().__init__(
            f"process group '{name}' is unsatisfiable after "
            f"reconfiguration: worker id(s) {list(self.missing)} "
            f"departed (explicit rank lists do not re-plan; re-create "
            f"the group from the surviving membership)")


class _Spec:
    """What a group IS, membership-independently: the worker ids (or
    grid shape) it was created from.  ``reform`` re-derives the live
    incarnation from (spec, members) — nothing else."""

    __slots__ = ("gid", "name", "kind", "wids", "sizes", "axis",
                 "coords")

    def __init__(self, gid, name, kind, wids=None, sizes=None,
                 axis=None, coords=None):
        self.gid = gid
        self.name = name
        self.kind = kind          # "ranks" | "grid"
        self.wids = wids          # tuple of worker ids ("ranks")
        self.sizes = sizes        # ordered (axis, size) tuple ("grid")
        self.axis = axis          # grid axis this group runs along
        self.coords = coords      # fixed coords on the other axes


class ProcessGroup:
    """Handle for a sub-communicator.  Accepted via ``group=`` by every
    public collective; identity is the deterministic ``gid`` (identical
    on every rank creating the same spec, no communication needed)."""

    __slots__ = ("gid", "name")

    def __init__(self, gid, name):
        self.gid = gid
        self.name = name

    @property
    def ranks(self):
        """Current member ranks (re-mapped at each elastic epoch)."""
        return live_ranks(self.gid)

    @property
    def size(self):
        return len(self.ranks)

    def rank(self, global_rank=None):
        """Group-local rank of ``global_rank`` (default: the calling
        rank), or -1 when it is not a member."""
        if global_rank is None:
            from horovod_tpu.common import basics
            global_rank = basics.rank()
        ranks = self.ranks
        try:
            return ranks.index(int(global_rank))
        except ValueError:
            return -1

    def __contains__(self, global_rank):
        return int(global_rank) in self.ranks

    def __repr__(self):
        return (f"ProcessGroup(name={self.name!r}, gid={self.gid!r}, "
                f"ranks={list(live_ranks(self.gid, strict=False) or ())})")


# ------------------------------------------------------------- registry
_lock = threading.RLock()
_specs = {}          # gid -> _Spec
_live = {}           # gid -> tuple(ranks) | GroupUnsatisfiableError
_tl = threading.local()   # per-rank-thread auto-name counters
_stats_lock = threading.Lock()
_max_inflight = 0    # high-water mark of distinct groups in flight


def _auto_seq(key):
    """Deterministic per-rank-thread sequence number for ``key``: every
    rank's n-th creation of the same spec names the same group (same
    pattern as eager's thread-local auto-names)."""
    counters = getattr(_tl, "counters", None)
    if counters is None:
        counters = _tl.counters = {}
    n = counters.get(key, 0)
    counters[key] = n + 1
    return n


def _gid(name, wids):
    return hashlib.sha1(
        f"{name}|{','.join(str(w) for w in wids)}".encode()
    ).hexdigest()[:12]


def _members():
    """Current worker-id list in rank order (identity before any
    elastic reconfiguration)."""
    from horovod_tpu.common import basics
    return basics.members()


def _max_groups():
    return env_util.get_int(env_util.HVD_TPU_GROUP_MAX,
                            env_util.DEFAULT_GROUP_MAX)


def new_group(ranks, name=None):
    """Create (or return) the process group over ``ranks``.

    ``ranks`` are CURRENT global ranks; the registry records the
    corresponding worker ids, so the group survives reconfigurations
    that keep all members alive and fails typed otherwise.  Identical
    calls on different ranks converge on the identical handle — the
    auto-name is a deterministic per-thread sequence, never random."""
    from horovod_tpu.common import basics
    world = basics.size()
    rank_list = tuple(sorted({int(r) for r in ranks}))
    if not rank_list:
        raise HvdError("new_group: empty rank list")
    if rank_list[0] < 0 or rank_list[-1] >= world:
        raise HvdError(
            f"new_group: ranks {list(rank_list)} out of range for "
            f"world size {world}")
    members = _members()
    wids = tuple(members[r] for r in rank_list)
    if name is None:
        name = f"group.{rank_list[0]}-{rank_list[-1]}" \
               f".{_auto_seq(('ranks', rank_list))}"
    gid = _gid(name, wids)
    with _lock:
        if gid not in _specs:
            if len(_specs) >= _max_groups():
                raise HvdError(
                    f"new_group: more than {_max_groups()} live "
                    f"process groups (HVD_TPU_GROUP_MAX); groups leak "
                    f"— create them once, not per step")
            _specs[gid] = _Spec(gid, name, "ranks", wids=wids)
            _live[gid] = rank_list
    return ProcessGroup(gid, name)


class Grid:
    """DP x TP x PP (x anything) rank grid: world ranks arranged
    C-order over the named axes — the SAME layout
    ``parallel.mesh.make_mesh`` gives the device mesh, so
    ``grid.group(axis)`` and the mesh axis of the same name always
    name the same peers."""

    __slots__ = ("name", "sizes", "_groups")

    def __init__(self, name, sizes, groups):
        self.name = name
        self.sizes = sizes          # ordered (axis, size) tuple
        self._groups = groups       # axis -> {coords: ProcessGroup}

    @property
    def axes(self):
        return tuple(a for a, _ in self.sizes)

    def group(self, axis, rank=None):
        """The ``axis`` group containing ``rank`` (default: caller)."""
        if rank is None:
            from horovod_tpu.common import basics
            rank = basics.rank()
        coords = self.coords(rank)
        key = tuple(c for (a, _), c in zip(self.sizes, coords)
                    if a != axis)
        try:
            return self._groups[axis][key]
        except KeyError:
            raise HvdError(
                f"grid '{self.name}': no {axis!r} group for rank "
                f"{rank}") from None

    def coords(self, rank):
        """(axis coords) of ``rank`` in C-order, mirroring make_mesh."""
        shape = tuple(s for _, s in self.sizes)
        return tuple(int(c) for c in np.unravel_index(int(rank), shape))

    def mesh_axes(self):
        """Axis-shape dict for ``make_mesh`` (insertion order kept)."""
        return dict(self.sizes)


def grid(**axes):
    """``hvd.grid(dp=..., tp=..., pp=...)``: partition the world into
    one group per line of each named axis.  Axis order follows the
    kwargs (C-order, consistent with ``MeshAxes``/``make_mesh``); the
    axis sizes must multiply to the world size.  Grid groups RE-PLAN at
    an elastic reconfiguration: the same shape is recomputed over the
    surviving membership, or the grid turns typed-unsatisfiable when
    the shape no longer fits."""
    from horovod_tpu.common import basics
    world = basics.size()
    sizes = tuple((str(a), int(s)) for a, s in axes.items() if s)
    if not sizes:
        raise HvdError("grid: at least one axis size is required")
    total = 1
    for _, s in sizes:
        if s <= 0:
            raise HvdError(f"grid: axis sizes must be positive: {axes}")
        total *= s
    if total != world:
        raise HvdError(
            f"grid: axis sizes {dict(sizes)} multiply to {total}, but "
            f"the world has {world} ranks")
    gname = f"grid.{'x'.join(f'{a}{s}' for a, s in sizes)}" \
            f".{_auto_seq(('grid', sizes))}"
    members = _members()
    groups = _plan_grid(gname, sizes, members, register=True)
    return Grid(gname, sizes, groups)


def _plan_grid(gname, sizes, members, register):
    """Form every axis group of a grid over ``members`` (rank i is
    worker members[i]).  Registration is idempotent by gid."""
    shape = tuple(s for _, s in sizes)
    arr = np.arange(int(np.prod(shape))).reshape(shape)
    groups = {}
    with _lock:
        for i, (axis, _) in enumerate(sizes):
            per_axis = {}
            moved = np.moveaxis(arr, i, -1)
            flat = moved.reshape(-1, shape[i])
            other_shape = moved.shape[:-1]
            for j in range(flat.shape[0]):
                coords = tuple(
                    int(c) for c in np.unravel_index(j, other_shape)) \
                    if other_shape else ()
                ranks = tuple(int(r) for r in flat[j])
                name = f"{gname}.{axis}." \
                       f"{'-'.join(str(c) for c in coords) or '0'}"
                wids = tuple(members[r] for r in ranks)
                gid = _gid(name, wids)
                if register and gid not in _specs:
                    _specs[gid] = _Spec(
                        gid, name, "grid", wids=wids, sizes=sizes,
                        axis=axis, coords=coords)
                    _live[gid] = ranks
                per_axis[coords] = ProcessGroup(gid, name)
            groups[axis] = per_axis
    return groups


def live_ranks(gid, strict=True):
    """Current global ranks of group ``gid``.  Raises the group's
    sticky :class:`GroupUnsatisfiableError` when a reconfiguration made
    it unsatisfiable (``strict=False``: return None instead)."""
    with _lock:
        cur = _live.get(gid)
    if cur is None:
        if strict:
            raise HvdError(f"unknown process group id {gid!r} (created "
                           f"before the last hvd.init()?)")
        return None
    if isinstance(cur, GroupUnsatisfiableError):
        if strict:
            raise cur
        return None
    return list(cur)


def resolve(group):
    """(gid, ranks) for a ``group=`` argument: (\"\", None) for the
    world (None), else the group's id and CURRENT member ranks.  The
    single choke point every collective goes through — unsatisfiable
    groups fail typed here, before anything reaches a controller."""
    if group is None:
        return "", None
    if not isinstance(group, ProcessGroup):
        raise HvdError(
            f"group= expects a ProcessGroup from hvd.new_group()/"
            f"hvd.grid(), got {type(group).__name__}")
    return group.gid, tuple(live_ranks(group.gid))


def reform(members):
    """Re-form every registered group for the new membership (called
    from the elastic reconfiguration path, under the state lock).  A
    group is a pure function of (spec, members): explicit-rank groups
    keep exactly their recorded workers (missing worker => typed
    unsatisfiable); grid groups re-plan the same shape over the
    survivors when it still fits."""
    members = list(members)
    pos = {w: r for r, w in enumerate(members)}
    with _lock:
        grids_replanned = set()
        for gid, spec in list(_specs.items()):
            if spec.kind == "ranks":
                missing = [w for w in spec.wids if w not in pos]
                if missing:
                    _live[gid] = GroupUnsatisfiableError(spec.name,
                                                         missing)
                else:
                    _live[gid] = tuple(sorted(pos[w]
                                              for w in spec.wids))
            else:    # grid: re-plan the shape over the new membership
                base = spec.name.rsplit(f".{spec.axis}.", 1)[0]
                shape_total = 1
                for _, s in spec.sizes:
                    shape_total *= s
                if (base, spec.sizes) in grids_replanned:
                    continue
                grids_replanned.add((base, spec.sizes))
                if shape_total != len(members):
                    err = GroupUnsatisfiableError(
                        base, [w for w in spec.wids if w not in pos])
                    for g2, s2 in _specs.items():
                        if s2.kind == "grid" and s2.sizes == spec.sizes \
                                and s2.name.startswith(base + "."):
                            _live[g2] = err
                    continue
                # same shape over the survivors, C-order: each existing
                # gid keeps its (axis, coords) slot with the NEW ranks
                shape = tuple(s for _, s in spec.sizes)
                arr = np.arange(shape_total).reshape(shape)
                for g2, s2 in _specs.items():
                    if s2.kind != "grid" or s2.sizes != spec.sizes \
                            or not s2.name.startswith(base + "."):
                        continue
                    i = [a for a, _ in s2.sizes].index(s2.axis)
                    moved = np.moveaxis(arr, i, -1)
                    other_shape = moved.shape[:-1]
                    j = int(np.ravel_multi_index(s2.coords,
                                                 other_shape)) \
                        if other_shape else 0
                    ranks = tuple(
                        int(r) for r in moved.reshape(-1, shape[i])[j])
                    _live[g2] = ranks
                    _specs[g2] = _Spec(
                        g2, s2.name, "grid",
                        wids=tuple(members[r] for r in ranks),
                        sizes=s2.sizes, axis=s2.axis, coords=s2.coords)


def reset():
    """Forget every group (hvd.init/shutdown boundary: groups belong
    to one job, and a fresh world must not inherit stale specs)."""
    global _max_inflight
    with _lock:
        _specs.clear()
        _live.clear()
    _tl.counters = {}
    with _stats_lock:
        _max_inflight = 0


def note_inflight(gids):
    """Record the number of DISTINCT sub-groups with negotiation
    entries open right now — the controllers call this from their
    cycle, and the acceptance tests read the high-water mark to assert
    cross-group concurrency rather than assume it.  The world ("") is
    excluded: ``max_concurrent_groups >= 2`` must certify two REAL
    groups in flight at once, not a world collective passing by."""
    global _max_inflight
    n = len({g for g in gids if g})
    if n:
        with _stats_lock:
            if n > _max_inflight:
                _max_inflight = n


def stats():
    with _stats_lock:
        return {"max_concurrent_groups": _max_inflight}
