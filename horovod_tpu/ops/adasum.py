"""Adasum: scale-invariant gradient combination.

The reference implements Adasum as a recursive vector-halving
distance-doubling (VHDD) allreduce in C++ (``horovod/common/ops/adasum/
adasum.h:194-330``): at each level, ranks exchange half-buffers with
``rank ^ level``, compute dot products and squared norms (allreduced over
per-level reduction communicators) and combine

    a' = (1 - a.b / (2 |a|^2)) * a  +  (1 - a.b / (2 |b|^2)) * b

The TPU-native formulation keeps the same pairing tree (rank r pairs with
r ^ 2^level) but expresses it as XLA ops inside the compiled step:
``all_gather`` the per-rank contributions over the mesh axis, then reduce the
leading axis pairwise.  XLA schedules the gather on ICI; the combine is pure
VPU work.  :func:`adasum_vhdd` is the large-tensor path: a true
ppermute-based VHDD (exchange halves, psum the dot/norm scalars over
per-level ``axis_index_groups``), and :func:`adasum_reduce_hierarchical`
composes it with an intra-group reduce-scatter/allgather, mirroring the
reference's NCCL+MPI hierarchical Adasum.

``adasum_reference`` is the numpy oracle used by the tests, mirroring the
reference's pure-Python reference implementation in
``test_adasum_pytorch.py``.
"""

import numpy as np
import jax
import jax.numpy as jnp

from horovod_tpu.common.compression import Compression
from horovod_tpu.parallel._compat import axis_size


def _pair_coefficients(dot, norm_a, norm_b):
    """Safe Adasum pair coefficients; zero-norm operand contributes plain
    addition (reference: adasum.h DispatchComputeDotAndNormSqrds guards)."""
    a_coeff = jnp.where(norm_a > 0, 1.0 - dot / (2.0 * norm_a), 1.0)
    b_coeff = jnp.where(norm_b > 0, 1.0 - dot / (2.0 * norm_b), 1.0)
    return a_coeff, b_coeff


def adasum_pair(a, b):
    """Combine two same-shaped tensors with the Adasum formula."""
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    dot = jnp.dot(af, bf)
    norm_a = jnp.dot(af, af)
    norm_b = jnp.dot(bf, bf)
    a_coeff, b_coeff = _pair_coefficients(dot, norm_a, norm_b)
    return (a_coeff * af + b_coeff * bf).reshape(a.shape).astype(a.dtype)


def adasum_reduce_stacked(stacked):
    """Reduce a [N, ...] stacked tensor along axis 0 with VHDD pairing
    (rank r pairs with r ^ 2^level).  N must be a power of two."""
    n = stacked.shape[0]
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-two rank count, got {n}")
    level = stacked
    while level.shape[0] > 1:
        half = level.shape[0] // 2
        pairs = level.reshape((half, 2) + level.shape[1:])
        combined = jax.vmap(adasum_pair)(pairs[:, 0], pairs[:, 1])
        level = combined
    return level[0]


def adasum_reduce_pytree(grads, named_axes=("hvd",), compression=None):
    """SPMD Adasum: inside shard_map, gather contributions over the mesh
    axes and tree-combine them.  Every rank computes the identical result."""
    compression = compression or Compression.none
    axis = named_axes if isinstance(named_axes, str) else tuple(named_axes)

    def reduce_leaf(g):
        compressed, ctx = compression.compress(g)
        gathered = jax.lax.all_gather(compressed, axis)
        reduced = adasum_reduce_stacked(gathered)
        return compression.decompress(reduced, ctx)

    return jax.tree.map(reduce_leaf, grads)


def adasum_vhdd(x, axis_name, scalar_axes=()):
    """True vector-halving distance-doubling Adasum inside ``shard_map``
    (reference: ``Adasum<Communicator_type>::FusedAllreduce``,
    ``adasum/adasum.h:194-330``), expressed TPU-natively:

    at level ``k`` (distance ``2^k``) each rank exchanges half of its
    current piece with rank ``r ^ 2^k`` via ``ppermute``, and the
    dot/norm scalars of the two logical vectors being combined — which are
    at that point distributed over ``2^(k+1)`` ranks — are reduced with
    ``psum`` over ``axis_index_groups`` (the reference's per-level
    ``reduction_comms``).  After ``log2(n)`` levels every rank holds
    ``1/n`` of the combined vector; a tiled ``all_gather`` restores it.

    Communication volume per rank is ``~2|x|`` (halving) versus
    ``(n-1)|x|`` for the gather-based tree — this is the large-tensor path.
    ``n`` must be a power of two.  ``x`` is the rank's flat vector.

    ``scalar_axes``: extra mesh axes over which the logical vectors are
    chunk-distributed (hierarchical mode: the local axis after a
    reduce-scatter).  The dot/norm scalars are additionally psum'd over
    them so the coefficients see the FULL vectors — the reference's
    reduction communicators likewise span the intra-node ranks holding the
    other chunks (adasum_gpu_operations.cc start_level=local_size).
    """
    n = axis_size(axis_name)
    if n & (n - 1):
        raise ValueError(f"Adasum VHDD requires power-of-two ranks, got {n}")
    if n == 1:
        return x

    size = x.size
    padded = -(-size // n) * n
    piece = jnp.pad(x.astype(jnp.float32).reshape(-1),
                    (0, padded - size))
    idx = jax.lax.axis_index(axis_name)

    dist = 1
    while dist < n:
        half = piece.size // 2
        low, high = piece[:half], piece[half:]
        bit = (idx // dist) % 2  # which half this rank keeps
        send = jnp.where(bit == 0, high, low)
        mine = jnp.where(bit == 0, low, high)
        perm = [(r, r ^ dist) for r in range(n)]
        recv = jax.lax.ppermute(send, axis_name, perm)

        # a = piece of the lower group's vector, b = the upper's; roles are
        # fixed by the rank's bit so every group member reduces the same
        # (a, b) scalars (reference: DispatchComputeDotAndNormSqrds +
        # allreduce over reduction_comms[level]).
        a = jnp.where(bit == 0, mine, recv)
        b = jnp.where(bit == 0, recv, mine)
        groups = [[g * 2 * dist + i for i in range(2 * dist)]
                  for g in range(n // (2 * dist))]
        partial = jnp.stack([jnp.dot(a, b), jnp.dot(a, a), jnp.dot(b, b)])
        for extra in scalar_axes:
            partial = jax.lax.psum(partial, extra)
        dot, na, nb = jax.lax.psum(partial, axis_name,
                                   axis_index_groups=groups)
        ca, cb = _pair_coefficients(dot, na, nb)
        piece = ca * a + cb * b
        dist *= 2

    # After halving, rank r holds the chunk at bit-reversed index: level k's
    # keep-high decision (bit k of r) selects the 2^(levels-1-k)-sized
    # stride.  The reference undoes this with its backward
    # distance-halving allgather (adasum.h:308-); one gather plus a static
    # row permutation is the XLA equivalent.
    levels = n.bit_length() - 1
    gathered = jax.lax.all_gather(piece, axis_name)  # [n, chunk]
    order = [int(format(i, f"0{levels}b")[::-1], 2) for i in range(n)]
    full = gathered[jnp.asarray(order)].reshape(-1)
    return full[:size].reshape(x.shape).astype(x.dtype)


def adasum_reduce_hierarchical(x, local_axis="local", cross_axis="cross"):
    """Hierarchical Adasum inside ``shard_map`` over a (cross, local) mesh
    (reference: ``AdasumGpuAllreduceOp``, ``adasum_gpu_operations.cc``):
    reduce-scatter (sum) within the fast local group, Adasum VHDD across
    the cross axis, allgather back, with the reference's ``local_size``
    divisor folded in (``torch/mpi_ops.py:110``)."""
    local_size = axis_size(local_axis)
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % local_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunk = jax.lax.psum_scatter(flat, local_axis, scatter_dimension=0,
                                 tiled=True)
    combined = adasum_vhdd(chunk, cross_axis, scalar_axes=(local_axis,))
    full = jax.lax.all_gather(combined, local_axis, tiled=True)
    if pad:
        full = full[:-pad]
    return (full / local_size).reshape(x.shape).astype(x.dtype)


def adasum_reference(tensors):
    """Numpy oracle for tests: VHDD pairing over a list of per-rank numpy
    arrays."""
    level = [np.asarray(t, dtype=np.float64) for t in tensors]
    if len(level) & (len(level) - 1):
        raise ValueError("power-of-two rank count required")
    while len(level) > 1:
        combined = []
        for i in range(0, len(level), 2):
            a, b = level[i].reshape(-1), level[i + 1].reshape(-1)
            dot = float(a @ b)
            norm_a = float(a @ a)
            norm_b = float(b @ b)
            a_coeff = 1.0 - dot / (2.0 * norm_a) if norm_a > 0 else 1.0
            b_coeff = 1.0 - dot / (2.0 * norm_b) if norm_b > 0 else 1.0
            combined.append(
                (a_coeff * a + b_coeff * b).reshape(level[i].shape))
        level = combined
    return level[0]
