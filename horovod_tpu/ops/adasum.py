"""Adasum: scale-invariant gradient combination.

The reference implements Adasum as a recursive vector-halving
distance-doubling (VHDD) allreduce in C++ (``horovod/common/ops/adasum/
adasum.h:194-330``): at each level, ranks exchange half-buffers with
``rank ^ level``, compute dot products and squared norms (allreduced over
per-level reduction communicators) and combine

    a' = (1 - a.b / (2 |a|^2)) * a  +  (1 - a.b / (2 |b|^2)) * b

The TPU-native formulation keeps the same pairing tree (rank r pairs with
r ^ 2^level) but expresses it as XLA ops inside the compiled step:
``all_gather`` the per-rank contributions over the mesh axis, then reduce the
leading axis pairwise.  XLA schedules the gather on ICI; the combine is pure
VPU work.  (A ppermute-based VHDD variant — exchange halves, psum the
dot/norm scalars — is the planned optimization for large tensors.)

``adasum_reference`` is the numpy oracle used by the tests, mirroring the
reference's pure-Python reference implementation in
``test_adasum_pytorch.py``.
"""

import numpy as np
import jax
import jax.numpy as jnp

from horovod_tpu.common.compression import Compression


def _pair_coefficients(dot, norm_a, norm_b):
    """Safe Adasum pair coefficients; zero-norm operand contributes plain
    addition (reference: adasum.h DispatchComputeDotAndNormSqrds guards)."""
    a_coeff = jnp.where(norm_a > 0, 1.0 - dot / (2.0 * norm_a), 1.0)
    b_coeff = jnp.where(norm_b > 0, 1.0 - dot / (2.0 * norm_b), 1.0)
    return a_coeff, b_coeff


def adasum_pair(a, b):
    """Combine two same-shaped tensors with the Adasum formula."""
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    dot = jnp.dot(af, bf)
    norm_a = jnp.dot(af, af)
    norm_b = jnp.dot(bf, bf)
    a_coeff, b_coeff = _pair_coefficients(dot, norm_a, norm_b)
    return (a_coeff * af + b_coeff * bf).reshape(a.shape).astype(a.dtype)


def adasum_reduce_stacked(stacked):
    """Reduce a [N, ...] stacked tensor along axis 0 with VHDD pairing
    (rank r pairs with r ^ 2^level).  N must be a power of two."""
    n = stacked.shape[0]
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-two rank count, got {n}")
    level = stacked
    while level.shape[0] > 1:
        half = level.shape[0] // 2
        pairs = level.reshape((half, 2) + level.shape[1:])
        combined = jax.vmap(adasum_pair)(pairs[:, 0], pairs[:, 1])
        level = combined
    return level[0]


def adasum_reduce_pytree(grads, named_axes=("hvd",), compression=None):
    """SPMD Adasum: inside shard_map, gather contributions over the mesh
    axes and tree-combine them.  Every rank computes the identical result."""
    compression = compression or Compression.none
    axis = named_axes if isinstance(named_axes, str) else tuple(named_axes)

    def reduce_leaf(g):
        compressed, ctx = compression.compress(g)
        gathered = jax.lax.all_gather(compressed, axis)
        reduced = adasum_reduce_stacked(gathered)
        return compression.decompress(reduced, ctx)

    return jax.tree.map(reduce_leaf, grads)


def adasum_reference(tensors):
    """Numpy oracle for tests: VHDD pairing over a list of per-rank numpy
    arrays."""
    level = [np.asarray(t, dtype=np.float64) for t in tensors]
    if len(level) & (len(level) - 1):
        raise ValueError("power-of-two rank count required")
    while len(level) > 1:
        combined = []
        for i in range(0, len(level), 2):
            a, b = level[i].reshape(-1), level[i + 1].reshape(-1)
            dot = float(a @ b)
            norm_a = float(a @ a)
            norm_b = float(b @ b)
            a_coeff = 1.0 - dot / (2.0 * norm_a) if norm_a > 0 else 1.0
            b_coeff = 1.0 - dot / (2.0 * norm_b) if norm_b > 0 else 1.0
            combined.append(
                (a_coeff * a + b_coeff * b).reshape(level[i].shape))
        level = combined
    return level[0]
