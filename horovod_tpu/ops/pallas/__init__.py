from horovod_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
from horovod_tpu.ops.pallas.layer_norm import (layer_norm,  # noqa: F401
                                               layer_norm_reference)
from horovod_tpu.ops.pallas.softmax_xent import (softmax_xent,  # noqa: F401
                                                 softmax_xent_reference)
