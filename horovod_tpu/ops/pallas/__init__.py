from horovod_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
