"""Pallas TPU flash attention: the hot-op kernel for the transformer family.

Forward and backward are hand-written Pallas kernels (the reference
framework has no kernels of its own — SURVEY §2.2 "No CUDA kernels... GPU
work is cudaMemcpyAsync + NCCL"; on TPU the hot op IS the kernel, so this
framework ships one).  Design per the TPU architecture:

- the q/k score and p/v matmuls run on the MXU in fp32 accumulation
  (``preferred_element_type``), activations may be bf16;
- online-softmax streaming over K blocks keeps the working set in VMEM —
  O(T) memory instead of the O(T²) score matrix;
- grid = (batch*heads, q-blocks); the K-block loop is a ``fori_loop``
  inside the kernel over K/V resident in VMEM (for sequences too long for
  VMEM, the ring-attention layer shards the sequence first — each shard's
  local block then fits);
- causal masking skips *whole* K blocks past the diagonal (``@pl.when``),
  so the MXU never sees fully-masked tiles;
- backward recomputes the forward blockwise from the saved logsumexp
  (flash-attention-2 style): one kernel accumulates dq over K blocks, one
  accumulates dk/dv over Q blocks.

Layout: public API takes ``[B, T, H, D]`` (framework convention);
kernels run on ``[B*H, T, D]``.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too, but keep a guard for odd builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _vmem_spec(*args):
    if _VMEM is None:  # pragma: no cover
        return pl.BlockSpec(*args)
    return pl.BlockSpec(*args, memory_space=_VMEM)


def _default_interpret():
    return jax.default_backend() != "tpu"


def _flatten_rows(x, fill=0.0, pad_multiple=8):
    """``[..., d] -> ([n_padded, d], n)``: flatten the leading axes and
    pad the row count up to a sublane multiple with ``fill`` rows (the
    padded rows are kernel garbage the caller slices off).  Shared by
    the row-blocked kernels (layer_norm, softmax_xent)."""
    d = x.shape[-1]
    n = 1
    for s in x.shape[:-1]:
        n *= s
    x2 = x.reshape(n, d)
    pad = (-n) % pad_multiple
    if pad:
        x2 = jnp.concatenate(
            [x2, jnp.full((pad, d), fill, x2.dtype)], axis=0)
    return x2, n


def _pick_block_n(n, d, slabs=1):
    """Row-block size for the row-blocked kernels (layer_norm,
    softmax_xent): keep the kernel's [block_n, d] fp32 slabs well under
    VMEM; ``slabs`` counts how many the kernel holds at once."""
    budget = max((4 << 20) // (d * 4 * slabs), 8)
    for cand in (256, 128, 64, 32, 16, 8):
        if cand <= budget and n % cand == 0:
            return cand
    return 8  # callers pad the row count to a multiple of 8 first


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-manual-axes of ``like`` so the
    kernel composes with new-style shard_map (check_vma=True)."""
    try:
        vma = getattr(jax.typeof(like), "vma", None)
        if vma is not None:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except Exception:  # pragma: no cover
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


# ----------------------------------------------------- row-scalar packing
#
# Per-row scalars (logsumexp, delta) are natural [rows, 1] columns inside
# the kernels (rows = sublanes) but must not be stored to HBM broadcast
# across a 128-lane tile — that costs 128x the necessary bandwidth and
# capped long-sequence backward (the bundled jax.experimental kernel
# pays exactly this).  When block_q == 128 the scalars are packed dense:
# HBM shape [bh, t/128, 1, 128], one q-block's column per lane row (the
# singleton sublane axis satisfies the TPU block-shape rule — the last
# two block dims must divide (8, 128) or equal the array dims).  The
# lane<->sublane conversion uses an MXU identity contraction — bit-exact
# for fp32 (one nonzero term per output) and guaranteed to lower on any
# Mosaic version, unlike a reshape across the minor-two dims.

def _eye(n):
    return (jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
            == jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
            ).astype(jnp.float32)


def _col_to_row(c):
    """[n, 1] fp32 column -> [1, n] lane row (MXU transpose)."""
    return jax.lax.dot_general(c, _eye(c.shape[0]), (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _row_to_col(r):
    """[1, n] lane row -> [n, 1] fp32 column (MXU transpose)."""
    return jax.lax.dot_general(_eye(r.shape[1]), r, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


_PACK = 128  # lane width: one q-block of row scalars per packed lane row


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, packed):
    # q_ref: [block_q, d]; k_ref/v_ref: [t_kv, d]; o_ref: [block_q, d]
    # lse_ref: packed [1, 128] (one lane per row) or broadcast
    # [block_q, 128] for odd block sizes
    iq = pl.program_id(1)
    t_kv = k_ref.shape[1]
    d = q_ref.shape[2]
    nk = t_kv // block_k

    q = q_ref[0].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o0 = jnp.zeros((block_q, d), jnp.float32)

    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(ik, carry):
        m, l, o = carry
        k_blk = k_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        if causal:
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m > _NEG_INF / 2, alpha, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o = o * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, o

    if causal:
        # K blocks fully past this q block contribute nothing; the loop
        # bound itself is static-per-program via the grid index.
        nk_eff = jnp.minimum(
            (iq + 1) * block_q + block_k - 1, t_kv) // block_k
    else:
        nk_eff = nk
    m, l, o = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, o0))

    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (o / l_safe).astype(o_ref.dtype)
    lse = m + jnp.log(l_safe)
    if packed:
        lse_ref[0, 0] = _col_to_row(lse)
    else:
        lse_ref[0] = jnp.broadcast_to(lse, (block_q, 128))


def _fwd(q3, k3, v3, *, scale, causal, block_q, block_k, interpret):
    """Returns ``(out [bh, t, d], lse [bh, t])``."""
    bh, t, d = q3.shape
    t_kv = k3.shape[1]
    nq = t // block_q
    packed = block_q == _PACK

    if packed:
        lse_spec = _vmem_spec((1, 1, 1, _PACK), lambda b, i: (b, i, 0, 0))
        lse_shape = _sds((bh, nq, 1, _PACK), jnp.float32, q3)
    else:
        lse_spec = _vmem_spec((1, block_q, 128), lambda b, i: (b, i, 0))
        lse_shape = _sds((bh, t, 128), jnp.float32, q3)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, packed=packed),
        grid=(bh, nq),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, t_kv, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            lse_spec,
        ],
        out_shape=[
            _sds((bh, t, d), q3.dtype, q3),
            lse_shape,
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse.reshape(bh, t) if packed else lse[:, :, 0]


# --------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, packed):
    iq = pl.program_id(1)
    t_kv = k_ref.shape[1]
    d = q_ref.shape[2]
    nk = t_kv // block_k

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    if packed:
        lse = _row_to_col(lse_ref[0, 0])                    # [bq, 1]
        delta = _row_to_col(delta_ref[0, 0])
    else:
        lse = lse_ref[0, :, 0:1]                            # [bq, 1]
        delta = delta_ref[0, :, 0:1]

    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(ik, dq):
        k_blk = k_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        # fully-masked rows carry lse = m = NEG_INF; exp(s - lse)
        # there would be exp(0) = 1 per entry — mirror the
        # forward's guard so such rows contribute zero gradient
        p = jnp.where(lse > _NEG_INF / 2,
                      jnp.exp(s - lse), 0.0)              # [bq, bk]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nk_eff = (jnp.minimum((iq + 1) * block_q + block_k - 1, t_kv)
              // block_k) if causal else nk
    dq = jax.lax.fori_loop(0, nk_eff, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    packed):
    ik = pl.program_id(1)
    t_q = q_ref.shape[1]
    d = k_ref.shape[2]
    nq = t_q // block_q

    k_blk = k_ref[0].astype(jnp.float32)                    # [bk, d]
    v_blk = v_ref[0].astype(jnp.float32)

    k_pos = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(iq, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        if packed:
            lse = _row_to_col(lse_ref[0, pl.ds(iq, 1), 0, :])
            delta = _row_to_col(delta_ref[0, pl.ds(iq, 1), 0, :])
        else:
            lse = lse_ref[0, pl.ds(iq * block_q, block_q), 0:1]
            delta = delta_ref[0, pl.ds(iq * block_q, block_q), 0:1]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        # fully-masked rows carry lse = m = NEG_INF; exp(s - lse)
        # there would be exp(0) = 1 per entry — mirror the
        # forward's guard so such rows contribute zero gradient
        p = jnp.where(lse > _NEG_INF / 2,
                      jnp.exp(s - lse), 0.0)              # [bq, bk]
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]
        return dk, dv

    if causal:
        # q blocks strictly before this k block see none of it
        iq_start = (ik * block_k) // block_q
    else:
        iq_start = 0
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(iq_start, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(res, g, *, scale, causal, block_q, block_k, interpret,
         g_lse=None):
    q3, k3, v3, out, lse = res
    bh, t, d = q3.shape
    t_kv = k3.shape[1]
    nq = t // block_q
    nk = t_kv // block_k

    # delta_i = rowsum(dO * O) — cheap elementwise, leave it to XLA.
    # A cotangent on lse folds in exactly here: d s = p*(dp - delta)*scale
    # gains p*g_lse*scale (since dlse/ds = p), i.e. delta -= g_lse.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                # [bh, t]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    packed = block_q == _PACK
    if packed:
        # dense: one q-block's 128 row scalars per lane row (a reshape,
        # i.e. free) — 128x less HBM than the broadcast fallback below
        lse_b = lse.reshape(bh, nq, 1, _PACK)
        delta_b = delta.reshape(bh, nq, 1, _PACK)
        dq_lse_spec = _vmem_spec((1, 1, 1, _PACK),
                                 lambda b, i: (b, i, 0, 0))
        dkv_lse_spec = _vmem_spec((1, nq, 1, _PACK),
                                  lambda b, i: (b, 0, 0, 0))
    else:
        lse_b = jnp.broadcast_to(lse[:, :, None], (bh, t, 128))
        delta_b = jnp.broadcast_to(delta[:, :, None], (bh, t, 128))
        dq_lse_spec = _vmem_spec((1, block_q, 128), lambda b, i: (b, i, 0))
        dkv_lse_spec = _vmem_spec((1, t, 128), lambda b, i: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, packed=packed),
        grid=(bh, nq),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            dq_lse_spec,
            dq_lse_spec,
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=_sds((bh, t, d), q3.dtype, q3),
        interpret=interpret,
    )(q3, k3, v3, g, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, packed=packed),
        grid=(bh, nk),
        in_specs=[
            _vmem_spec((1, t, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, block_k, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, block_k, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, t, d), lambda b, i: (b, 0, 0)),
            dkv_lse_spec,
            dkv_lse_spec,
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, t_kv, d), k3.dtype, k3),
            _sds((bh, t_kv, d), v3.dtype, v3),
        ],
        interpret=interpret,
    )(q3, k3, v3, g, lse_b, delta_b)
    return dq, dk, dv


# ------------------------------------------------------------- public API

def _pick_block(t, want):
    """Largest divisor of t that is <= want (kernel blocks must tile T)."""
    if want < 1:
        raise ValueError(f"block size must be >= 1, got {want}")
    b = min(want, t)
    while t % b != 0:
        b -= 1
    return b


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q3, k3, v3, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, interpret=interpret)
    return out


def _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q3, k3, v3, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, interpret=interpret)
    return out, (q3, k3, v3, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    return _bwd(res, g, scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    """Like ``_flash`` but also returns the logsumexp — the streaming-
    softmax state ring attention needs to combine per-block results."""
    return _fwd(q3, k3, v3, scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, interpret=interpret)


def _flash_lse_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q3, k3, v3, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, interpret=interpret)
    return (out, lse), (q3, k3, v3, out, lse)


def _flash_lse_bwd(scale, causal, block_q, block_k, interpret, res, g):
    g_out, g_lse = g
    return _bwd(res, g_out, scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, interpret=interpret, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _env_block(name, default):
    from horovod_tpu.utils.env import get_int

    value = get_int(name, default)
    return value if value >= 1 else default


def flash_attention(q, k, v, *, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None, return_lse=False):
    """Flash multi-head attention, ``[B, T, H, D] -> [B, T, H, D]``.

    Differentiable (custom VJP with Pallas backward kernels).  On
    non-TPU backends runs in Pallas interpret mode (tests);
    drop-in for ``TransformerConfig.attn_fn`` and as the local-block
    kernel of ring/Ulysses attention.

    ``return_lse=True`` additionally returns the logsumexp ``[B, H, T]``
    (differentiable), which lets callers combine partial attention
    results streaming-softmax style (ring attention's per-block use).
    """
    b, t, h, d = q.shape
    t_kv = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _default_interpret()
    # HVD_FLASH_BLOCK_Q/K: measured-default overrides (bank-tpu's
    # flash_blocks sweep is the evidence source).  block_q=128 keeps
    # the packed lse/delta layout; other values fall back to the
    # broadcast layout.
    if block_q is None:
        block_q = _env_block("HVD_FLASH_BLOCK_Q", 128)
    if block_k is None:
        block_k = _env_block("HVD_FLASH_BLOCK_K", 128)
    block_q = _pick_block(t, block_q)
    block_k = _pick_block(t_kv, block_k)

    def to3(x):
        tt = x.shape[1]
        return x.transpose(0, 2, 1, 3).reshape(b * h, tt, x.shape[3])

    if return_lse:
        out3, lse3 = _flash_lse(to3(q), to3(k), to3(v), scale, causal,
                                block_q, block_k, interpret)
        out = out3.reshape(b, h, t, d).transpose(0, 2, 1, 3)
        return out, lse3.reshape(b, h, t)

    out3 = _flash(to3(q), to3(k), to3(v), scale, causal, block_q, block_k,
                  interpret)
    return out3.reshape(b, h, t, d).transpose(0, 2, 1, 3)
