"""Pallas TPU fused softmax cross-entropy (forward + custom-VJP backward).

The LM-loss hot op.  The stock lowering materializes ``log_softmax``
over the full ``[rows, vocab]`` logits twice (forward + backward); at
vocab 32k that array dominates HBM traffic of the loss.  The fused
kernels stream the vocab axis in VMEM-resident chunks:

- forward: one pass per row block — running max / sum-exp (online
  logsumexp, same trick as flash attention's softmax) and the label
  logit picked up via an iota==label mask in the same pass; saves
  ``lse`` ([rows, 1] broadcast to the 128-lane tile) for the backward;
- backward: ``dlogits = (exp(x - lse) - onehot(label)) * dloss`` — one
  read of the logits, no recomputed reduction;
- labels ride as int32 ``[rows, 1]`` blocks; rows pad to the sublane
  multiple exactly like ``layer_norm.py`` (padded rows get label 0 and
  zero cotangent, then slice off).

API: ``softmax_xent(logits, labels)`` -> per-row loss ``[...,]`` in
fp32; logits may be bf16 (accumulation is fp32).  Interpret mode
off-TPU; `softmax_xent_reference` is the XLA oracle.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from horovod_tpu.ops.pallas.flash_attention import (_default_interpret,
                                                    _flatten_rows,
                                                    _pick_block_n, _sds,
                                                    _vmem_spec)

_VCHUNK = 2048  # vocab streamed in chunks of this many columns


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref=None, *, vchunk):
    # x_ref: [block_n, V]; lab_ref: [block_n, 1] int32
    bn, v = x_ref.shape
    nchunk = v // vchunk
    lab = lab_ref[...]                                  # [bn, 1]

    def body(c, carry):
        m, s, picked = carry
        x = x_ref[:, pl.ds(c * vchunk, vchunk)].astype(jnp.float32)
        cols = jax.lax.broadcasted_iota(jnp.int32, (bn, vchunk), 1) \
            + c * vchunk
        picked = picked + jnp.sum(
            jnp.where(cols == lab, x, 0.0), axis=1, keepdims=True)
        m_new = jnp.maximum(m, jnp.max(x, axis=1, keepdims=True))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(x - m_new), axis=1, keepdims=True)
        return m_new, s, picked

    m0 = jnp.full((bn, 1), -1e30, jnp.float32)
    z0 = jnp.zeros((bn, 1), jnp.float32)
    m, s, picked = jax.lax.fori_loop(0, nchunk, body, (m0, z0, z0))
    lse = m + jnp.log(s)
    loss_ref[...] = jnp.broadcast_to(lse - picked, loss_ref.shape)
    if lse_ref is not None:
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_kernel(x_ref, lab_ref, lse_ref, dy_ref, dx_ref, *, vchunk):
    bn, v = x_ref.shape
    nchunk = v // vchunk
    lab = lab_ref[...]
    lse = lse_ref[...][:, :1]
    dy = dy_ref[...][:, :1]

    def body(c, _):
        x = x_ref[:, pl.ds(c * vchunk, vchunk)].astype(jnp.float32)
        cols = jax.lax.broadcasted_iota(jnp.int32, (bn, vchunk), 1) \
            + c * vchunk
        p = jnp.exp(x - lse)
        dx = (p - jnp.where(cols == lab, 1.0, 0.0)) * dy
        dx_ref[:, pl.ds(c * vchunk, vchunk)] = dx.astype(dx_ref.dtype)
        return 0

    jax.lax.fori_loop(0, nchunk, body, 0)


def _pick_vchunk(v):
    if v % _VCHUNK == 0:
        return _VCHUNK
    for cand in (1024, 512, 256, 128):
        if v % cand == 0:
            return cand
    return v  # small/odd vocab: single chunk


def _rows(logits, labels):
    x2, n = _flatten_rows(logits)
    l2, _ = _flatten_rows(labels[..., None].astype(jnp.int32))
    return x2, l2, n


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_xent(logits, labels, interpret=None):
    """Per-row softmax cross-entropy over the last axis (fp32).

    The primal (non-differentiated) call skips the lse residual
    output; differentiation swaps in the residual-saving forward."""
    if interpret is None:
        interpret = _default_interpret()
    x2, l2, n = _rows(logits, labels)
    loss = _call_fwd(x2, l2, interpret, with_lse=False)[0]
    return loss[:n, 0].reshape(logits.shape[:-1])


def _call_fwd(x2, l2, interpret, with_lse):
    np_, v = x2.shape
    block_n = _pick_block_n(np_, v)
    vchunk = _pick_vchunk(v)
    grid = (np_ // block_n,)
    out_specs = [_vmem_spec((block_n, 128), lambda i: (i, 0))]
    out_shape = [_sds((np_, 128), jnp.float32, x2)]
    if with_lse:
        out_specs.append(_vmem_spec((block_n, 128), lambda i: (i, 0)))
        out_shape.append(_sds((np_, 128), jnp.float32, x2))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, vchunk=vchunk),
        grid=grid,
        in_specs=[
            _vmem_spec((block_n, v), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x2, l2)


def _sx_fwd(logits, labels, interpret):
    if interpret is None:
        interpret = _default_interpret()
    x2, l2, n = _rows(logits, labels)
    out = _call_fwd(x2, l2, interpret, with_lse=True)
    loss, lse = out
    return (loss[:n, 0].reshape(logits.shape[:-1]),
            (x2, l2, lse, logits.shape))


def _sx_bwd(interpret, residuals, dloss):
    if interpret is None:
        interpret = _default_interpret()
    x2, l2, lse, logits_shape = residuals
    np_, v = x2.shape
    n = 1
    for s in logits_shape[:-1]:
        n *= s
    dy = dloss.reshape(n, 1).astype(jnp.float32)
    if np_ != n:
        dy = jnp.concatenate(
            [dy, jnp.zeros((np_ - n, 1), jnp.float32)], axis=0)
    dy = jnp.broadcast_to(dy, (np_, 128))

    block_n = _pick_block_n(np_, v, slabs=2)
    vchunk = _pick_vchunk(v)
    grid = (np_ // block_n,)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, vchunk=vchunk),
        grid=grid,
        in_specs=[
            _vmem_spec((block_n, v), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 128), lambda i: (i, 0)),
            _vmem_spec((block_n, 128), lambda i: (i, 0)),
        ],
        out_specs=[_vmem_spec((block_n, v), lambda i: (i, 0))],
        out_shape=[_sds((np_, v), x2.dtype, x2)],
        interpret=interpret,
    )(x2, l2, lse, dy)[0]
    return dx[:n].reshape(logits_shape), None


softmax_xent.defvjp(_sx_fwd, _sx_bwd)


def softmax_xent_reference(logits, labels):
    """XLA oracle (optax-equivalent) for tests and non-Pallas paths."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
