"""Pallas TPU fused LayerNorm (forward + custom-VJP backward kernels).

Second hand-written kernel of the transformer hot path (with
``flash_attention.py``).  LayerNorm is HBM-bandwidth-bound: the naive
lowering reads the activation several times (mean, variance, normalize)
and the backward re-reads it for three separate reductions.  The fused
kernels make exactly one pass over the rows per direction:

- forward: per-row mean/rstd in fp32 on the VPU, normalize + affine in
  the same VMEM-resident block; saves ``rstd``/``mean`` ([N, 1]) for the
  backward — O(N) extra memory instead of re-reducing;
- backward: one kernel computes dx for a row block AND accumulates
  dgamma/dbeta into the same output tiles across sequential grid steps
  (TPU grids iterate in order, so cross-step accumulation into an output
  ref is well-defined);
- rows are processed in ``block_n``-row tiles with the full feature dim
  resident in VMEM (d_model up to ~8k at fp32 fits comfortably).

Public API keeps the framework convention: ``layer_norm(x, gamma, beta)``
over the last axis, any leading shape.  Runs interpret-mode off-TPU
(same numerics, used by the CPU test suite), compiled Pallas on TPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from horovod_tpu.ops.pallas.flash_attention import (_default_interpret,
                                                    _flatten_rows,
                                                    _pick_block_n, _sds,
                                                    _vmem_spec)


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref=None, rstd_ref=None,
                *, eps):
    x = x_ref[...].astype(jnp.float32)          # [block_n, d]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = centered * rstd
    out = xhat * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)
    if mean_ref is not None:
        # broadcast across the 128-lane minor dim so the save is tileable
        mean_ref[...] = jnp.broadcast_to(mean, mean_ref.shape)
        rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref,
                dx_ref, dg_ref, db_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    gamma = g_ref[...].astype(jnp.float32)
    mean = mean_ref[...][:, :1]
    rstd = rstd_ref[...][:, :1]
    xhat = (x - mean) * rstd

    # dx = rstd * (dyg - mean(dyg) - xhat * mean(dyg * xhat))
    dyg = dy * gamma
    m1 = jnp.mean(dyg, axis=-1, keepdims=True)
    m2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dyg - m1 - xhat * m2)).astype(dx_ref.dtype)

    # parameter grads accumulate across sequential row-block steps
    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True).astype(
        dg_ref.dtype)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True).astype(db_ref.dtype)


def _call_fwd(x2, gamma, beta, eps, interpret, with_stats):
    """One pallas_call builder for both forwards; ``with_stats`` adds
    the mean/rstd residual outputs the VJP needs."""
    np_, d = x2.shape
    block_n = _pick_block_n(np_, d, slabs=2)
    grid = (np_ // block_n,)
    out_specs = [_vmem_spec((block_n, d), lambda i: (i, 0))]
    out_shape = [_sds((np_, d), x2.dtype, x2)]
    if with_stats:
        for _ in range(2):
            out_specs.append(_vmem_spec((block_n, 128), lambda i: (i, 0)))
            out_shape.append(_sds((np_, 128), jnp.float32, x2))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x2, gamma.reshape(1, d), beta.reshape(1, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x, gamma, beta, eps=1e-6, interpret=None):
    """Fused LayerNorm over the last axis of ``x``.

    The primal (inference) path runs a stats-free kernel — no
    mean/rstd residual writes; differentiation swaps in the
    residual-saving forward via the custom VJP."""
    if interpret is None:
        interpret = _default_interpret()
    # fill=1.0: padded rows have zero variance, which rsqrt(0+eps)
    # handles; any finite fill works since the rows are sliced off
    x2, n = _flatten_rows(x, fill=1.0)
    out = _call_fwd(x2, gamma, beta, eps, interpret, with_stats=False)[0]
    return out[:n].reshape(x.shape)


def _ln_fwd(x, gamma, beta, eps, interpret):
    if interpret is None:
        interpret = _default_interpret()
    x2, n = _flatten_rows(x, fill=1.0)
    out, mean, rstd = _call_fwd(x2, gamma, beta, eps, interpret,
                                with_stats=True)
    return out[:n].reshape(x.shape), (x2, gamma, mean, rstd, x.shape)


def _ln_bwd(eps, interpret, residuals, dout):
    if interpret is None:
        interpret = _default_interpret()
    x2, gamma, mean, rstd, orig_shape = residuals
    np_, d = x2.shape
    n = 1
    for s in orig_shape[:-1]:
        n *= s
    dy2 = dout.reshape(n, d)
    if np_ != n:
        # zero cotangents for the padded rows: they drop out of the
        # dgamma/dbeta accumulation and their dx is sliced off below
        dy2 = jnp.concatenate(
            [dy2, jnp.zeros((np_ - n, d), dy2.dtype)], axis=0)
    block_n = _pick_block_n(np_, d, slabs=3)
    grid = (np_ // block_n,)

    dx, dg, db = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
            _vmem_spec((block_n, 128), lambda i: (i, 0)),
            _vmem_spec((block_n, 128), lambda i: (i, 0)),
            _vmem_spec((block_n, d), lambda i: (i, 0)),
        ],
        out_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            _sds((np_, d), x2.dtype, x2),
            _sds((1, d), jnp.float32, x2),
            _sds((1, d), jnp.float32, x2),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, d), mean, rstd, dy2)

    return (dx[:n].reshape(orig_shape),
            dg.reshape(gamma.shape).astype(gamma.dtype),
            db.reshape(gamma.shape).astype(gamma.dtype))


layer_norm.defvjp(_ln_fwd, _ln_bwd)


def layer_norm_reference(x, gamma, beta, eps=1e-6):
    """Plain-XLA oracle for tests and non-Pallas fallback."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return out.astype(x.dtype)
