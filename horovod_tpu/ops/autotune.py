"""Autotune for the multi-process controllers (tcp / gmesh / python).

The reference tunes runtime knobs on rank 0 with a Gaussian-process
Bayesian optimizer and broadcasts the winners to every rank inside the
coordinator's response stream, so all ranks apply identical values at
the same cycle boundary (``horovod/common/controller.cc:33``
``SynchronizeParameters``, ``parameter_manager.cc:88``).  This module
gives the pure-Python controllers the same machinery through
:class:`horovod_tpu.common.autotune.ParameterManager` (the ctypes face
of the C++ GP + expected-improvement tuner with CSV logging,
``csrc/hvd/parameter_manager.cc``).

Distribution of tuned values is controller-specific but always
coordinator-serialized:

- **gmesh**: rank 0's metadata coordinator emits a ``params`` entry
  into the global sequence log; every process applies it at that exact
  point of the ordered response stream.
- **tcp**: the coordinator stamps a ``(seq, params)`` publication onto
  every result message of an entry at completion time, so all ranks of
  the same collective apply the same values.
- **python** (in-process): the single cycle loop both tunes and
  applies — no distribution needed (all logical ranks share one
  process).
"""

import threading
import time

from horovod_tpu.common.autotune import ParameterManager
from horovod_tpu.utils.logging import get_logger


def default_params(config):
    """The untuned knob view every controller's ``tuned_params()``
    reports when autotune is off — ONE definition so the surface cannot
    drift between controllers."""
    from horovod_tpu.utils import env as env_util

    return {
        "fusion_threshold_bytes": config.fusion_threshold_bytes,
        "cycle_time_ms": config.cycle_time_ms,
        "hierarchical_allreduce": config.hierarchical_allreduce,
        "hierarchical_allgather": config.hierarchical_allgather,
        "cache_enabled": True,
        "compression": getattr(config, "compression", "none"),
        "ring_segment_bytes": getattr(
            config, "ring_segment_bytes",
            env_util.DEFAULT_RING_SEGMENT_BYTES),
        "ring_stripes": getattr(config, "ring_stripes",
                                env_util.DEFAULT_RING_STRIPES),
        "schedule": getattr(config, "schedule", "auto"),
        "tuning": False,
        "best_score_bytes_per_sec": 0.0,
    }


class AutotuneManager:
    """Rank-0-owned tuner: records per-cycle tensor bytes, periodically
    re-optimizes (fusion threshold, cycle time, cache on/off), and
    reports value changes for the controller to distribute."""

    @classmethod
    def create(cls, config, log):
        """Build the manager iff autotune is enabled; a native-lib
        build failure logs a warning and runs the job untuned instead
        of taking it down."""
        if not config.autotune:
            return None
        try:
            return cls(config)
        except Exception as exc:  # noqa: BLE001
            log.warning("autotune unavailable: %s", exc)
            return None

    def __init__(self, config):
        # The tuner explores compression as on/off; the NAME of the
        # compressor stays the operator's configured choice (numerics
        # are the operator's call, whether they pay for themselves is
        # the tuner's).  With no compressor configured the toggle is
        # excluded from the walk entirely.
        self._compression = str(getattr(config, "compression", "none"))
        comp_on = self._compression != "none"
        # The ring transfer-engine knobs only steer the tcp data plane;
        # tuning them on the in-process controllers would burn walk
        # budget on inert parameters.
        from horovod_tpu.ops.tcp_dataplane import SCHEDULES
        from horovod_tpu.utils import env as env_util
        ring_tunable = getattr(config, "controller", "native") == "tcp"
        # the schedule knob is likewise tcp-plane-only; the int encoding
        # is the index into the canonical SCHEDULES tuple
        sched_name = str(getattr(config, "schedule", "auto"))
        self._schedules = SCHEDULES
        self._pm = ParameterManager(
            ring_segment_bytes=int(getattr(
                config, "ring_segment_bytes",
                env_util.DEFAULT_RING_SEGMENT_BYTES)),
            ring_stripes=int(getattr(config, "ring_stripes",
                                     env_util.DEFAULT_RING_STRIPES)),
            ring_tunable=ring_tunable,
            schedule=(SCHEDULES.index(sched_name)
                      if sched_name in SCHEDULES else 0),
            schedule_tunable=ring_tunable,
            warmup_samples=int(
                getattr(config, "autotune_warmup_samples", 3)),
            steady_state_samples=int(
                getattr(config, "autotune_steady_state_samples", 10)),
            bayes_opt_max_samples=int(
                getattr(config, "autotune_bayes_opt_max_samples", 20)),
            gp_noise=float(
                getattr(config, "autotune_gaussian_process_noise", 0.8)),
            log_path=config.autotune_log or None,
            fusion_threshold_bytes=int(config.fusion_threshold_bytes),
            cycle_time_ms=float(config.cycle_time_ms),
            hierarchical_allreduce=bool(config.hierarchical_allreduce),
            hierarchical_allgather=bool(config.hierarchical_allgather),
            compression=comp_on, compression_available=comp_on)
        self._start = time.monotonic()
        self._lock = threading.Lock()
        self._seq = 0
        self._last = None
        self._closed = False
        self._log = get_logger()

    def record(self, nbytes: int):
        with self._lock:
            if not self._closed:
                self._pm.record(int(nbytes))

    def maybe_update(self):
        """Feed the tuner a clock tick; returns ``(seq, params)`` when
        the tuned values changed (or on the first call), else None."""
        with self._lock:
            if self._closed:
                return None
            changed = self._pm.update(time.monotonic() - self._start)
            if not changed and self._last is not None:
                return None
            params = self._snapshot()
            if params == self._last:
                return None
            self._last = params
            self._seq += 1
            self._log.debug("autotune: new params #%d %s", self._seq,
                            params)
            return self._seq, params

    def params(self):
        with self._lock:
            if self._closed:
                return dict(self._last or {})
            return self._snapshot()

    def _snapshot(self):
        pm = self._pm
        return {
            "fusion_threshold_bytes": pm.fusion_threshold_bytes,
            "cycle_time_ms": pm.cycle_time_ms,
            "hierarchical_allreduce": pm.hierarchical_allreduce,
            "hierarchical_allgather": pm.hierarchical_allgather,
            "cache_enabled": pm.cache_enabled,
            "compression": (self._compression if pm.compression_enabled
                            else "none"),
            "ring_segment_bytes": pm.ring_segment_bytes,
            "ring_stripes": pm.ring_stripes,
            "schedule": self._schedules[pm.schedule],
            "tuning": pm.tuning,
            "best_score_bytes_per_sec": pm.best_score,
        }

    def close(self):
        with self._lock:
            self._closed = True
            self._pm = None  # __del__ frees the native handle
