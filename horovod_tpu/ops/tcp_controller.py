"""TCP controller: multi-process coordination + data plane.

The process-rank analog of the reference's Gloo configuration
(``horovod/common/gloo/gloo_controller.cc`` + ``gloo_operations.cc``): a
job launched as N OS processes (``hvdrun -np N``) coordinates named
collectives through a rank-0 service.

v2 design (round 2 — replaces the round-1 star):

- **Control plane**: ONE persistent multiplexed connection per worker to
  the rank-0 coordinator (``network.MuxClient``); each named collective
  is a signed request that blocks until all ranks contributed
  (negotiation-order freedom, cross-rank validation, Join stand-ins and
  stall handling per the reference's protocol).
- **Response cache**: the coordinator keeps an LRU of validated
  signatures per name (reference: ``response_cache.cc``); steady-state
  resubmissions with a matching signature skip re-validation.
- **Data plane**: small tensors ride the coordinator round-trip (one
  RTT, latency-optimal).  Tensors >= ``HVD_TCP_RING_THRESHOLD``
  (default 1 MB) move rank-to-rank on the worker ring instead
  (``ops/tcp_dataplane.py``): ring allreduce / pipelined broadcast /
  block-rotation allgather — the coordinator only referees metadata, so
  no O(N·bytes) hot spot (reference: ``gloo_operations.cc:30-100`` ring
  allreduce).
- **Timeline**: enabled per rank (``HVD_TIMELINE=<path>`` writes
  ``<path>.rank<r>``); rank 0 merges every rank's trace into ``<path>``
  at shutdown (reference: rank 0 writes one file for all ranks,
  ``timeline.cc``).

THE PERF PATH ON TPU PODS IS NOT THIS: under ``hvdrun --tpu`` the
global-mesh controller compiles collectives over ICI/DCN
(``ops/global_controller.py``); the tcp plane is the no-accelerator
configuration.
"""

import base64
import hashlib
import os
import threading
import time

import numpy as np

from horovod_tpu.common import busy, faults
from horovod_tpu.common import rtt as rtt_mod
from horovod_tpu.common.handles import (RECONFIG_MARKER, HvdAbortedError,
                                        HvdError, is_drain_reason,
                                        make_abort_error)
from horovod_tpu.common.ops_enum import (ReduceOp, RequestType,
                                         is_float_dtype,
                                         reduce_scatter_split_sizes)
from horovod_tpu.common.response_cache import SignatureCache
from horovod_tpu.ops.tcp_dataplane import (DEFAULT_RHD_MAX_BYTES,
                                           DEFAULT_RHD_MIN_BYTES,
                                           DEFAULT_RING_THRESHOLD,
                                           PeerService, RingPlane,
                                           RingSendError)
from horovod_tpu.run.service import network
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

CONTROLLER_SCOPE = "controller"
CONTROLLER_KEY = "addr"
PEERS_SCOPE = "peers"
TIMELINE_SCOPE = "timeline"
# dead-epoch GC watermark (rendezvous): highest epoch whose suffixed
# scopes have already been torn down, so a reconfiguration at epoch k
# purges only the epochs since the last purge instead of rescanning
# 0..k-1 every time (O(k^2) cumulative rendezvous calls at soak scale)
GC_SCOPE = "gc"
GC_PURGED_KEY = "purged-epoch"


# ------------------------------------------------------------------ messages
class CollectiveMsg:
    def __init__(self, name, rank, req_type, op, payload, shape, dtype,
                 root_rank=-1, splits=None, prescale=1.0, postscale=1.0,
                 ring=False, sig=None, compression="none", epoch=0,
                 schedule="auto", group="", group_ranks=None):
        self.name = name
        # process-group scoping (docs/groups.md): "" = the world.  The
        # member list rides the message so the coordinator never needs
        # this worker's group registry — negotiation state is keyed
        # (group, name) and readiness counts exactly these ranks.
        self.group = group
        self.group_ranks = tuple(group_ranks) if group_ranks else None
        self.epoch = epoch              # sender's membership epoch
        self.rank = rank
        self.req_type = int(req_type)
        self.op = int(op)
        self.payload = payload          # raw little-endian bytes (None=ring)
        self.shape = tuple(shape)
        self.dtype = dtype              # numpy dtype string
        self.root_rank = root_rank
        self.splits = splits
        self.prescale = prescale
        self.postscale = postscale
        self.ring = ring
        self.sig = sig                  # signature digest (response cache)
        self.compression = compression  # requested wire compression
        self.schedule = schedule        # requested collective schedule


# epoch-exempt: responses ride the fenced request's connection — the
# coordinator only writes a ResultMsg back on the socket that carried a
# CollectiveMsg already admitted past the epoch fence in
# _handle_collective, so a stale-epoch result cannot reach a re-formed
# world's rank
class ResultMsg:
    def __init__(self, payload=None, shape=None, dtype=None, error=None,
                 recv_splits=None, ring_go=False, participants=None,
                 dims0=None, ring_id=None, params_seq=0, params=None,
                 resend=False, compression="none", aborted=None,
                 ring_segment_bytes=None, schedule=None, groups=None):
        self.payload = payload
        self.shape = shape
        self.dtype = dtype
        self.error = error
        self.recv_splits = recv_splits
        self.ring_go = ring_go
        self.participants = participants
        self.dims0 = dims0              # per-rank first dims (ring allgather)
        self.ring_id = ring_id          # coordinator-assigned round id
        self.params_seq = params_seq    # autotune publication counter
        self.params = params            # tuned knob dict (rank 0 -> all)
        self.resend = resend    # ring infeasible: resubmit with payload
        self.compression = compression  # coordinator-resolved wire format
        self.aborted = aborted  # (origin_rank, reason) coordinated abort
        # coordinator-resolved pipeline segment size for THIS round
        # (None: every rank uses its identical launch-env value) — both
        # ring endpoints must derive the same segment plan even while a
        # tuned value propagates
        self.ring_segment_bytes = ring_segment_bytes
        # coordinator-resolved collective schedule for THIS round,
        # stamped like the segment size so endpoints can't desync:
        # "flat_ring" | "hierarchical" | "rhd" (None: flat ring, the
        # pre-schedule wire default)
        self.schedule = schedule
        # hierarchical only: the group plan (list of sorted rank lists)
        # every participant executes — stamped so re-grouping after an
        # elastic reconfiguration is digest-identical by construction
        self.groups = groups


# epoch-exempt: join barriers run inside one epoch by construction —
# the coordinator address is published under an epoch-suffixed
# rendezvous scope (run/rendezvous.py) and the session hello fences
# resumed connections, so a JoinMsg can only reach the coordinator of
# the epoch it was minted in
class JoinMsg:
    def __init__(self, rank):
        self.rank = rank


# epoch-exempt: reply half of the JoinMsg barrier above — rides the
# fenced join connection
class JoinDoneMsg:
    def __init__(self, last_rank, abort=None):
        self.last_rank = last_rank
        self.abort = abort              # (origin_rank, reason) | None


# epoch-exempt: teardown is epoch-agnostic by design — a shutdown must
# deregister the rank whichever epoch the frame was minted in, and
# acting on a straggler shutdown is idempotent (the rank is gone either
# way)
class ShutdownMsg:
    def __init__(self, rank=None):
        self.rank = rank  # deregisters the rank from liveness tracking


# epoch-exempt: drain intent is epoch-agnostic by design — the rank is
# leaving whichever world it lands in; the reconfiguration it triggers
# mints the next epoch itself, and a duplicate/straggler drain for an
# already-departed rank is a no-op
class DrainMsg:
    """A rank announces planned departure: it received the preemption
    notice (SIGTERM) and asks the coordinator to reconfigure the job
    without it at the next collective boundary (docs/checkpoint.md)."""

    def __init__(self, rank):
        self.rank = rank


class DrainAck:
    def __init__(self, ok, reason=""):
        self.ok = ok          # False: drain not survivable, die as preempted
        self.reason = reason


def _wire_dtype(arr):
    """(native-endian array, wire dtype string).  Extension dtypes
    (bfloat16) have opaque ``.str`` so they travel by name; fixed-width
    bytes/str keep ``.str`` (their ``.name`` doesn't round-trip); any
    non-native byte order is normalized before the bytes hit the wire."""
    dt = arr.dtype
    if dt.kind in "SU":
        return arr, dt.str
    if dt.byteorder == ">":
        arr = arr.astype(dt.newbyteorder("="))
    return arr, arr.dtype.name


def _decode(msg):
    return np.frombuffer(msg.payload, dtype=np.dtype(msg.dtype)).reshape(
        msg.shape)


def _encode(arr):
    arr = np.asarray(arr)
    # ascontiguousarray promotes 0-d to 1-d; keep the true shape
    shape = arr.shape
    arr, dtype = _wire_dtype(arr)
    return ResultMsg(payload=np.ascontiguousarray(arr).tobytes(),
                     shape=shape, dtype=dtype)


def _signature(msg) -> bytes:
    """Validation-relevant fields of a request (reference: the response
    cache key is tensor name + params, ``response_cache.h:45``)."""
    parts = (msg.req_type, msg.op, msg.dtype, tuple(msg.shape),
             msg.root_rank, tuple(msg.splits or ()), msg.prescale,
             msg.postscale, bool(msg.ring),
             getattr(msg, "compression", "none"),
             getattr(msg, "schedule", "auto"),
             # group id + membership join the signature (docs/groups.md:
             # the same tensor name in two groups must never validate —
             # or cache — against the other's round)
             getattr(msg, "group", ""),
             tuple(getattr(msg, "group_ranks", None) or ()))
    return hashlib.sha1(repr(parts).encode()).digest()


# ---------------------------------------------------------------- entry
class _Entry:
    """One named collective being negotiated (reference: the coordinator's
    message table, controller.cc:62)."""

    def __init__(self, req_type, group="", group_ranks=None):
        self.req_type = req_type
        self.group = group              # "" = world (docs/groups.md)
        self.group_ranks = group_ranks  # tuple | None
        self.requests = {}   # rank -> CollectiveMsg
        self.results = {}    # rank -> ResultMsg
        self.done = threading.Event()
        self.first_ts = time.monotonic()
        self.stall_warned = False

    def expected_ranks(self, size):
        """The ranks whose contribution completes this entry: the
        group's members, or the full world."""
        return (self.group_ranks if self.group else range(size))


class CoordinatorService(network.MuxService):
    """Rank 0's collective coordinator (persistent mux connections)."""

    NAME = "horovod_tpu coordinator"

    def __init__(self, size, key, stall_warning_sec=60.0,
                 stall_shutdown_sec=0.0, cache_capacity=1024,
                 autotune=None, liveness_timeout_sec=0.0, epoch=0,
                 elastic=None, straggler_factor=None,
                 straggler_windows=None, straggler_exclude=False):
        self._size = size
        # membership epoch this coordinator serves; a CollectiveMsg
        # stamped with a different epoch is refused (stale negotiation
        # from a torn-down membership must not form entries here)
        self._epoch = epoch
        # ElasticContext (rank 0, HVD_TPU_ELASTIC=1) or None: consulted
        # by _initiate_abort to rewrite a survivable failure into a
        # reconfiguration directive instead of a fatal abort
        self._elastic = elastic
        self._stall_warning = stall_warning_sec
        self._stall_shutdown = stall_shutdown_sec
        self._liveness = liveness_timeout_sec
        self._cv = threading.Condition()
        self._forming = {}          # name -> _Entry; guarded by self._cv
        self._joined = set()        # guarded by self._cv
        # (rank, Event, [last_rank]); guarded by self._cv
        self._join_waiters = []
        # rank -> monotonic ts of last message; guarded by self._cv
        self._last_seen = {}
        # ranks whose LAST heartbeat carried the busy flag (checkpoint
        # write / drain teardown in progress): liveness doubles their
        # deadline so slow disk I/O can't read as death; guarded by
        # self._cv
        self._busy_ranks = set()
        # ranks whose last heartbeat reported a session heal in flight
        # (docs/fault_tolerance.md "connection blips vs dead peers"):
        # treated as busy for liveness AND exempt from straggler
        # verdicts — a recovering link is never converted into an
        # exclusion or an abort; guarded by self._cv
        self._reconnecting_ranks = set()
        # ranks that announced a graceful drain: excluded from liveness
        # blame entirely — silence is their planned departure, not a
        # death to abort over; guarded by self._cv
        self._draining = set()
        # degraded-network tolerance (docs/fault_tolerance.md): each
        # rank's self-reported worst link RTT EWMA widens its liveness
        # window by an ADDITIVE slack (composing with — never
        # double-doubling — the multiplicative busy factor), and a rank
        # whose RTT stays over factor x median for ``windows``
        # consecutive scans earns a straggler verdict
        self._straggler_factor = (
            env_util.get_float(env_util.HVD_TPU_STRAGGLER_FACTOR,
                               env_util.DEFAULT_STRAGGLER_FACTOR)
            if straggler_factor is None else straggler_factor)
        self._straggler_windows = (
            env_util.get_int(env_util.HVD_TPU_STRAGGLER_WINDOWS,
                             env_util.DEFAULT_STRAGGLER_WINDOWS)
            if straggler_windows is None else straggler_windows)
        self._straggler_exclude = straggler_exclude
        self._peer_rtt = {}        # rank -> seconds; guarded by self._cv
        # rank -> launcher host hash carried on heartbeats: the raw
        # material for hierarchical group planning; guarded by self._cv
        self._host_of = {}
        # rank -> consecutive over-threshold scans; guarded by self._cv
        self._straggler_hits = {}
        # rank -> verdict dict, sticky; guarded by self._cv
        self._straggler_verdicts = {}
        # monotonic ts of the last O(N) liveness scan (the scan is
        # time-gated, not per-heartbeat); guarded by self._cv
        self._last_liveness_scan = 0.0
        # (origin_rank, reason), sticky: written once under self._cv;
        # guarded by self._cv (the lock-free reads below are annotated —
        # a stale None is at worst one poll late, never wrong)
        self._abort = None
        self._sig_cache = SignatureCache(cache_capacity)
        self._ring_seq = 0     # unique id per ring round; guarded by self._cv
        self._autotune = autotune        # rank-0-owned manager | None
        # (seq, tuned knob dict); guarded by self._publish_lock
        self._published = None
        self._publish_lock = threading.Lock()
        self._log = get_logger()
        super().__init__(self.NAME, key)

    # ----------------------------------------------------------- negotiation
    def _handle(self, req, client_address):
        rank = getattr(req, "rank", None)
        if rank is not None:
            with self._cv:
                self._last_seen[rank] = time.monotonic()
                if isinstance(req, network.HeartbeatMsg):
                    # getattr: a pre-busy-field peer's heartbeat simply
                    # never widens its window
                    rec = getattr(req, "reconnecting", None)
                    if getattr(req, "busy", False) or rec:
                        self._busy_ranks.add(rank)
                    else:
                        self._busy_ranks.discard(rank)
                    if rec:
                        self._reconnecting_ranks.add(rank)
                    else:
                        self._reconnecting_ranks.discard(rank)
                    rtt = getattr(req, "rtt", None)
                    if rtt is not None:
                        self._peer_rtt[rank] = float(rtt)
                    host = getattr(req, "host", None)
                    if host is not None:
                        self._host_of[rank] = host
        if isinstance(req, CollectiveMsg):
            return self._handle_collective(req)
        if isinstance(req, JoinMsg):
            return self._handle_join(req)
        if isinstance(req, DrainMsg):
            return self._handle_drain(req)
        if isinstance(req, network.HeartbeatMsg):
            self._check_liveness()
            # sticky set-once flag: a stale None here is one heartbeat
            # late, never wrong
            return network.HeartbeatReply(abort=self._abort)  # hvd-lint: ignore[lock-discipline]
        if isinstance(req, network.AbortMsg):
            self._initiate_abort(req.origin_rank, req.reason)
            return network.AckResponse()
        if isinstance(req, ShutdownMsg):
            # a cleanly-departing rank stops heartbeating BY DESIGN: it
            # must leave the liveness table, or a survivor doing slow
            # post-training work would trip a spurious "presumed dead"
            # abort on its stale last-seen entry
            if req.rank is not None:
                with self._cv:
                    self._last_seen.pop(req.rank, None)
                    self._busy_ranks.discard(req.rank)
                    self._reconnecting_ranks.discard(req.rank)
                    self._draining.discard(req.rank)
                    self._peer_rtt.pop(req.rank, None)
                    self._straggler_hits.pop(req.rank, None)
                    self._host_of.pop(req.rank, None)
            return network.AckResponse()
        return super()._handle(req, client_address)

    # -------------------------------------------------- abort + liveness
    def _abort_result(self):
        # sticky flag, set-once before the waiter events fire: callers
        # only reach here after observing it non-None
        origin, reason = self._abort  # hvd-lint: ignore[lock-discipline]
        return ResultMsg(
            error=f"collective runtime aborted (origin rank {origin}): "
                  f"{reason}",
            aborted=(origin, reason))

    def _initiate_abort(self, origin_rank, reason):
        """Coordinated abort (reference analog: the stall inspector's
        shutdown path, promoted from a log line into action): fail every
        negotiating rank NOW with one typed, symmetric error; ranks not
        currently negotiating learn the abort from their next heartbeat
        reply.  Sticky — the surviving ranks are expected to unwind.

        With an ElasticContext attached, a survivable failure is
        rewritten into a membership-reconfiguration directive BEFORE the
        sticky flag is set: the same fan-out then delivers "re-form at
        epoch N+1" instead of "die" (docs/elastic.md)."""
        # plan() runs outside the lock (it talks to the rendezvous
        # server); idempotence is re-checked under the lock, and the
        # plan itself is sticky, so a racing second abort just reads
        # the cached directive.  A reason that already IS a directive
        # (the drain path planned before calling here) passes through
        # unchanged.
        if (self._elastic is not None and self._abort is None  # hvd-lint: ignore[lock-discipline]
                and not (isinstance(reason, str)
                         and reason.startswith(RECONFIG_MARKER))):
            planned = self._elastic.plan(origin_rank, reason)
            if planned is not None:
                reason = planned
        with self._cv:
            if self._abort is not None:
                return
            self._abort = (origin_rank, reason)
            # satellite bugfix: a signature validated pre-abort must not
            # short-circuit validation after a reconfiguration reuses
            # the same tensor names with a different membership
            self._sig_cache.clear()
            forming, self._forming = self._forming, {}
            waiters, self._join_waiters = self._join_waiters, []
            self._joined.clear()
        self._log.error("coordinated abort (origin rank %s): %s",
                        origin_rank, reason)
        for entry in forming.values():
            entry.results = {r: self._abort_result()
                             for r in entry.requests}
            entry.done.set()
        for _, event, slot in waiters:
            slot[0] = None  # join handler converts to a typed error
            event.set()

    def _handle_drain(self, req):
        """Graceful drain (docs/checkpoint.md): exempt the announcing
        rank from liveness blame, plan a reconfiguration WITHOUT it,
        wait for the next collective boundary, then publish the
        directive through the ordinary abort delivery (minus the peer
        fan-out — ``is_drain_reason`` delivery is pull-only).  Runs on
        this request's own mux thread, so blocking here blocks nobody
        else."""
        rank = req.rank
        with self._cv:
            if self._abort is not None:
                # a failure (or another drain) beat this announcement;
                # the rank leaves through whatever is already in flight
                return DrainAck(False, "abort already in flight")
            self._draining.add(rank)
        directive = (self._elastic.plan_drain(rank)
                     if self._elastic is not None else None)
        if directive is None:
            with self._cv:
                self._draining.discard(rank)
            return DrainAck(
                False, "drain not survivable: elastic disabled, "
                       "coordinator rank, or too few survivors")
        # collective boundary: no entry mid-negotiation.  Polled OUTSIDE
        # _cv (the wait must not starve negotiations, and
        # _initiate_abort below re-acquires it).  Bounded: a steady
        # stream of collectives may never leave _forming observably
        # empty, and a late directive is still correct — it just fails
        # one in-flight round into the reconfiguration.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._cv:
                if self._abort is not None or not self._forming:
                    break
            time.sleep(0.005)
        self._initiate_abort(rank, directive)
        return DrainAck(True)

    def _deadline_for_locked(self, r):  # holds: self._cv
        """Effective liveness window for rank ``r``: the busy factor
        MULTIPLIES the base window (slow local I/O scales everything),
        the RTT slack ADDS to it (a slow link delays delivery by a
        bounded absolute amount) — composed, never double-doubled."""
        base = self._liveness * (2.0 if r in self._busy_ranks else 1.0)
        return base + self._rtt_slack_locked(r)

    def _rtt_slack_locked(self, r):  # holds: self._cv
        """Additive deadline slack from the rank's self-reported RTT
        EWMA, capped at factor x the base window so a pathological
        report cannot make the rank effectively unkillable."""
        return min(self._peer_rtt.get(r, 0.0) * self._straggler_factor,
                   self._liveness * self._straggler_factor)

    def _check_liveness(self):
        """Convert a silently-dead peer (no message within its adaptive
        liveness window) into a coordinated abort instead of an
        indefinite wait.

        A rank whose last heartbeat was busy-flagged (checkpoint write /
        drain teardown) gets a doubled window; a rank reporting a high
        link RTT gets an additive slack (slow is not dead,
        docs/fault_tolerance.md "degraded networks"); a rank that
        announced a drain is never blamed at all — its silence is the
        planned departure."""
        # sticky-flag fast path; _initiate_abort re-checks under the lock
        if self._liveness <= 0 or self._abort is not None:  # hvd-lint: ignore[lock-discipline]
            return
        now = time.monotonic()
        with self._cv:
            # the O(N) table scan runs at most ~10x per window — on
            # every heartbeat it would be O(N^2) per window at 64
            # ranks, a measured rank-0 hot spot in the soak rig
            if now - self._last_liveness_scan < self._liveness / 10.0:
                return
            self._last_liveness_scan = now
            dead = sorted(
                r for r, ts in self._last_seen.items()
                if now - ts > self._deadline_for_locked(r)
                and r not in self._joined and r not in self._draining)
            window = self._deadline_for_locked(dead[0]) if dead else 0.0
            straggler = None if dead else self._straggler_scan_locked()
        if dead:
            self._initiate_abort(
                dead[0],
                f"rank {dead[0]} sent no heartbeat for more than "
                f"{window:g}s (presumed dead)")
        elif straggler is not None:
            # boundary-wait + plan_drain can block; never on a
            # heartbeat handler thread.  lifecycle: daemon, one-shot
            threading.Thread(
                target=self._propose_straggler_exclusion,
                args=(straggler,), daemon=True,
                name="hvd-straggler-drain").start()

    def _straggler_scan_locked(self):  # holds: self._cv
        """k x median straggler verdict: a rank whose reported RTT EWMA
        exceeds ``straggler_factor`` x the median of all reports for
        ``straggler_windows`` consecutive scans is recorded (and
        logged) as a straggler.  Returns a rank to propose for
        drain-style exclusion, or None (exclusion is opt-in and
        elastic-only — the default verdict is a report, not an
        eviction)."""
        if len(self._peer_rtt) < 3:
            return None  # no meaningful median from fewer peers
        med = rtt_mod.median(self._peer_rtt.values())
        exclude = None
        for r, value in self._peer_rtt.items():
            if r in self._reconnecting_ranks:
                # a healing link inflates RTT by construction; a
                # reconnect in progress must never ripen into a
                # straggler verdict (docs/fault_tolerance.md
                # "connection blips vs dead peers")
                self._straggler_hits.pop(r, None)
                continue
            if not (med > 0 and value > self._straggler_factor * med):
                self._straggler_hits.pop(r, None)
                continue
            self._straggler_hits[r] = self._straggler_hits.get(r, 0) + 1
            if (self._straggler_hits[r] >= self._straggler_windows
                    and r not in self._straggler_verdicts):
                self._straggler_verdicts[r] = {
                    "rank": r, "rtt": value, "median": med,
                    "factor": self._straggler_factor}
                self._log.warning(
                    "straggler verdict: rank %d RTT %.3fs > %g x "
                    "median %.3fs for %d consecutive windows", r,
                    value, self._straggler_factor, med,
                    self._straggler_hits[r])
                if exclude is None:
                    exclude = r
        if (exclude is not None and self._straggler_exclude
                and self._elastic is not None):
            return exclude
        return None

    def straggler_verdicts(self):
        """Recorded straggler verdicts (rank -> verdict dict) — the
        soak rig's regression artifact reads these off the logs; tests
        read them here."""
        with self._cv:
            return {r: dict(v)
                    for r, v in self._straggler_verdicts.items()}

    def _propose_straggler_exclusion(self, rank):
        """Drain-style exclusion of a confirmed straggler
        (HVD_TPU_STRAGGLER_EXCLUDE, elastic only): same protocol as a
        granted drain — plan a membership without the rank, wait for a
        collective boundary, deliver the drain-marked directive
        pull-only.  Nothing crashed, so nothing aborts: survivors
        reconfigure, the straggler exits cleanly."""
        with self._cv:
            if self._abort is not None or rank in self._draining:
                return
            self._draining.add(rank)
        directive = self._elastic.plan_drain(
            rank, cause=f"rank {rank} excluded as confirmed straggler")
        if directive is None:
            with self._cv:
                self._draining.discard(rank)
            return
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._cv:
                if self._abort is not None or not self._forming:
                    break
            time.sleep(0.005)
        self._initiate_abort(rank, directive)

    def _ready(self, entry):  # holds: self._cv
        """Ready once every live (non-joined) rank has contributed — a
        raw count would let a since-joined rank's own request stand in
        for a live rank's missing one (silent wrong result).  A grouped
        entry waits for exactly its members: joins are a world-level
        protocol, so they never stand in for a group rank."""
        if entry.group:
            return set(entry.group_ranks) <= entry.requests.keys()
        live = set(range(self._size)) - self._joined
        return live <= entry.requests.keys()

    def _handle_collective(self, req):
        if getattr(req, "epoch", 0) != self._epoch:
            # stale membership epoch: a straggler negotiation from a
            # torn-down world must not form entries at this coordinator
            return ResultMsg(error=(
                f"stale membership epoch {getattr(req, 'epoch', 0)} for "
                f"tensor '{req.name}' (coordinator is at epoch "
                f"{self._epoch})"))
        # (group, name) is THE negotiation key: the same tensor name in
        # two groups forms two independent entries that can be in
        # flight concurrently (docs/groups.md)
        key = (getattr(req, "group", ""), req.name)
        with self._cv:
            if self._abort is not None:
                return self._abort_result()
            entry = self._forming.get(key)
            if entry is None:
                entry = _Entry(req.req_type, group=key[0],
                               group_ranks=getattr(req, "group_ranks",
                                                   None))
                self._forming[key] = entry
            if req.rank in entry.requests:
                return ResultMsg(error=(
                    f"duplicate request for tensor '{req.name}' from rank "
                    f"{req.rank} before previous one completed"))
            entry.requests[req.rank] = req
            gids = {g for (g, _) in self._forming}
            if self._ready(entry):
                self._complete(key, entry)
                self._check_join_barrier()
        # concurrency gauge (read by the acceptance tests): distinct
        # groups simultaneously negotiating at this coordinator
        from horovod_tpu import groups as groups_mod
        groups_mod.note_inflight(gids)
        # Wait outside negotiation state; requests run on their own mux
        # threads, so blocking here is the reference's "wait for the
        # response list" on this rank.
        deadline = (time.monotonic() + self._stall_shutdown
                    if self._stall_shutdown > 0 else None)
        while not entry.done.wait(timeout=1.0):
            # sticky-flag poll; the typed result is taken under the lock
            if self._abort is not None:  # hvd-lint: ignore[lock-discipline]
                # abort raced entry creation: take the typed result (and
                # drop the orphaned entry so it can't pin the join
                # barrier)
                with self._cv:
                    if self._forming.get(key) is entry:
                        del self._forming[key]
                return self._abort_result()
            age = time.monotonic() - entry.first_ts
            # hvd-race: ok[racy fast-path check only; warn-once is
            # decided by the re-check under the lock below]
            if age > self._stall_warning and not entry.stall_warned:
                with self._cv:
                    already, entry.stall_warned = entry.stall_warned, \
                        True
                    missing = [r for r in entry.expected_ranks(self._size)
                               if r not in entry.requests
                               and r not in self._joined]
                    ready = sorted(entry.requests)
                    if not already:
                        # reference: InvalidateStalledCachedTensors
                        self._sig_cache.evict(self._cache_name(key))
                if not already:
                    self._log.warning(
                        "Stalled tensor: %s ready ranks: %s, waiting "
                        "on: %s for more than %ds", req.name, ready,
                        missing, int(self._stall_warning))
            if deadline is not None and time.monotonic() > deadline:
                # stall shutdown, promoted into a coordinated abort: the
                # first missing rank is the culprit, EVERY rank (not just
                # this entry's waiters) raises the same typed error, and
                # ring state everywhere is purged via the abort broadcast
                with self._cv:
                    missing = [r for r in entry.expected_ranks(self._size)
                               if r not in entry.requests
                               and r not in self._joined]
                origin = missing[0] if missing else req.rank
                self._initiate_abort(
                    origin,
                    f"stalled tensor '{req.name}' exceeded shutdown "
                    f"threshold of {self._stall_shutdown}s (waiting on "
                    f"ranks {missing})")
                break
        # sticky-flag read: once done fired, results are immutable
        if self._abort is not None and req.rank not in entry.results:  # hvd-lint: ignore[lock-discipline]
            return self._abort_result()
        # hvd-race: ok[results written before done.set(); immutable and
        # deliberately lock-free once the done event ordered this read]
        return entry.results.get(req.rank,
                                 ResultMsg(error="internal: no result"))

    def _handle_join(self, req):
        event = threading.Event()
        slot = [None]
        with self._cv:
            if self._abort is not None:
                return JoinDoneMsg(None, abort=self._abort)
            self._joined.add(req.rank)
            self._join_waiters.append((req.rank, event, slot))
            # a rank joining may complete entries now only missing it
            for key, entry in list(self._forming.items()):
                if entry.requests and self._ready(entry):
                    self._complete(key, entry)
            self._check_join_barrier()
        # wakeable: _initiate_abort and _check_join_barrier both set
        # every registered join-waiter event (tested by test_stall's
        # join-barrier abort coverage)
        event.wait()
        # sticky flag: the abort path set slot[0]=None before event.set
        if slot[0] is None and self._abort is not None:  # hvd-lint: ignore[lock-discipline]
            return JoinDoneMsg(None, abort=self._abort)  # hvd-lint: ignore[lock-discipline]
        return JoinDoneMsg(slot[0])

    def _check_join_barrier(self):  # holds: self._cv
        # all ranks joined and nothing pending -> release joins (reference:
        # controller joined handling: the join barrier completes only when
        # the tensor table is empty)
        if (len(self._joined) == self._size and not self._forming
                and self._join_waiters):
            last_rank = self._join_waiters[-1][0]
            for _, event, slot in self._join_waiters:
                slot[0] = last_rank
                event.set()
            self._join_waiters.clear()
            self._joined.clear()

    # ------------------------------------------------------------- execution
    def _complete(self, key, entry):  # holds: self._cv
        """Validate cross-rank agreement and compute every rank's result
        (reference: ConstructResponse validation + the backend op).
        ``key`` is the (group, name) negotiation key."""
        del self._forming[key]
        reqs = entry.requests
        try:
            results = self._execute(key, entry)
        except Exception as exc:  # noqa: BLE001 — done MUST be set: the
            # entry left _forming already, so an unset event would spin
            # every waiting rank forever with no stall escape
            results = {r: ResultMsg(error=str(exc)) for r in reqs}
        if self._autotune is not None:
            # only SUCCESSFUL entries score the tuner: a failed
            # collective transferred nothing, and counting its bytes
            # would inflate bytes/sec for whatever knob values were
            # active (the gmesh coordinator records validated-only for
            # the same reason)
            if not any(r.error or r.resend for r in results.values()):
                first = next(iter(reqs.values()))
                self._autotune.record(
                    np.dtype(first.dtype).itemsize
                    * int(np.prod(first.shape or (1,))))
            upd = self._autotune.maybe_update()
            if upd is not None:
                # publish: result messages carry the new values
                # (reference: SynchronizeParameters — rank 0 tunes,
                # winners ride the coordinator's responses).  Today both
                # _complete call sites already hold self._cv, so stores
                # are serialized; the lock + newer-seq guard are
                # DEFENSIVE, so a future call site outside _cv cannot
                # roll a later stamp back and leave ranks on stale
                # knobs until the next value change.
                with self._publish_lock:
                    if (self._published is None
                            or upd[0] > self._published[0]):
                        self._published = upd
                        self._sig_cache.enabled = upd[1]["cache_enabled"]
        # latest-wins advisory read: a racing publish just means the
        # stamp rides the next entry
        stamped = self._published  # hvd-lint: ignore[lock-discipline]
        if stamped is not None:
            # stamp HERE (one point per entry), not at each rank's
            # return: every rank of the same collective must see the
            # same (seq, params) — the "same cycle boundary" contract
            seq, params = stamped
            for resp in results.values():
                resp.params_seq, resp.params = seq, params
        entry.results = results
        entry.done.set()

    @property
    def cache_hits(self):
        return self._sig_cache.hits

    @staticmethod
    def _cache_name(key):
        """Signature-cache key for a (group, name) entry: group-
        qualified so the same tensor name in two groups can never hit
        the other's cached validation (docs/groups.md)."""
        group, name = key
        return f"g:{group}:{name}" if group else name

    def _cache_check(self, key, entry) -> bool:
        """Response-cache fast path (reference: response_cache.cc) — a
        steady-state name whose every rank resubmits the exact signature
        of the last validated round skips re-validation."""
        return self._sig_cache.check(
            self._cache_name(key), (r.sig for r in entry.requests.values()))

    def _cache_store(self, key, entry):
        self._sig_cache.store(
            self._cache_name(key), (r.sig for r in entry.requests.values()))

    def _ring_seg(self):
        """Coordinator-resolved pipeline segment size for a ring round:
        the latest published tuned value, or None before any
        publication (all ranks then share the identical launch-env
        value).  Stamped onto every ring_go so both endpoints of every
        hop derive the same segment plan even while a tuned value is
        still propagating rank by rank."""
        # latest-wins advisory read (see _complete)
        published = self._published  # hvd-lint: ignore[lock-discipline]
        if published is not None \
                and "ring_segment_bytes" in published[1]:
            return int(published[1]["ring_segment_bytes"])
        return None

    def _sched(self):
        """Latest published tuned schedule (autotune walk probing the
        schedule knob), or None when unpublished / left on auto."""
        # latest-wins advisory read (see _complete)
        published = self._published  # hvd-lint: ignore[lock-discipline]
        if published is not None and "schedule" in published[1]:
            val = published[1]["schedule"]
            if val and val != "auto":
                return str(val)
        return None

    def _plan_groups(self, participants):  # holds: self._cv
        """Partition ``participants`` into co-located groups for the
        hierarchical schedule.  Precedence: an explicit
        ``HVD_HIER_LOCAL_SIZE`` (> 0) chunks the sorted membership (the
        deterministic override, and the only grouping available on a
        single host); otherwise the launcher host hashes carried on
        heartbeats.  Returns None when no two-level plan exists (every
        rank on one host, or one rank per host, or unknown topology).
        Planned per collective from live membership, so an elastic
        reconfiguration that breaks a host group re-plans automatically
        — and because the plan is stamped on the ring_go, every
        survivor executes the identical (digest-identical) grouping."""
        ranks = sorted(participants)
        local = env_util.get_int(env_util.HVD_HIER_LOCAL_SIZE, 0)
        if local > 0:
            groups = [ranks[i:i + local]
                      for i in range(0, len(ranks), local)]
        else:
            by_host = {}
            for r in ranks:
                host = self._host_of.get(r)
                if host is None:
                    return None     # unknown topology: stay flat
                by_host.setdefault(host, []).append(r)
            groups = sorted(by_host.values(), key=lambda g: g[0])
        if len(groups) < 2 or all(len(g) == 1 for g in groups):
            return None
        return groups

    def _resolve_schedule(self, reqs, participants, nbytes):
        """Resolve the collective schedule for one ring round (same
        role as the compression resolution: unanimous request wins,
        disagreement falls back to auto).  Auto picks rhd in the
        latency-bound small-tensor regime, hierarchical when the
        topology offers co-located groups, flat ring otherwise.  A
        forced-but-infeasible hierarchical degrades to the flat ring;
        "star" reaching a ring round (possible mid-propagation of a
        tuned value) likewise runs flat — the star IS the payload
        path, decided worker-side before the ring_go."""
        from horovod_tpu.ops.python_controller import PythonController

        sched = PythonController.resolve_group_schedule(
            getattr(r, "schedule", "auto") for r in reqs.values())
        if sched == "auto":
            sched = self._sched() or "auto"
        groups = None
        if sched in ("auto", "hierarchical"):
            groups = self._plan_groups(participants)
        if sched == "auto":
            if (DEFAULT_RHD_MIN_BYTES <= nbytes <= DEFAULT_RHD_MAX_BYTES
                    and len(participants) > 1):
                sched = "rhd"
            elif groups is not None:
                sched = "hierarchical"
            else:
                sched = "flat_ring"
        if sched == "hierarchical" and groups is None:
            sched = "flat_ring"
        if sched != "hierarchical":
            groups = None
        if sched == "star":
            sched = "flat_ring"
        return sched, groups

    def _next_ring_id(self, group):  # holds: self._cv
        """Coordinator-assigned id for one ring round.  Grouped rounds
        live in a per-group namespace ("g<gid>:<seq>") so purge/straggler
        drops at the peer mailbox stay group-scoped (docs/groups.md);
        world rounds keep the bare integer for wire compatibility."""
        self._ring_seq += 1
        return f"g{group}:{self._ring_seq}" if group else self._ring_seq

    def _execute(self, key, entry):  # holds: self._cv
        _, name = key
        reqs = entry.requests
        first = next(iter(reqs.values()))
        rtype = RequestType(first.req_type)
        cached = self._cache_check(key, entry)
        # a grouped collective's "world" is its member list
        gsize = len(entry.group_ranks) if entry.group else self._size

        if not cached:
            for r in reqs.values():
                if r.req_type != first.req_type:
                    raise ValueError(
                        f"mismatched collective types for tensor "
                        f"'{first.name}'")
                if r.dtype != first.dtype:
                    raise ValueError(
                        f"mismatched dtypes for tensor '{first.name}'")

        # The coordinator RESOLVES the data plane: any rank asking for
        # the ring wins (thresholds can transiently disagree while
        # autotuned values propagate; every rank holds its array locally
        # so ring_go is always executable).  When the ring is infeasible
        # but payload-less requests exist, everyone resends with payload.
        ring = any(r.ring for r in reqs.values())

        if self._joined and rtype in (RequestType.ALLGATHER,
                                      RequestType.BROADCAST,
                                      RequestType.ALLTOALL,
                                      RequestType.REDUCE_SCATTER):
            raise ValueError(f"{rtype.name} is not supported while ranks "
                             f"have joined")

        if rtype in (RequestType.ALLREDUCE, RequestType.ADASUM):
            if not cached:
                for r in reqs.values():
                    if r.shape != first.shape:
                        raise ValueError(
                            f"mismatched shapes for allreduce "
                            f"'{first.name}'")
                    if r.op != first.op or r.prescale != first.prescale \
                            or r.postscale != first.postscale:
                        raise ValueError(
                            f"mismatched reduce ops or scale factors for "
                            f"tensor '{first.name}'")
                self._cache_store(key, entry)
            if ring and rtype == RequestType.ALLREDUCE:
                participants = sorted(reqs.keys())
                rid = self._next_ring_id(entry.group)
                # coordinator-resolved wire format (same role as the
                # ring-vs-payload resolution): unanimous choice wins,
                # disagreement — e.g. tuned params applied at slightly
                # different times on different ranks — resolves to the
                # exact path instead of erroring
                from horovod_tpu.ops.python_controller import \
                    PythonController

                comp = PythonController.resolve_group_compression(
                    getattr(r, "compression", "none")
                    for r in reqs.values())
                count = 1
                for d in first.shape:
                    count *= int(d)
                try:
                    nbytes = count * np.dtype(first.dtype).itemsize
                except TypeError:
                    nbytes = count * 2      # extension dtype (bf16)
                sched, groups = self._resolve_schedule(
                    reqs, participants, nbytes)
                return {r: ResultMsg(ring_go=True,
                                     participants=participants,
                                     ring_id=rid,
                                     compression=comp,
                                     ring_segment_bytes=self._ring_seg(),
                                     schedule=sched, groups=groups)
                        for r in reqs}
            if ring and rtype == RequestType.ADASUM:
                participants = sorted(reqs.keys())
                p = len(participants)
                # grouped adasum always rides the payload path: the
                # distributed VHDD tree is laid out over world positions
                if (not entry.group and p == self._size
                        and p & (p - 1) == 0):
                    rid = self._next_ring_id(entry.group)
                    return {r: ResultMsg(
                        ring_go=True, participants=participants,
                        ring_id=rid,
                        ring_segment_bytes=self._ring_seg())
                        for r in reqs}
                # joined ranks (zero stand-ins at world tree positions)
                # or non-power-of-two world: only the payload path keeps
                # the reference tree semantics — uniform resend
                return {r: ResultMsg(resend=True) for r in reqs}
            # reaching here means ring resolved False: every rank
            # submitted a payload (ring=True implies payload=None and
            # takes the branches above)
            arrs = {r: _decode(m) for r, m in reqs.items()}
            if rtype == RequestType.ADASUM:
                out = self._adasum(arrs, first,
                                   ranks=entry.group_ranks
                                   if entry.group else None)
            else:
                out = self._allreduce(arrs, first, divisor=gsize)
            return {r: _encode(out) for r in reqs}

        if rtype == RequestType.REDUCE_SCATTER:
            if not cached:
                if not first.shape:
                    raise ValueError(
                        f"reduce_scatter '{first.name}': 0-d tensors are "
                        f"not supported; reshape to (1,) first")
                for r in reqs.values():
                    if r.shape != first.shape:
                        raise ValueError(
                            f"mismatched shapes for reduce_scatter "
                            f"'{first.name}'")
                    if r.op != first.op or r.prescale != first.prescale \
                            or r.postscale != first.postscale:
                        raise ValueError(
                            f"mismatched reduce ops or scale factors for "
                            f"tensor '{first.name}'")
                self._cache_store(key, entry)
            if ring:
                participants = sorted(reqs.keys())
                rid = self._next_ring_id(entry.group)
                from horovod_tpu.ops.python_controller import \
                    PythonController

                comp = PythonController.resolve_group_compression(
                    getattr(r, "compression", "none")
                    for r in reqs.values())
                return {r: ResultMsg(ring_go=True,
                                     participants=participants,
                                     ring_id=rid,
                                     compression=comp,
                                     ring_segment_bytes=self._ring_seg())
                        for r in reqs}
            # star path: reduce exactly like the allreduce (ascending-
            # rank float64/int64 sum), then hand each rank its row block
            # of the np.array_split partition
            arrs = {r: _decode(m) for r, m in reqs.items()}
            out = self._allreduce(arrs, first, divisor=gsize)
            participants = sorted(reqs.keys())
            counts = reduce_scatter_split_sizes(first.shape[0],
                                                len(participants))
            results = {}
            off = 0
            for i, r in enumerate(participants):
                results[r] = _encode(out[off:off + counts[i]])
                off += counts[i]
            return results

        if rtype == RequestType.ALLGATHER:
            shapes = {r: m.shape for r, m in reqs.items()}
            trailing = {s[1:] for s in shapes.values()}
            if any(not s for s in shapes.values()):
                raise ValueError(
                    f"allgather '{first.name}': 0-d tensors are not "
                    f"supported; reshape to (1,) first")
            if len(trailing) > 1:
                raise ValueError(
                    f"mismatched trailing dimensions for allgather "
                    f"'{first.name}'")
            if ring:
                participants = sorted(reqs.keys())
                dims0 = [shapes[r][0] for r in participants]
                rid = self._next_ring_id(entry.group)
                return {r: ResultMsg(ring_go=True,
                                     participants=participants,
                                     dims0=dims0, ring_id=rid,
                                     ring_segment_bytes=self._ring_seg())
                        for r in reqs}
            out = np.concatenate(
                [_decode(reqs[r]) for r in sorted(reqs)], axis=0)
            return {r: _encode(out) for r in reqs}

        if rtype == RequestType.BROADCAST:
            if not cached:
                for r in reqs.values():
                    if r.root_rank != first.root_rank:
                        raise ValueError(
                            f"mismatched root ranks for broadcast "
                            f"'{first.name}'")
                    if r.shape != first.shape:
                        raise ValueError(
                            f"mismatched shapes for broadcast "
                            f"'{first.name}'")
                self._cache_store(key, entry)
            if first.root_rank not in reqs:
                raise ValueError(
                    f"broadcast '{first.name}': root rank "
                    f"{first.root_rank} did not participate")
            if ring:
                participants = sorted(reqs.keys())
                rid = self._next_ring_id(entry.group)
                return {r: ResultMsg(ring_go=True,
                                     participants=participants,
                                     ring_id=rid,
                                     ring_segment_bytes=self._ring_seg())
                        for r in reqs}
            out = _decode(reqs[first.root_rank])
            return {r: _encode(out) for r in reqs}

        if rtype == RequestType.ALLTOALL:
            pieces = {}
            offsets = {}
            for r, m in reqs.items():
                if m.splits is None or len(m.splits) != gsize:
                    raise ValueError(
                        f"alltoall '{first.name}': splits must have one "
                        f"entry per rank ({gsize})")
                if sum(m.splits) != (m.shape[0] if m.shape else 0):
                    raise ValueError(
                        f"alltoall '{first.name}': splits sum "
                        f"{sum(m.splits)} != first dimension "
                        f"{m.shape[0] if m.shape else 0}")
                arr = _decode(m)
                off = 0
                offsets[r] = []
                for n in m.splits:
                    pieces[(r, len(offsets[r]))] = arr[off:off + n]
                    offsets[r].append(n)
                    off += n
            # splits rows are indexed by GROUP-LOCAL position for grouped
            # entries (the member order the group was declared with); for
            # the world the global rank is the index
            order = list(entry.group_ranks) if entry.group else sorted(reqs)
            out = {}
            for dst in reqs:
                di = order.index(dst) if entry.group else dst
                parts = [pieces[(src, di)] for src in order]
                recv_splits = [offsets[src][di] for src in order]
                res = _encode(np.concatenate(parts, axis=0))
                res.recv_splits = recv_splits
                out[dst] = res
            return out

        raise ValueError(f"unknown request type {rtype}")

    def _allreduce(self, arrs, first, divisor=None):
        acc = None
        for r in sorted(arrs):
            a = arrs[r].astype(np.float64) if is_float_dtype(
                arrs[r].dtype) else arrs[r].astype(np.int64)
            if first.prescale != 1.0:
                a = a * first.prescale
            acc = a if acc is None else acc + a
        if ReduceOp(first.op) == ReduceOp.AVERAGE:
            # the divisor is the collective's world: the process group's
            # size for grouped entries, the full size otherwise (joined
            # ranks still count — they contribute zeros by contract)
            acc = acc / (divisor or self._size)
        if first.postscale != 1.0:
            acc = acc * first.postscale
        return acc.astype(np.dtype(first.dtype))

    def _adasum(self, arrs, first, ranks=None):
        from horovod_tpu.ops.adasum import adasum_reference

        # joined ranks contribute zero stand-ins, like the device-mode
        # executor (zero norm -> plain addition); a grouped entry's tree
        # spans exactly its member list
        tensors = []
        for r in (ranks if ranks is not None else range(self._size)):
            if r in arrs:
                tensors.append(arrs[r])
            else:
                tensors.append(np.zeros(first.shape,
                                        dtype=np.dtype(first.dtype)))
        return adasum_reference(tensors).astype(np.dtype(first.dtype))


# ----------------------------------------------------------------- controller
class TcpController:
    """Per-process controller facade (same interface as the in-process
    controllers: enqueue / join / start / shutdown)."""

    def __init__(self, topology, executor, timeline, config, epoch=0,
                 members=None):
        self._topo = topology
        self._executor = executor
        self._timeline = timeline
        self._config = config
        self._rank = topology.rank
        self._size = topology.size
        # elastic membership (docs/elastic.md): the epoch names this
        # controller's generation of the world; rendezvous scopes are
        # suffixed with it so a re-formed job can never read the old
        # world's addresses.  ``members`` lists the stable worker ids in
        # new-rank order (None: pre-elastic identity mapping).
        self._epoch = epoch
        self._members = (list(members) if members is not None
                         else list(range(self._size)))
        self._coordinator = None
        self._client_addrs = None
        self._mux = None            # guarded by self._mux_lock
        self._mux_lock = threading.Lock()
        self._key = None
        self._peer_service = None
        self._ring = None
        # per-group ring planes (docs/groups.md): each live group gets
        # its own RingPlane (own send queue, sender thread and stripe
        # connections) lazily on first grouped ring round, sharing the
        # one PeerService mailbox — the concurrency lever that lets two
        # groups' rounds be in flight at once; guarded by _rings_lock
        self._rings = {}
        self._rings_lock = threading.Lock()
        self._ring_threshold = env_util.get_int(
            env_util.HVD_TCP_RING_THRESHOLD, DEFAULT_RING_THRESHOLD)
        self._autotune = None       # rank 0 only
        # last applied (seq, params); guarded by self._tuned_lock
        self._tuned = None
        self._tuned_lock = threading.Lock()
        # (origin_rank, reason), sticky; guarded by self._abort_lock
        self._abort_state = None
        self._abort_lock = threading.Lock()
        # id(handle) -> handle (abort fan-out); guarded by self._abort_lock
        self._inflight = {}
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._host_hash_val = None      # cached launcher host identity
        self._log = get_logger()

    def _scope(self, base):
        """Rendezvous scope for this membership epoch.  Epoch 0 keeps
        the bare names (wire/rendezvous compatibility with every
        pre-elastic artifact); later epochs get a fresh namespace so
        survivors re-forming the job can never read the dead world's
        addresses."""
        return base if self._epoch == 0 else f"{base}.e{self._epoch}"

    def _start_timeout(self):
        # initial gang start keeps its own deadline; a reconfiguration
        # window is bounded by the (usually tighter) reconfig budget
        if self._epoch == 0:
            return env_util.get_float(env_util.HVD_START_TIMEOUT, 120.0)
        return self._config.reconfig_timeout_seconds

    # -------------------------------------------------------------- lifecycle
    def start(self):
        key_b64 = env_util.get_str(env_util.HVD_SECRET_KEY)
        if key_b64:
            self._key = base64.b64decode(key_b64)
        else:
            # standalone/testing: derive a per-job key from the rendezvous
            # location so all ranks agree
            seed = (env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR,
                                     "local") +
                    env_util.get_str(env_util.HVD_RENDEZVOUS_PORT, "0"))
            self._key = hashlib.sha256(seed.encode()).digest()

        addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
        port = env_util.get_str(env_util.HVD_RENDEZVOUS_PORT)
        if self._rank == 0:
            from horovod_tpu.ops.autotune import AutotuneManager
            self._autotune = AutotuneManager.create(self._config,
                                                    self._log)
            elastic_ctx = None
            if self._config.elastic and addr is not None:
                from horovod_tpu.elastic.membership import ElasticContext
                elastic_ctx = ElasticContext(
                    members=self._members, epoch=self._epoch,
                    min_ranks=self._config.min_ranks,
                    max_ranks=self._config.max_ranks,
                    rendezvous=(addr, int(port)),
                    coord_failover=self._config.coord_failover)
            self._coordinator = CoordinatorService(
                self._size, self._key,
                stall_warning_sec=self._config.stall_warning_seconds,
                stall_shutdown_sec=self._config.stall_shutdown_seconds,
                cache_capacity=self._config.cache_capacity,
                autotune=self._autotune,
                liveness_timeout_sec=self._config.liveness_timeout_seconds,
                epoch=self._epoch, elastic=elastic_ctx,
                straggler_factor=self._config.straggler_factor,
                straggler_windows=self._config.straggler_windows,
                straggler_exclude=self._config.straggler_exclude)
            tagged = [(iface, ip, self._coordinator.port)
                      for iface, ip in network.local_interfaces().items()]
            tagged.append(("lo", "127.0.0.1", self._coordinator.port))
            if addr is not None:
                from horovod_tpu.run import http_client
                http_client.put(
                    addr, int(port), self._scope(CONTROLLER_SCOPE),
                    CONTROLLER_KEY,
                    ";".join(f"{i}={ip}:{p}"
                             for i, ip, p in tagged).encode())
                if self._epoch > 0:
                    # dead-epoch cleanup: the previous memberships'
                    # suffixed scopes would otherwise accumulate on the
                    # rendezvous server for the life of the job.  Every
                    # epoch < ours is torn down by construction (we are
                    # the reconfigured successor); best-effort — a
                    # leaked scope is garbage, not a correctness hazard.
                    # A GC watermark bounds the sweep to the epochs
                    # since the LAST purge: rescanning 0..k-1 on every
                    # reconfiguration is O(k^2) cumulative rendezvous
                    # calls — a rank-0 hot spot under elastic churn at
                    # soak scale.
                    purge_from = 0
                    try:
                        purge_from = int(http_client.get(
                            addr, int(port), GC_SCOPE, GC_PURGED_KEY,
                            timeout=2.0, retry_for=0).decode()) + 1
                    except Exception:  # noqa: BLE001 — first purge
                        pass
                    for e in range(purge_from, self._epoch):
                        suffix = "" if e == 0 else f".e{e}"
                        for base in (CONTROLLER_SCOPE, PEERS_SCOPE,
                                     TIMELINE_SCOPE):
                            try:
                                http_client.delete_scope(
                                    addr, int(port), f"{base}{suffix}")
                            except Exception:  # noqa: BLE001
                                pass
                    try:
                        http_client.put(
                            addr, int(port), GC_SCOPE, GC_PURGED_KEY,
                            str(self._epoch - 1).encode(),
                            retry_for=2.0)
                    except Exception:  # noqa: BLE001 — next purge
                        # just rescans from the stale watermark
                        pass
            self._client_addrs = self._filter_ifaces(tagged)
        else:
            if addr is None:
                raise RuntimeError(
                    "multi-process mode requires the rendezvous env "
                    "contract (launch with hvdrun)")
            from horovod_tpu.run import http_client
            blob = http_client.get(
                addr, int(port), self._scope(CONTROLLER_SCOPE),
                CONTROLLER_KEY, timeout=self._start_timeout()).decode()
            tagged = []
            for part in blob.split(";"):
                iface, rest = part.split("=", 1)
                ip, p = rest.rsplit(":", 1)
                tagged.append((iface, ip, int(p)))
            self._client_addrs = self._filter_ifaces(tagged)

        # peer mailbox for the ring data plane (epoch-stamped: stale
        # chunks from a pre-reconfiguration ring are refused at framing)
        self._peer_service = PeerService(self._key, epoch=self._epoch)
        # a peer-pushed abort must fail negotiation-blocked handles too,
        # not only blocked ring recvs (no re-fan-out: the pusher
        # already reached every peer)
        self._peer_service.abort_callback = self._on_peer_abort
        if addr is not None:
            from horovod_tpu.run import http_client
            tagged = [(iface, ip, self._peer_service.port)
                      for iface, ip in network.local_interfaces().items()]
            tagged.append(("lo", "127.0.0.1", self._peer_service.port))
            http_client.put(addr, int(port), self._scope(PEERS_SCOPE),
                            str(self._rank),
                            ";".join(f"{i}={ip}:{p}"
                                     for i, ip, p in tagged).encode())
            self._ring = RingPlane(
                self._rank, self._peer_service, self._resolve_peer,
                resolve_bulk=self._resolve_stripe,
                segment_bytes=self._config.ring_segment_bytes,
                stripes=self._config.ring_stripes,
                epoch=self._epoch)

        # peer liveness: a background heartbeat per worker keeps the
        # coordinator's last-seen table fresh AND carries the abort
        # state back, so a rank blocked on ring chunks (never touching
        # the control plane) still observes a coordinated abort within
        # one heartbeat interval
        from horovod_tpu.common.config import effective_heartbeat_interval
        interval = effective_heartbeat_interval(self._config)
        if self._size > 1 and interval > 0:
            # one synchronous beat before init returns: the coordinator
            # knows this rank exists BEFORE any user collective can run,
            # so a crash at ANY later point falls inside the liveness
            # window.  Failing this beat is fatal — a silently-skipped
            # registration would leave this rank invisible to liveness
            # (the monitor only watches ranks it has seen), reopening
            # the unbounded-hang window for the peers.  The mux client's
            # own connect retry already absorbed transient blips.
            try:
                t0 = time.monotonic()
                # the registration beat carries this rank's launcher
                # host hash: the coordinator needs the full topology
                # BEFORE the first collective so the hierarchical
                # schedule is plannable from round one
                self._client().send(
                    network.HeartbeatMsg(self._rank,
                                         host=self._host_hash()),
                    timeout=30.0)
                # seed the control-plane RTT EWMA with the very first
                # round-trip so the adaptive deadline starts from a
                # measured baseline, not from zero slack
                rtt_mod.tracker().sample(rtt_mod.COORD_KEY,
                                         time.monotonic() - t0)
            except Exception as exc:
                raise RuntimeError(
                    f"rank {self._rank} could not register with the "
                    f"coordinator at startup: {exc}") from exc
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(interval,),
                daemon=True, name="hvd-heartbeat")
            self._hb_thread.start()

    def _peer_addrs(self, rank, resolve_timeout, retry_for=None):
        from horovod_tpu.run import http_client

        addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
        port = env_util.get_str(env_util.HVD_RENDEZVOUS_PORT)
        kwargs = {} if retry_for is None else {"retry_for": retry_for}
        blob = http_client.get(addr, int(port), self._scope(PEERS_SCOPE),
                               str(rank), timeout=resolve_timeout,
                               **kwargs).decode()
        tagged = []
        for part in blob.split(";"):
            iface, rest = part.split("=", 1)
            ip, p = rest.rsplit(":", 1)
            tagged.append((iface, ip, int(p)))
        return self._filter_ifaces(tagged)

    def _resolve_peer(self, rank):
        # epoch rides along so a session healing across a
        # reconfiguration is fenced by the peer's PeerService instead
        # of replaying a torn-down ring's frames into the new epoch
        return network.MuxClient(
            self._peer_addrs(rank, env_util.get_float(
                env_util.HVD_START_TIMEOUT, 120.0)),
            self._key, timeout=30, peer=rank, epoch=self._epoch)

    def _resolve_stripe(self, rank):
        """One dedicated bulk-data connection to ``rank``'s mailbox —
        the ring opens up to HVD_TPU_RING_STRIPES of these per peer,
        so chunk segments never share a socket (or a write lock) with
        control traffic."""
        return network.StripeClient(
            self._peer_addrs(rank, env_util.get_float(
                env_util.HVD_START_TIMEOUT, 120.0)),
            self._key, timeout=30, peer=rank, epoch=self._epoch)

    @staticmethod
    def _filter_ifaces(tagged):
        """Pin to the launcher-discovered interface when HVD_IFACE is set
        and the coordinator advertises it; otherwise keep every address
        (reference: NIC discovery exporting the common interface)."""
        iface = env_util.get_str(env_util.HVD_IFACE)
        pinned = [(ip, p) for i, ip, p in tagged if i == iface]
        return pinned or [(ip, p) for _, ip, p in tagged]

    def _client(self):
        # ONE persistent multiplexed connection (v2); concurrent
        # blocking requests ride separate mux frames.  Guarded: many
        # request threads hit first-use together (one burst per backward
        # pass) and unsynchronized construction leaks every loser's
        # socket + reader thread
        with self._mux_lock:
            if self._mux is None:
                self._mux = network.MuxClient(self._client_addrs,
                                              self._key, timeout=30,
                                              peer=0)
            return self._mux

    def _spawn(self, target, *args):
        # one daemon thread per in-flight request (a bounded pool of
        # blocking round-trips can deadlock: with >pool outstanding
        # collectives submitted in different per-rank orders, no name ever
        # has all contributions.  The reference's request inserts are
        # non-blocking for the same reason.)
        threading.Thread(target=target, args=args, daemon=True,
                         name="hvd-tcp-req").start()

    # ------------------------------------------------------- fault tolerance
    def _host_hash(self):
        """This rank's launcher host identity (run/host_hash.py),
        computed once: heartbeats carry it so the coordinator can group
        co-located ranks when planning the hierarchical schedule."""
        if self._host_hash_val is None:
            from horovod_tpu.run.host_hash import host_hash
            self._host_hash_val = host_hash()
        return self._host_hash_val

    def _heartbeat_loop(self, interval):
        # a DEDICATED no-retry client: the shared mux's connect retry
        # (HVD_TPU_CONNECT_RETRY_SECONDS per attempt) would stretch the
        # dead-coordinator budget below to a multiple of itself, and a
        # failed heartbeat must be cheap to observe
        hb_client = network.MuxClient(self._client_addrs, self._key,
                                      timeout=max(interval, 2.0),
                                      retry_for=0, peer=0)
        tracker = rtt_mod.tracker()
        fail_since = None
        try:
            while True:
                try:
                    t0 = time.monotonic()
                    # each beat carries the worst smoothed RTT this rank
                    # observes (control plane or ring acks): the
                    # coordinator widens this rank's liveness deadline
                    # by that slack, telling slow-but-alive from dead
                    reply = hb_client.send(
                        network.HeartbeatMsg(
                            self._rank,
                            busy=busy.active(),
                            rtt=tracker.worst() or None,
                            host=self._host_hash(),
                            # peers this rank is healing a session
                            # toward RIGHT NOW: the coordinator widens
                            # the liveness window and skips straggler
                            # verdicts instead of reading the recovery
                            # pause as death
                            reconnecting=network.healing_peers() or None),
                        timeout=max(interval * 2, 5.0))
                    tracker.sample(rtt_mod.COORD_KEY,
                                   time.monotonic() - t0)
                except Exception as exc:  # noqa: BLE001 — outage
                    now = time.monotonic()
                    fail_since = (fail_since if fail_since is not None
                                  else now)
                    # the abort deadline, not the liveness window,
                    # bounds how long this rank may spin against a dead
                    # coordinator; a measured-slow network widens the
                    # budget by the same capped slack the coordinator
                    # grants us, so both sides give up symmetrically
                    budget = (self._config.abort_timeout_seconds
                              or self._config.liveness_timeout_seconds)
                    if budget > 0:
                        budget += min(
                            tracker.worst()
                            * self._config.straggler_factor,
                            budget)
                    if budget > 0 and now - fail_since > budget:
                        # a dead coordinator must fail the job, not
                        # hang it: fail-over election when armed,
                        # else self-abort naming the coordinator
                        self._coordinator_lost(
                            f"coordinator unreachable for "
                            f"{int(now - fail_since)}s: {exc}")
                        return
                else:
                    fail_since = None
                    ab = getattr(reply, "abort", None)
                    if ab is not None:
                        self._learned_abort(*ab)
                        return
                # first beat went out BEFORE the first wait: the
                # coordinator learns this rank exists the moment init
                # completes, so a rank that dies at any later point is
                # inside the liveness window from its very first
                # collective
                if self._hb_stop.wait(timeout=interval):
                    return
        finally:
            hb_client.close()

    def _coordinator_lost(self, reason):
        """Every path that decides the coordinator is unreachable funnels
        here: with fail-over armed the survivors race the rendezvous CAS
        election and the winning reconfiguration directive replaces the
        fatal abort — the same typed delivery, a different verdict.  Not
        armed (or the election is not winnable): today's exact behavior,
        a fatal self-abort naming the coordinator rank."""
        directive = self._try_failover(reason)
        self._local_abort(0, directive if directive is not None
                          else reason)

    def _try_failover(self, reason):
        """Attempt the coordinator fail-over election
        (docs/elastic.md#coordinator-fail-over).  Returns the winning
        reconfiguration directive, or None when fail-over is off, not
        survivable (below --min-ranks), or the election cannot be won
        within HVD_TPU_ELECTION_TIMEOUT — every None falls back to the
        fatal path, byte-identical to fail-over-off behavior."""
        if not (self._config.coord_failover and self._config.elastic):
            return None
        if self._rank == 0 or self._size <= 1:
            # rank 0 IS the coordinator host: its own unreachability
            # verdict means this process is the casualty, not a survivor
            return None
        with self._abort_lock:
            if self._abort_state is not None:
                return None   # a verdict (or directive) already landed
        addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
        port = env_util.get_str(env_util.HVD_RENDEZVOUS_PORT)
        if addr is None or port is None:
            return None   # no rendezvous server, no election ground
        if len(self._members) - 1 < self._config.min_ranks:
            self._log.error(
                "fail-over: %d survivors < --min-ranks %d; coordinator "
                "loss is fatal", len(self._members) - 1,
                self._config.min_ranks)
            return None
        from horovod_tpu.elastic import election
        return election.elect(
            addr, int(port), self._epoch, self._members, reason,
            proposer_wid=self._members[self._rank],
            timeout=self._config.election_timeout_seconds)

    def _local_abort(self, origin_rank, reason, fan_out=True):
        """Apply a coordinated abort on this worker: purge the ring
        mailbox (waking every blocked ``recv`` with the typed error) and
        fail all in-flight handles symmetrically.  ``fan_out=False``
        when the abort ARRIVED as a peer push — the pushing rank already
        reached everyone, and N ranks each re-pushing to N-1 peers would
        be an O(N^2) storm of fresh rendezvous lookups mid-failure."""
        with self._abort_lock:
            if self._abort_state is not None:
                return
            self._abort_state = (origin_rank, reason)
            inflight = list(self._inflight.values())
            self._inflight.clear()
        self._log.error("aborting collectives (origin rank %s): %s",
                        origin_rank, reason)
        # push to every peer mailbox BEFORE waking local waiters: a
        # waiter's thread may exit the process (taking the coordinator
        # with it on rank 0) the moment it observes the error, and the
        # peers must have heard by then — heartbeats remain the backstop
        # for peers the push cannot reach
        if fan_out:
            self._push_abort_to_peers(origin_rank, reason)
        if self._peer_service is not None:
            self._peer_service.abort(origin_rank, reason)
        exc = make_abort_error(origin_rank, reason)
        for handle in inflight:
            handle.set_error(exc)

    def _on_peer_abort(self, origin_rank, reason):
        """PeerService push receipt: apply locally, no re-fan-out."""
        self._local_abort(origin_rank, reason, fan_out=False)

    def _learned_abort(self, origin_rank, reason):
        """Abort learned from a live coordinator (heartbeat reply,
        negotiation/join response).  Only rank 0 re-pushes to peers: its
        process HOSTS the coordinator, so its exit would cut the relay
        before slower ranks hear — every other rank can rely on its own
        heartbeat, keeping the fan-out O(N) instead of O(N^2).

        A drain-marked directive skips even that push: nothing crashed,
        every rank is alive and heartbeating, so pull delivery reaches
        everyone within one interval without the abort storm the drain
        protocol exists to avoid."""
        self._local_abort(origin_rank, reason,
                          fan_out=(self._rank == 0
                                   and not is_drain_reason(reason)))

    def _push_abort_to_peers(self, origin_rank, reason, budget=2.0):
        """Best-effort direct abort fan-out to every peer's mailbox
        service (bounded: dead peers refuse the connect instantly,
        unreachable ones are cut off by the join budget).  Reuses the
        ring's live peer connections where they exist; otherwise one
        short-budget resolve + connect per peer.

        Pushes ride a BOUNDED worker pool, not a thread per peer: at
        soak scale (64 ranks) a per-peer burst is 63 simultaneous
        thread spawns + rendezvous resolves on the failing rank — an
        O(N) hot spot exactly when the process is dying.  Each pool
        worker walks a strided slice of the peer list, so a stuck peer
        delays only its own slice and the deadline still bounds the
        whole fan-out; heartbeats remain the backstop for peers the
        pool never reached."""
        if self._ring is None:
            return

        deadline = time.monotonic() + budget

        def push_one(rank):
            try:
                cached = self._ring.cached_peer(rank)
                if cached is not None:
                    cached.post(network.AbortMsg(origin_rank, reason))
                    return
                client = network.MuxClient(
                    self._peer_addrs(rank, resolve_timeout=2.0,
                                     retry_for=0),
                    self._key, timeout=2, retry_for=0, peer=rank)
                try:
                    client.post(network.AbortMsg(origin_rank, reason))
                finally:
                    client.close()
            except Exception:  # noqa: BLE001 — heartbeat backstop
                pass

        def push_slice(ranks):
            for rank in ranks:
                if time.monotonic() >= deadline:
                    return
                push_one(rank)

        peers = [r for r in range(self._size) if r != self._rank]
        if not peers:
            return
        width = min(8, len(peers))
        threads = [threading.Thread(target=push_slice,
                                    args=(peers[i::width],),
                                    daemon=True, name="hvd-abort-push")
                   for i in range(width)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def _report_abort(self, origin_rank, reason):
        """Broadcast an abort: best-effort notify the coordinator (which
        relays it to every rank via heartbeat replies and negotiation
        responses), then apply it locally.  When the notify fails AND
        the evidence already names rank 0 dead (a ring send to the
        coordinator's own process broke — RingSendError(peer=0)), the
        two signals corroborate: the coordinator is gone, so this is a
        fail-over trigger, not merely an undeliverable report."""
        from horovod_tpu.elastic.membership import USER_ABORT_PREFIX
        try:
            self._client().send(network.AbortMsg(origin_rank, reason),
                                timeout=5.0)
        except Exception:  # noqa: BLE001 — local abort still proceeds
            if (origin_rank == 0
                    and not (isinstance(reason, str)
                             and reason.startswith(USER_ABORT_PREFIX))):
                self._coordinator_lost(reason)
                return
        self._local_abort(origin_rank, reason)

    def abort(self, origin_rank, reason):
        """Any rank may broadcast an abort for the in-flight round
        (``hvd.abort()``); all ranks raise ``HvdAbortedError`` within
        the abort deadline."""
        self._report_abort(origin_rank, reason)

    def request_drain(self) -> bool:
        """Announce this rank's planned departure (preemption notice)
        to the coordinator and wait for its verdict.  True: a boundary
        reconfiguration without this rank is in flight — keep running
        until the directive arrives.  False: the drain is not
        survivable (single process, elastic off, coordinator rank, too
        few survivors) and the caller should treat the preemption as
        death."""
        if self._size <= 1 or self._client_addrs is None:
            return False
        try:
            # 30s cap: the coordinator's boundary wait is bounded at 5s,
            # the rest is headroom for a loaded control plane
            reply = self._client().send(DrainMsg(self._rank),
                                        timeout=30.0)
        except Exception as exc:  # noqa: BLE001 — a dead coordinator
            # while this rank is being preempted: nothing to drain into
            self._log.warning("drain announce failed: %s", exc)
            return False
        return bool(getattr(reply, "ok", False))

    # ------------------------------------------------------------ producer API
    def enqueue(self, request):
        with self._abort_lock:
            ab = self._abort_state
            if ab is None:
                self._inflight[id(request.handle)] = request.handle
        if ab is not None:
            request.handle.set_error(make_abort_error(*ab))
            return
        self._spawn(self._run_one, request)

    def _ring_for(self, group):
        """The ring plane a round runs on: the world plane, or the
        group's own lazily-built plane (same resolver + PeerService,
        independent sender/stripes so concurrent groups never share a
        send queue)."""
        if not group:
            return self._ring
        with self._rings_lock:
            plane = self._rings.get(group)
            if plane is None:
                plane = RingPlane(
                    self._rank, self._peer_service, self._resolve_peer,
                    resolve_bulk=self._resolve_stripe,
                    segment_bytes=self._config.ring_segment_bytes,
                    stripes=self._config.ring_stripes,
                    epoch=self._epoch)
                self._rings[group] = plane
            return plane

    def _use_ring(self, req_type, nbytes):
        if self._ring is None or self._size <= 1:
            return False
        rtype = RequestType(req_type)
        if rtype == RequestType.ALLGATHER:
            # first dims legitimately differ per rank, so a local
            # nbytes-vs-threshold choice would disagree across ranks;
            # the ring is the uniform choice
            return True
        if rtype == RequestType.ADASUM:
            # distributed VHDD only over the full power-of-two world;
            # the coordinator still referees (joined ranks force the
            # payload path via resend)
            return (nbytes >= self._ring_threshold
                    and self._size & (self._size - 1) == 0)
        if rtype == RequestType.ALLREDUCE:
            # the schedule knob owns the ring-vs-star choice for
            # allreduce: a forced ring schedule always negotiates
            # ring_go, "star" always rides coordinator payloads, and
            # auto keeps the threshold split — sub-threshold tensors
            # stay on the star (its single fused round-trip plus the
            # fusion/caching machinery beat per-tensor ring
            # negotiation there); WHICH peer pattern a ring-bound
            # tensor runs is the coordinator's pick (_resolve_schedule:
            # rhd in the latency band, hierarchical over groups)
            sched = getattr(self._config, "schedule", "auto")
            if sched == "star":
                return False
            if sched in ("flat_ring", "hierarchical", "rhd"):
                return True
            return nbytes >= self._ring_threshold
        return (nbytes >= self._ring_threshold
                and rtype in (RequestType.BROADCAST,
                              RequestType.REDUCE_SCATTER))

    def _run_one(self, request, force_payload=False):
        dropped = False
        try:
            arr = np.asarray(request.tensor)
            arr, wire_dtype = _wire_dtype(arr)
            rtype = RequestType(request.req_type)
            if not force_payload and faults.check(rtype.name.lower()):
                # injected drop: this rank silently never contributes —
                # the handle is failed by the eventual stall/liveness
                # abort (it stays registered in _inflight)
                dropped = True
                return
            ring = (not force_payload
                    and self._use_ring(request.req_type, arr.nbytes))
            msg = CollectiveMsg(
                name=request.name, rank=self._rank,
                req_type=request.req_type, op=request.op,
                payload=(None if ring
                         else np.ascontiguousarray(arr).tobytes()),
                shape=arr.shape, dtype=wire_dtype,
                root_rank=request.root_rank, splits=request.splits,
                prescale=request.prescale_factor,
                postscale=request.postscale_factor, ring=ring,
                compression=getattr(request, "compression", "none"),
                epoch=self._epoch,
                schedule=getattr(self._config, "schedule", "auto"),
                group=getattr(request, "group", ""),
                group_ranks=getattr(request, "group_ranks", None))
            msg.sig = _signature(msg)
            self._timeline.begin(request.name,
                                 f"NEGOTIATE_{rtype.name}")
            try:
                resp = self._client().send(msg)
            except (ConnectionError, TimeoutError, OSError) as exc:
                # the control plane is gone (mux retry budget spent):
                # surface the SAME typed, symmetric error as the
                # heartbeat self-abort, not a one-off transport string
                # — or, fail-over armed, the SAME election verdict
                self._coordinator_lost(
                    f"coordinator unreachable during negotiation of "
                    f"'{request.name}': {exc}")
                # sticky: _local_abort just set it (or an earlier abort
                # did); set-once means this read cannot tear
                request.handle.set_error(
                    make_abort_error(*self._abort_state))  # hvd-lint: ignore[lock-discipline]
                return
            self._timeline.end(request.name)
            self._maybe_apply_params(resp)
            ab = getattr(resp, "aborted", None)
            if ab is not None:
                # coordinated abort: fail EVERY in-flight handle (this
                # one included) with the one typed error + purge rings
                self._learned_abort(*ab)
                request.handle.set_error(make_abort_error(*ab))
                return
            if resp.error is not None:
                request.handle.set_error(resp.error)
                return
            if getattr(resp, "resend", False):
                # coordinator resolved to the payload path but this
                # round had payload-less submissions — one uniform retry
                self._run_one(request, force_payload=True)
                return
            if resp.ring_go:
                # "ring" fires AFTER negotiation: crash models a rank
                # dying mid-collective with peers already committed;
                # drop models a rank silently abandoning the round (its
                # handle stays registered for the eventual abort, and
                # the peers' recv backstop converts the silence)
                if faults.check("ring"):
                    dropped = True
                    return
                out = self._run_ring(rtype, request, arr, resp)
            else:
                self._timeline.begin(request.name, rtype.name)
                out = np.frombuffer(
                    resp.payload,
                    dtype=np.dtype(resp.dtype)).reshape(resp.shape)
                self._timeline.end(request.name,
                                   {"bytes": out.nbytes})
            if out.dtype.itemsize >= 8 or out.dtype.kind == "u":
                # jax without x64 narrows 64-bit dtypes (and flips some
                # unsigned ints); the tcp plane promises exact transport,
                # so hand back numpy without paying a device copy
                result = out
            else:
                import jax.numpy as jnp

                result = jnp.asarray(out)
            if rtype == RequestType.ALLTOALL:
                request.handle.set_result((result, resp.recv_splits))
            else:
                request.handle.set_result(result)
        except HvdError as exc:  # typed (e.g. HvdAbortedError): keep it
            request.handle.set_error(exc)
        except Exception as exc:  # noqa: BLE001 — surface on the handle
            request.handle.set_error(str(exc))
        finally:
            if not dropped:
                with self._abort_lock:
                    self._inflight.pop(id(request.handle), None)

    def _run_ring(self, rtype, request, arr, resp):
        """Execute the worker-ring data plane after the coordinator's
        metadata go-ahead."""
        self._timeline.begin(request.name, f"RING_{rtype.name}")
        # every ring recv is time-bounded even with the stall shutdown
        # off: post-negotiation all participants are committed, so a
        # chunk that never arrives (silently dropped on the wire, sender
        # wedged but still heartbeating) is a failure to detect — the
        # timeout converts it into a coordinated abort below instead of
        # an indefinite wait.  4x the abort deadline leaves generous
        # room for a slow multi-hundred-MB ring step.
        timeout = (self._config.stall_shutdown_seconds
                   or (self._config.abort_timeout_seconds * 4
                       if self._config.abort_timeout_seconds > 0
                       else None))
        # coordinator-resolved segment size for this round (None until
        # a tuned value is published): both endpoints of every ring hop
        # must slice identically, whatever this rank last applied
        seg = getattr(resp, "ring_segment_bytes", None)
        # coordinator-resolved schedule for this round, stamped like the
        # segment size so every participant runs the identical plan
        sched = getattr(resp, "schedule", None)
        groups = getattr(resp, "groups", None)
        # grouped rounds run on the group's own plane; the effective
        # world of an AVERAGE (and of split planning) is the group size
        gid = getattr(request, "group", "")
        plane = self._ring_for(gid)
        wsize = (len(request.group_ranks)
                 if gid and request.group_ranks else self._size)
        try:
            if rtype == RequestType.ALLREDUCE:
                kwargs = dict(
                    op_average=(ReduceOp(request.op) == ReduceOp.AVERAGE),
                    world_size=wsize,
                    prescale=request.prescale_factor,
                    postscale=request.postscale_factor, timeout=timeout,
                    compression=getattr(resp, "compression", "none"),
                    segment_bytes=seg)
                if sched == "hierarchical" and groups:
                    out = plane.allreduce_hierarchical(
                        resp.ring_id, arr, resp.participants, groups,
                        **kwargs)
                elif sched == "rhd":
                    out = plane.allreduce_rhd(
                        resp.ring_id, arr, resp.participants, **kwargs)
                else:
                    out = plane.allreduce(
                        resp.ring_id, arr, resp.participants, **kwargs)
            elif rtype == RequestType.REDUCE_SCATTER:
                out = plane.reduce_scatter(
                    resp.ring_id, arr, resp.participants,
                    op_average=(ReduceOp(request.op) == ReduceOp.AVERAGE),
                    world_size=wsize,
                    prescale=request.prescale_factor,
                    postscale=request.postscale_factor, timeout=timeout,
                    compression=getattr(resp, "compression", "none"),
                    segment_bytes=seg)
            elif rtype == RequestType.ADASUM:
                out = plane.adasum(
                    resp.ring_id, arr, resp.participants, timeout=timeout,
                    segment_bytes=seg)
            elif rtype == RequestType.BROADCAST:
                out = plane.broadcast(
                    resp.ring_id,
                    arr if self._rank == request.root_rank else None,
                    resp.participants, request.root_rank,
                    shape=tuple(arr.shape), dtype=arr.dtype.name,
                    timeout=timeout, segment_bytes=seg)
            else:  # ALLGATHER
                trailing = arr.shape[1:]
                per_row = int(np.prod(trailing or (1,))) \
                    * arr.dtype.itemsize
                blocks = plane.allgather(
                    resp.ring_id, arr, resp.participants,
                    block_nbytes=[d * per_row for d in resp.dims0],
                    timeout=timeout, segment_bytes=seg)
                parts = [np.frombuffer(
                    b, dtype=arr.dtype).reshape((d,) + trailing)
                    for b, d in zip(blocks, resp.dims0)]
                out = np.concatenate(parts, axis=0)
        except HvdAbortedError:
            # already a coordinated abort (the peer mailbox was purged
            # wholesale when it was applied) — just propagate the type
            raise
        except BaseException as exc:
            # drop any chunks of the aborted round so nothing lingers
            # (a retry gets a fresh ring_id and can never match them) …
            if self._peer_service is not None:
                self._peer_service.purge(resp.ring_id)
            # … then turn the local failure (recv timeout, codec error,
            # dead neighbor) into a coordinated abort: the OTHER ranks of
            # this round are blocked on chunks this rank will never send,
            # and without the broadcast they would hang or time out
            # asymmetrically with leaked mailbox state.  When the
            # failure PROVES a peer dead (RingSendError: the transport
            # write to that rank broke), the abort origin is THAT rank
            # — the same origin the liveness monitor would name — so
            # culprit attribution doesn't depend on which detector
            # fires first under machine load (the mid-ring crash
            # flake).  A recv timeout is NOT such proof: in a 3+-rank
            # ring the silent predecessor is usually blocked behind
            # the real casualty, so it names this rank as before.
            origin = exc.peer_rank if isinstance(
                exc, RingSendError) else self._rank
            reason = (f"ring {rtype.name.lower()} '{request.name}' failed "
                      f"on rank {self._rank}: {exc}")
            self._report_abort(origin, reason)
            raise HvdAbortedError(origin, reason) from exc
        finally:
            self._timeline.end(request.name, {"bytes": arr.nbytes})
        return out

    # req-exempt: JOIN — joins never travel through the collective
    # dispatch; they cross the wire as the dedicated JoinMsg barrier
    # below (docs/elastic.md)
    def join(self, rank, handle):
        def run():
            try:
                resp = self._client().send(JoinMsg(rank))
                ab = getattr(resp, "abort", None)
                if ab is not None:
                    self._learned_abort(*ab)
                    handle.set_error(make_abort_error(*ab))
                    return
                handle.set_result(resp.last_rank)
            except Exception as exc:  # noqa: BLE001
                handle.set_error(str(exc))
            finally:
                with self._abort_lock:
                    self._inflight.pop(id(handle), None)

        with self._abort_lock:
            ab = self._abort_state
            if ab is None:
                self._inflight[id(handle)] = handle
        if ab is not None:
            handle.set_error(make_abort_error(*ab))
            return
        self._spawn(run)

    # -------------------------------------------------------------- autotune
    def _maybe_apply_params(self, resp):
        """Apply tuned knob values published by the coordinator
        (reference: SynchronizeParameters applies rank-0's winners on
        every rank).  The knob this data plane owns is its byte-size
        cutover: the tuned fusion threshold IS the ring threshold — the
        size above which tensors take the bulk p2p path instead of
        riding coordinator payloads (same role the fusion threshold
        plays for the in-process planners).  A transiently-stale
        threshold on some rank is safe: the coordinator resolves the
        ring-vs-payload choice per tensor and all participants follow
        its ring_go."""
        seq = getattr(resp, "params_seq", 0)
        params = getattr(resp, "params", None)
        if not params:
            return
        # in-flight request threads race here: without the lock a
        # thread holding an OLDER stamp could overwrite a newer one
        with self._tuned_lock:
            if self._tuned is not None and seq <= self._tuned[0]:
                return
            self._tuned = (seq, dict(params))
            self._ring_threshold = params["fusion_threshold_bytes"]
            self._config.fusion_threshold_bytes = \
                params["fusion_threshold_bytes"]
            self._config.cycle_time_ms = params["cycle_time_ms"]
            if "compression" in params:
                self._config.compression = params["compression"]
            # ring transfer-engine knobs: every rank of a collective
            # receives the same (seq, params) stamp with its ring_go
            # and applies it BEFORE running the ring, so the segment
            # plan both endpoints derive stays identical within a round
            if "ring_segment_bytes" in params:
                self._config.ring_segment_bytes = \
                    int(params["ring_segment_bytes"])
                for plane in self._all_ring_planes():
                    plane.segment_bytes = \
                        int(params["ring_segment_bytes"])
            if "ring_stripes" in params:
                self._config.ring_stripes = int(params["ring_stripes"])
                for plane in self._all_ring_planes():
                    plane.stripes = int(params["ring_stripes"])
            if "schedule" in params:
                # worker-side effect is the ring-vs-star choice in
                # _use_ring; the per-round plan itself always comes
                # stamped on the ring_go, so a transiently-stale value
                # here can never desync a round
                self._config.schedule = str(params["schedule"])

    def _all_ring_planes(self):
        """World plane + every live group plane (tuned-knob fan-out and
        teardown walk the same list)."""
        with self._rings_lock:
            planes = list(self._rings.values())
        if self._ring is not None:
            planes.append(self._ring)
        return planes

    def _close_ring_planes(self):
        with self._rings_lock:
            planes, self._rings = list(self._rings.values()), {}
        for plane in planes:
            plane.close()
        if self._ring is not None:
            self._ring.close()
            self._ring = None

    def tuned_params(self):
        """Same surface as the native controller (reference:
        ParameterManager values after SynchronizeParameters)."""
        if self._autotune is not None:    # rank 0: live tuner view
            return self._autotune.params()
        with self._tuned_lock:
            if self._tuned is not None:
                return dict(self._tuned[1])
        from horovod_tpu.ops.autotune import default_params
        return default_params(self._config)

    def close_for_reconfig(self):
        """Tear down this controller's generation of the world so a
        successor at the next membership epoch can be built: no
        ShutdownMsg (the coordinator we would deregister from is part of
        the dead world), no timeline merge (that is a job-end barrier —
        the job is NOT ending).  Closing the ring plane and peer
        service here is what "rebuild ring topology + stripe
        connections" means: the successor's RingPlane re-resolves every
        peer through the new epoch's rendezvous scope from scratch."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        with self._mux_lock:
            mux, self._mux = self._mux, None
        if mux is not None:
            mux.close()
        self._close_ring_planes()
        if self._peer_service is not None:
            self._peer_service.shutdown()
            self._peer_service = None
        if self._coordinator is not None:
            self._coordinator.shutdown()
            self._coordinator = None
        if self._autotune is not None:
            self._autotune.close()
            self._autotune = None

    def shutdown(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        with self._abort_lock:
            aborted = self._abort_state is not None
        with self._mux_lock:
            mux = self._mux
        if self._size > 1 and mux is not None and not aborted:
            try:  # deregister from liveness (best-effort)
                mux.send(ShutdownMsg(self._rank), timeout=5.0)
            except Exception:  # noqa: BLE001 — coordinator may be gone
                pass
        self._merge_timelines()
        with self._mux_lock:
            mux, self._mux = self._mux, None
        if mux is not None:
            mux.close()
        self._close_ring_planes()
        if self._peer_service is not None:
            self._peer_service.shutdown()
            self._peer_service = None
        if self._coordinator is not None:
            self._coordinator.shutdown()
            self._coordinator = None
        if self._autotune is not None:
            self._autotune.close()
            self._autotune = None

    # -------------------------------------------------------------- timeline
    def _merge_timelines(self):
        from horovod_tpu.utils.timeline import publish_and_merge

        publish_and_merge(self._rank, self._size,
                          self._config.timeline_path, self._timeline,
                          scope=self._scope(TIMELINE_SCOPE))
