"""Peer-to-peer ring data plane for process-rank (tcp) mode.

Round 1's tcp mode shipped every payload through the rank-0 coordinator
(an O(N·bytes) star on one host).  The reference's no-dependency config
does better: Gloo runs ring allreduce between workers
(``gloo_operations.cc:30-100``).  This module is that ring, built on the
HMAC mux transport: every worker runs a :class:`PeerService` (a chunk
mailbox) and keeps ONE persistent connection to each neighbor it talks
to.  Large collectives negotiate metadata through the coordinator as
usual, then move bytes rank-to-rank:

- **allreduce**: ring reduce-scatter + ring allgather — each rank moves
  ~2·bytes·(P−1)/P regardless of P, no hot spot (the classic
  Baidu/Horovod ring the reference popularized).
- **broadcast**: chunked pipeline around the ring from the root — the
  root uploads each byte once instead of N−1 times.
- **allgather**: ring block rotation (N−1 forwarding steps).

Accumulation is float64/int64 like the coordinator star path.  The
two planes are rank-consistent but not bitwise-identical to each other
for floats: the ring reduces each chunk in ring-rotation order while
the star sums in ascending rank order, and float addition is not
associative — a tensor crossing HVD_TCP_RING_THRESHOLD can change in
the last ulp.
"""

import collections
import threading

import numpy as np

from horovod_tpu.common import faults
from horovod_tpu.common.handles import HvdAbortedError
from horovod_tpu.common.ops_enum import INT8_BLOCK
from horovod_tpu.run.service import network

# payloads at or above this ride the ring; below it the coordinator star
# round-trip is latency-optimal (one RTT, no rendezvous fan-out)
DEFAULT_RING_THRESHOLD = 1 << 20
# broadcast pipeline chunk
BCAST_CHUNK = 1 << 22


# ------------------------------------------------------- compressed codecs
# enc(float64 1-D chunk) -> wire bytes; dec(blob, n) -> float64 [n].
# int8 blobs are [ceil(n/256) fp32 scales][ceil(n/256)*256 int8 values]
# (~27% of the fp64-path's fp32-equivalent bytes); cast codecs are plain
# dtype reinterpretations.
def _enc_int8(chunk):
    # all math in float32 with in-place rint/clip: the encoder sits on
    # the ring's critical path and f64 temporaries double its memory
    # traffic (the quantization error bound doesn't need f64 — the
    # scale only has to be within an ulp of max|x|/127)
    n = chunk.size
    nb = -(-n // INT8_BLOCK)
    x = np.ascontiguousarray(chunk, dtype=np.float32)
    if nb * INT8_BLOCK != n:
        x = np.concatenate(
            [x, np.zeros(nb * INT8_BLOCK - n, np.float32)])
    blocks = x.reshape(nb, INT8_BLOCK)
    maxabs = np.maximum(blocks.max(axis=1), -blocks.min(axis=1))
    scale = np.where(maxabs > 0, maxabs / np.float32(127.0),
                     np.float32(1.0)).astype(np.float32)
    # divide like the jnp quantizer — a reciprocal multiply overflows to
    # inf for denormal scales and would send the block's zeros through
    # 0 * inf = NaN into an undefined NaN->int8 cast
    q = blocks / scale[:, None]
    np.rint(q, out=q)
    np.clip(q, -127, 127, out=q)
    return scale.tobytes() + q.astype(np.int8).tobytes()


def _dec_int8(blob, n):
    nb = -(-n // INT8_BLOCK)
    scale = np.frombuffer(blob[:nb * 4], np.float32)
    q = np.frombuffer(blob, np.int8, offset=nb * 4).reshape(
        nb, INT8_BLOCK).astype(np.float32)
    # float32 out: these ARE the wire values (int8 x fp32 scale); the
    # caller's float64 accumulator upcasts on +=
    q *= scale[:, None]
    return q.reshape(-1)[:n]


def _cast_codec(wire_dtype):
    dt = np.dtype(wire_dtype)

    def enc(chunk):
        return np.ascontiguousarray(chunk.astype(dt)).tobytes()

    def dec(blob, n):
        # float32 is exact for bf16/fp16 wire values; the caller's
        # float64 accumulator upcasts on +=
        return np.frombuffer(blob, dtype=dt)[:n].astype(np.float32)

    return enc, dec


def _codecs():
    # bfloat16 comes from ml_dtypes (a jax dependency) — resolved lazily
    # so importing this module never pulls it in on the no-accelerator
    # path until a compressed collective actually runs
    import ml_dtypes

    return {
        "int8": (_enc_int8, _dec_int8),
        "bf16": _cast_codec(ml_dtypes.bfloat16),
        "fp16": _cast_codec(np.float16),
    }


class ChunkMsg:
    __slots__ = ("tag", "src", "payload")

    def __init__(self, tag, src, payload):
        self.tag = tag
        self.src = src
        self.payload = payload


class PeerService(network.MuxService):
    """Per-worker chunk mailbox: peers push ``ChunkMsg`` frames; the
    local compute thread collects them by tag."""

    NAME = "horovod_tpu peer"

    # purged ring ids remembered so late-arriving chunks of aborted
    # rounds are dropped instead of leaking in the mailbox forever.
    # Bounded LRU: re-purging a hot id refreshes its slot instead of
    # evicting a different recent id, and total memory is O(KEEP)
    # however long the job runs.
    _PURGED_KEEP = 256

    def __init__(self, key):
        self._cv = threading.Condition()
        self._mailbox = {}   # (tag, src) -> payload
        self._purged = collections.OrderedDict()  # ring_id -> None (LRU)
        self._aborted = None  # (origin_rank, reason) once abort observed
        # set by the controller: called (origin, reason) when a PEER
        # pushes an abort here, so in-flight negotiation handles fail
        # too, not just blocked ring recvs
        self.abort_callback = None
        super().__init__(self.NAME, key)

    def _handle(self, req, client_address):
        if isinstance(req, ChunkMsg):
            with self._cv:
                if self._aborted is not None \
                        or req.tag[0] in self._purged:
                    return network.AckResponse()  # aborted round, drop
                self._mailbox[(req.tag, req.src)] = req.payload
                self._cv.notify_all()
            return network.AckResponse()
        if isinstance(req, network.AbortMsg):
            # direct peer-to-peer abort fan-out: delivery does not
            # depend on the coordinator (or its host process) surviving
            self.abort(req.origin_rank, req.reason)
            callback = self.abort_callback
            if callback is not None:
                callback(req.origin_rank, req.reason)
            return network.AckResponse()
        return super()._handle(req, client_address)

    def recv(self, tag, src, timeout=None):
        import time as _time

        deadline = (_time.monotonic() + timeout) if timeout else None
        with self._cv:
            while (tag, src) not in self._mailbox:
                if self._aborted is not None:
                    raise HvdAbortedError(*self._aborted)
                remaining = None
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no chunk {tag!r} from rank {src} within "
                            f"{timeout}s")
                self._cv.wait(timeout=remaining)
            return self._mailbox.pop((tag, src))

    def purge(self, ring_id):
        """Drop chunks of an aborted collective round (its tags lead with
        the coordinator-assigned ring id, so a retry — which gets a NEW
        id — can never consume stale data)."""
        with self._cv:
            self._purged[ring_id] = None
            self._purged.move_to_end(ring_id)
            while len(self._purged) > self._PURGED_KEEP:
                self._purged.popitem(last=False)
            for key in [k for k in self._mailbox if k[0][0] == ring_id]:
                del self._mailbox[key]

    def abort(self, origin_rank, reason):
        """Coordinated abort observed: fail every blocked ``recv`` with
        the typed error, drop all buffered chunks and refuse new ones —
        no mailbox state survives the abort (sticky; the job is over)."""
        with self._cv:
            if self._aborted is not None:
                return
            self._aborted = (origin_rank, reason)
            self._mailbox.clear()
            self._cv.notify_all()


class RingPlane:
    """This process's endpoint of the worker ring."""

    def __init__(self, rank, service, resolve_peer):
        """``resolve_peer(rank) -> MuxClient`` (lazy, cached)."""
        self.rank = rank
        self._service = service
        self._resolve = resolve_peer
        self._clients = {}
        self._lock = threading.Lock()

    def _peer(self, rank):
        with self._lock:
            client = self._clients.get(rank)
            if client is None:
                client = self._clients[rank] = self._resolve(rank)
            return client

    def cached_peer(self, rank):
        """The already-connected client for ``rank``, or None — the
        abort fan-out prefers live connections over re-resolving peers
        through the rendezvous mid-failure."""
        with self._lock:
            return self._clients.get(rank)

    def send(self, dst, tag, payload: bytes):
        # fire-and-forget: the mailbox is tag-keyed, so ordering doesn't
        # need acks, and ring steps stay bandwidth-bound (no ack RTT on
        # the critical path)
        if faults.check("send"):
            return  # injected drop: the chunk vanishes on the wire
        self._peer(dst).post(ChunkMsg(tag, self.rank, payload))

    def recv(self, tag, src, timeout=None) -> bytes:
        if faults.check("recv"):
            raise TimeoutError(
                f"no chunk {tag!r} from rank {src} (injected recv fault)")
        return self._service.recv(tag, src, timeout=timeout)

    def close(self):
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()

    # ------------------------------------------------------------- allreduce
    def allreduce(self, ring_id, arr, participants, *, op_average,
                  world_size, prescale=1.0, postscale=1.0, timeout=None,
                  compression="none"):
        """Ring allreduce over ``participants`` (sorted rank ids; must
        include ``self.rank``).  Joined ranks simply aren't in the ring —
        their zero stand-ins are additive identities.

        ``compression`` ("int8" / "bf16" / "fp16", floats only) moves
        the bulk bytes in the compressed wire format; accumulation stays
        float64 either way and integer dtypes always take the exact
        path."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        from horovod_tpu.common.ops_enum import is_float_dtype

        out_dtype = arr.dtype
        float_in = is_float_dtype(arr.dtype)
        acc_dtype = np.float64 if float_in else np.int64
        flat = arr.reshape(-1).astype(acc_dtype)
        if prescale != 1.0:
            flat = flat * prescale
        codec = (_codecs().get(compression)
                 if float_in and compression not in (None, "none") else None)
        if p == 1:
            total = flat
        elif codec is not None:
            total = self._allreduce_compressed(ring_id, flat, participants,
                                               idx, codec, timeout)
        else:
            right = participants[(idx + 1) % p]
            left = participants[(idx - 1) % p]
            chunks = np.array_split(flat, p)
            # reduce-scatter: after p-1 steps this rank owns the fully
            # reduced chunk (idx+1) % p
            for s in range(p - 1):
                send_i = (idx - s) % p
                recv_i = (idx - 1 - s) % p
                self.send(right, ((ring_id, "rs", s)),
                          np.ascontiguousarray(chunks[send_i]).tobytes())
                data = self.recv(((ring_id, "rs", s)), left, timeout=timeout)
                chunks[recv_i] = chunks[recv_i] + np.frombuffer(
                    data, dtype=acc_dtype)
            # allgather: rotate owned chunks p-1 times
            for s in range(p - 1):
                send_i = (idx + 1 - s) % p
                recv_i = (idx - s) % p
                self.send(right, ((ring_id, "ag", s)),
                          np.ascontiguousarray(chunks[send_i]).tobytes())
                data = self.recv(((ring_id, "ag", s)), left, timeout=timeout)
                chunks[recv_i] = np.frombuffer(data, dtype=acc_dtype)
            total = np.concatenate(chunks)
        if op_average:
            total = total / world_size
        if postscale != 1.0:
            total = total * postscale
        return total.astype(out_dtype).reshape(arr.shape)

    def _allreduce_compressed(self, ring_id, flat, participants, idx,
                              codec, timeout):
        """Compressed bulk exchange (EQuARX-style block scaling mapped
        onto the p2p transport).  Reduce-scatter leg: each rank encodes
        its contribution to every destination chunk ONCE at the source
        and ships it straight to the chunk's owner — same (p-1)/p bytes
        per rank as the classic ring's reduce-scatter, but one
        quantization per contribution instead of a requantize at every
        hop.  The owner accumulates all contributions in float64,
        encodes its reduced chunk once, and the allgather leg rotates
        the compressed blobs around the ring verbatim.  Every rank
        decodes the SAME blobs (the owner included), so the result stays
        rank-consistent like the exact ring."""
        enc, dec = codec
        p = len(participants)
        chunks = np.array_split(flat, p)
        sizes = [c.size for c in chunks]
        for d in range(p):
            if d != idx:
                self.send(participants[d], ((ring_id, "qrs", d)),
                          enc(np.ascontiguousarray(chunks[d])))
        acc = chunks[idx].astype(np.float64, copy=True)
        for src_i, src in enumerate(participants):
            if src_i == idx:
                continue
            blob = self.recv(((ring_id, "qrs", idx)), src, timeout=timeout)
            acc += dec(blob, sizes[idx])
        # allgather: rotate the compressed reduced chunks p-1 times
        right = participants[(idx + 1) % p]
        left = participants[(idx - 1) % p]
        blobs = {idx: enc(np.ascontiguousarray(acc))}
        carry = idx
        for s in range(p - 1):
            self.send(right, ((ring_id, "qag", s)), blobs[carry])
            recv_owner = (idx - 1 - s) % p
            blobs[recv_owner] = self.recv(((ring_id, "qag", s)), left,
                                          timeout=timeout)
            carry = recv_owner
        return np.concatenate([dec(blobs[i], sizes[i]) for i in range(p)])

    # --------------------------------------------------------------- adasum
    def adasum(self, ring_id, arr, participants, *, timeout=None):
        """Distributed Adasum vector-halving distance-doubling
        (reference: ``Adasum<Communicator_type>::FusedAllreduce``,
        ``adasum/adasum.h:194-330``) over the p2p plane — no rank-0
        payload hotspot: per-rank traffic is ~2|x| halves plus 24-byte
        scalar rounds.

        At level ``k`` this rank exchanges half of its current piece
        with ``participants[idx ^ 2^k]``; the dot/norm scalars of the
        two logical vectors (distributed over the ``2^(k+1)``-rank
        group) are star-reduced through the group's lowest rank (the
        reference's per-level ``reduction_comms``); coefficients
        combine the halves.  After ``log2(p)`` levels each rank holds
        ``1/p`` of the result at bit-reversed chunk order; a block
        gather + static permutation rebuilds the full vector — same
        algebra as :func:`horovod_tpu.ops.adasum.adasum_vhdd`, which the
        numpy oracle validates.

        ``participants`` must be ALL world ranks (the coordinator
        falls back to the payload path when ranks have joined) and a
        power of two.
        """
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        if p & (p - 1):
            raise ValueError(
                f"ring Adasum requires power-of-two ranks, got {p}")
        out_dtype = arr.dtype
        shape = arr.shape
        size = arr.size
        if p == 1:
            return arr
        padded = -(-size // p) * p
        piece = np.zeros(padded, np.float64)
        piece[:size] = arr.reshape(-1).astype(np.float64)

        dist = 1
        level = 0
        while dist < p:
            half = piece.size // 2
            low, high = piece[:half], piece[half:]
            bit = (idx // dist) % 2
            send_half, mine = (high, low) if bit == 0 else (low, high)
            peer = participants[idx ^ dist]
            self.send(peer, ((ring_id, "ad", level)),
                      np.ascontiguousarray(send_half).tobytes())
            recv = np.frombuffer(
                self.recv(((ring_id, "ad", level)), peer, timeout=timeout),
                dtype=np.float64)
            # a = the lower sub-group's vector piece, b = the upper's —
            # fixed roles so every group member reduces the same scalars
            a, b = (mine, recv) if bit == 0 else (recv, mine)
            partial = np.array([a @ b, a @ a, b @ b])

            group = [r for r in range(p)
                     if r // (2 * dist) == idx // (2 * dist)]
            leader = group[0]
            if idx == leader:
                total = partial.copy()
                for member in group[1:]:
                    total += np.frombuffer(self.recv(
                        ((ring_id, "adp", level)),
                        participants[member], timeout=timeout), np.float64)
                blob = np.ascontiguousarray(total).tobytes()
                for member in group[1:]:
                    self.send(participants[member],
                              ((ring_id, "ads", level)), blob)
            else:
                self.send(participants[leader], ((ring_id, "adp", level)),
                          np.ascontiguousarray(partial).tobytes())
                total = np.frombuffer(self.recv(
                    ((ring_id, "ads", level)), participants[leader],
                    timeout=timeout), np.float64)
            dot, na, nb = total
            a_coeff = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
            b_coeff = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
            piece = a_coeff * a + b_coeff * b
            dist *= 2
            level += 1

        # block gather (ring rotation), then undo the bit-reversed chunk
        # order the halving walk leaves behind (adasum.py:150-153)
        blocks = {idx: np.ascontiguousarray(piece).tobytes()}
        right = participants[(idx + 1) % p]
        left = participants[(idx - 1) % p]
        carry = idx
        for s in range(p - 1):
            self.send(right, ((ring_id, "adg", s)), blocks[carry])
            recv_owner = (idx - 1 - s) % p
            blocks[recv_owner] = self.recv(((ring_id, "adg", s)), left,
                                           timeout=timeout)
            carry = recv_owner
        levels = p.bit_length() - 1
        order = [int(format(i, f"0{levels}b")[::-1], 2) for i in range(p)]
        full = np.concatenate([
            np.frombuffer(blocks[order[i]], np.float64)
            for i in range(p)])
        return full[:size].reshape(shape).astype(out_dtype)

    # ------------------------------------------------------------- broadcast
    def broadcast(self, ring_id, arr_or_none, participants, root, *,
                  shape, dtype, timeout=None):
        """Chunked pipeline around the ring rooted at ``root``: every rank
        receives each chunk once from its left neighbor and forwards it
        once to its right — the root uploads the tensor exactly once."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        root_idx = participants.index(root)
        right = participants[(idx + 1) % p]
        nbytes = int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
        n_chunks = max(1, -(-nbytes // BCAST_CHUNK))

        if self.rank == root:
            data = np.ascontiguousarray(arr_or_none).tobytes()
            if p > 1:
                for c in range(n_chunks):
                    self.send(right, ((ring_id, "bc", c)),
                              data[c * BCAST_CHUNK:(c + 1) * BCAST_CHUNK])
        else:
            left = participants[(idx - 1) % p]
            pieces = []
            last = (idx + 1) % p == root_idx  # my right neighbor is root
            for c in range(n_chunks):
                piece = self.recv(((ring_id, "bc", c)), left, timeout=timeout)
                if not last:
                    self.send(right, ((ring_id, "bc", c)), piece)
                pieces.append(piece)
            data = b"".join(pieces)
        return np.frombuffer(data, dtype=np.dtype(dtype)).reshape(shape)

    # ------------------------------------------------------------- allgather
    def allgather(self, ring_id, arr, participants, *, timeout=None):
        """Ring block rotation: each step forwards the block received the
        previous step; after p-1 steps every rank holds every block.
        Returns the blocks concatenated in rank order (variable first
        dims supported — blocks travel as raw bytes + shape header is
        negotiated out-of-band by the coordinator)."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        blocks = {self.rank: np.ascontiguousarray(arr).tobytes()}
        if p > 1:
            right = participants[(idx + 1) % p]
            left = participants[(idx - 1) % p]
            carry_owner = self.rank
            for s in range(p - 1):
                self.send(right, ((ring_id, "ag", s)), blocks[carry_owner])
                recv_owner = participants[(idx - 1 - s) % p]
                blocks[recv_owner] = self.recv(((ring_id, "ag", s)), left,
                                               timeout=timeout)
                carry_owner = recv_owner
        return [blocks[r] for r in participants]
