"""Peer-to-peer ring data plane for process-rank (tcp) mode.

Round 1's tcp mode shipped every payload through the rank-0 coordinator
(an O(N·bytes) star on one host).  The reference's no-dependency config
does better: Gloo runs ring allreduce between workers
(``gloo_operations.cc:30-100``).  This module is that ring, built on the
HMAC mux transport: every worker runs a :class:`PeerService` (a chunk
mailbox) and keeps persistent connections to each neighbor it talks
to.  Large collectives negotiate metadata through the coordinator as
usual, then move bytes rank-to-rank:

- **allreduce**: ring reduce-scatter + ring allgather — each rank moves
  ~2·bytes·(P−1)/P regardless of P, no hot spot (the classic
  Baidu/Horovod ring the reference popularized).
- **broadcast**: segmented pipeline around the ring from the root — the
  root uploads each byte once instead of N−1 times.
- **allgather**: ring block rotation (N−1 forwarding steps).

Transfer engine (round 3):

- **Native wire dtypes** — chunk bytes ship in the tensor's input dtype
  (fp32/bf16/int32/...); float64/int64 accumulation is strictly local
  to each rank.  A fp32 allreduce moves half the bytes the
  f64-on-the-wire seed moved (bf16: a quarter).  Rank-consistency is
  preserved the same way the compressed path always did it: the owner
  of each reduced chunk encodes it ONCE and the allgather leg rotates
  the encoded blob verbatim, so every rank decodes identical bytes.
  Integer dtypes stay exact: partial sums wrap modulo 2^width on the
  wire, and modular addition is associative, so the final
  cast-to-input-dtype result equals the wide-accumulator sum.
- **Segment pipelining** — each ring step's chunk is split into
  ``HVD_TPU_RING_SEGMENT_BYTES`` segments driven through a dedicated
  sender thread, so the send of segment k+1 overlaps the recv +
  accumulate of segment k (double-buffered; both the exact and the
  compressed legs).
- **Socket striping** — bulk segments ride a pool of
  ``HVD_TPU_RING_STRIPES`` dedicated raw-frame connections per peer
  (:class:`network.StripeClient`), separate from the control
  ``MuxClient``: heartbeats, negotiation and abort fan-out never queue
  behind a multi-MB chunk write, and high-BDP links get multi-stream
  throughput.  Abort/purge wake and drain every stripe — blocked recvs
  all wait on the one mailbox condition the abort signals.

The two planes are rank-consistent but not bitwise-identical to each
other for floats: the ring reduces each chunk in ring-rotation order
at wire precision while the star sums in ascending rank order in
float64 — a tensor crossing HVD_TCP_RING_THRESHOLD can change in the
last ulps.
"""

import collections
import queue
import threading
import time

import numpy as np

from horovod_tpu.common import faults
from horovod_tpu.common import rtt as rtt_mod
from horovod_tpu.common.handles import make_abort_error
from horovod_tpu.common.ops_enum import (INT8_BLOCK, is_float_dtype,
                                         reduce_scatter_split_sizes)
from horovod_tpu.run.service import network
from horovod_tpu.tools.race import hooks as race_hooks
from horovod_tpu.utils import env as env_util

# payloads at or above this ride the ring; below it the coordinator star
# round-trip is latency-optimal (one RTT, no rendezvous fan-out)
DEFAULT_RING_THRESHOLD = 1 << 20
# the collective schedules the coordinator can stamp on a ring_go
# (docs/tuning.md "Choosing a collective schedule"); order is the wire
# encoding the C++ ParameterManager autotune walk uses (index = int id)
SCHEDULES = ("auto", "flat_ring", "hierarchical", "rhd", "star")
# the latency-bound regime: among RING-BOUND tensors (past
# HVD_TCP_RING_THRESHOLD, or schedule-forced onto the ring), the
# coordinator resolves auto to recursive halving/doubling (O(log N)
# serialized rounds vs the flat ring's O(N)) inside [MIN, MAX].
# Below MIN the coordinator star's single fused round-trip wins —
# log2(P) serialized peer hops cost more than one coordinator
# exchange for control-plane-sized tensors — and forcing tiny tensors
# onto the ring would also bypass the star's fusion/caching machinery,
# so the band never widens ring ENTRY: sub-threshold traffic keeps
# the star unless a ring schedule is forced
DEFAULT_RHD_MAX_BYTES = 1 << 18
DEFAULT_RHD_MIN_BYTES = 1 << 13
# broadcast pipeline chunk when segmenting is disabled
BCAST_CHUNK = 1 << 22
# pipeline segment size / bulk connections per peer (tunable:
# HVD_TPU_RING_SEGMENT_BYTES / HVD_TPU_RING_STRIPES, docs/tuning.md)
DEFAULT_SEGMENT_BYTES = env_util.DEFAULT_RING_SEGMENT_BYTES
DEFAULT_STRIPES = env_util.DEFAULT_RING_STRIPES


# ------------------------------------------------------- compressed codecs
# enc(float64 1-D chunk) -> wire bytes; dec(blob, n) -> float32-ish [n];
# nbytes(n) -> deterministic blob size (sender and receiver derive the
# segment count from it independently, so no size header travels).
# int8 blobs are [ceil(n/256) fp32 scales][ceil(n/256)*256 int8 values]
# (~27% of fp32 bytes); cast codecs are plain dtype reinterpretations.
def _enc_int8(chunk):
    # all math in float32 with in-place rint/clip: the encoder sits on
    # the ring's critical path and f64 temporaries double its memory
    # traffic (the quantization error bound doesn't need f64 — the
    # scale only has to be within an ulp of max|x|/127)
    n = chunk.size
    nb = -(-n // INT8_BLOCK)
    x = np.ascontiguousarray(chunk, dtype=np.float32)
    if nb * INT8_BLOCK != n:
        x = np.concatenate(
            [x, np.zeros(nb * INT8_BLOCK - n, np.float32)])
    blocks = x.reshape(nb, INT8_BLOCK)
    maxabs = np.maximum(blocks.max(axis=1), -blocks.min(axis=1))
    scale = np.where(maxabs > 0, maxabs / np.float32(127.0),
                     np.float32(1.0)).astype(np.float32)
    # divide like the jnp quantizer — a reciprocal multiply overflows to
    # inf for denormal scales and would send the block's zeros through
    # 0 * inf = NaN into an undefined NaN->int8 cast
    q = blocks / scale[:, None]
    np.rint(q, out=q)
    np.clip(q, -127, 127, out=q)
    return scale.tobytes() + q.astype(np.int8).tobytes()


def _dec_int8(blob, n):
    nb = -(-n // INT8_BLOCK)
    scale = np.frombuffer(blob, np.float32, count=nb)
    q = np.frombuffer(blob, np.int8, offset=nb * 4).reshape(
        nb, INT8_BLOCK).astype(np.float32)
    # float32 out: these ARE the wire values (int8 x fp32 scale); the
    # caller's float64 accumulator upcasts on +=
    q *= scale[:, None]
    return q.reshape(-1)[:n]


def _int8_nbytes(n):
    nb = -(-n // INT8_BLOCK)
    return nb * 4 + nb * INT8_BLOCK


def _cast_codec(wire_dtype):
    dt = np.dtype(wire_dtype)

    def enc(chunk):
        return np.ascontiguousarray(chunk.astype(dt)).tobytes()

    def dec(blob, n):
        # float32 is exact for bf16/fp16 wire values; the caller's
        # float64 accumulator upcasts on +=
        return np.frombuffer(blob, dtype=dt, count=n).astype(np.float32)

    def nbytes(n):
        return n * dt.itemsize

    return enc, dec, nbytes


def _codecs():
    # bfloat16 comes from ml_dtypes (a jax dependency) — resolved lazily
    # so importing this module never pulls it in on the no-accelerator
    # path until a compressed collective actually runs
    import ml_dtypes

    return {
        "int8": (_enc_int8, _dec_int8, _int8_nbytes),
        "bf16": _cast_codec(ml_dtypes.bfloat16),
        "fp16": _cast_codec(np.float16),
    }


def _as_bytes_view(arr):
    """Zero-copy raw-bytes view of a contiguous array — via a uint8
    reinterpretation, because numpy refuses direct buffer export for
    ml_dtypes extension dtypes (bfloat16)."""
    return arr.view(np.uint8).data


def _wire_spec(dtype, prescale, widen):
    """(wire dtype, accumulator dtype) for the exact ring path.

    Floats wire natively and accumulate in f64.  Integers wire natively
    and accumulate in int64 — modular wrap on the wire is exact for the
    final input-dtype result, but ONLY for a pure sum: ``widen`` (an
    average or postscale, which read the true wide total before the
    cast back) keeps int64 on the wire like the seed did, and a
    prescale promotes the math to float entirely, so f64 wires (exact
    for every integer the cast back can represent)."""
    dt = np.dtype(dtype)
    if is_float_dtype(dt):
        return dt, np.float64
    if prescale != 1.0:
        return np.dtype(np.float64), np.float64
    if widen:
        return np.dtype(np.int64), np.int64
    return dt, np.int64


class ChunkMsg:
    # ``epoch`` is the membership epoch the sender's plane belongs to
    # (docs/elastic.md): the header rides pickled on BOTH frame kinds
    # (control-connection chunks and raw bulk stripes share the pickled
    # header in write_bulk_message), so a straggler chunk from a
    # pre-reconfiguration ring is droppable at the framing layer.
    # __weakref__ keeps instances weakref-able despite __slots__: the
    # race shim's address-recycling check needs a liveness weakref, and
    # chunk headers churn through recycled addresses constantly.
    # (pickle skips the __weakref__ slot, so the wire format is
    # unchanged.)
    __slots__ = ("tag", "src", "payload", "epoch", "__weakref__")

    def __init__(self, tag, src, payload, epoch=0):
        self.tag = tag
        self.src = src
        self.payload = payload
        self.epoch = epoch


class RingSendError(ConnectionError):
    """A bulk segment write to a SPECIFIC peer failed.  Carrying the
    peer rank lets the abort that follows name the rank the transport
    proved unreachable — not the rank that happened to notice first —
    so culprit attribution stays deterministic under machine-load skew
    (the mid-ring crash scenario races liveness detection against the
    survivor's own failed sends; both now name the same origin).

    Only the SEND side carries this evidence: a failed connection to a
    peer proves THAT peer is gone, while a recv timeout only proves the
    ring stalled somewhere upstream — in a 3+-rank ring the silent
    predecessor is usually an innocent rank blocked behind the real
    casualty, so recv timeouts keep naming the noticing rank and leave
    precise attribution to the liveness monitor."""

    def __init__(self, peer_rank, cause):
        super().__init__(
            f"ring bulk send to rank {peer_rank} failed: {cause}")
        self.peer_rank = peer_rank


class _PlaneClosedError(ConnectionError):
    """This plane's own close() refused the operation — a local
    teardown artifact, never evidence about a peer (the sender loop
    must not convert it into a RingSendError that blames one)."""


class PeerService(network.MuxService):
    """Per-worker chunk mailbox: peers push ``ChunkMsg`` frames (pickled
    small ones on the control connection, raw bulk frames on the
    stripes); the local compute thread collects them by tag."""

    NAME = "horovod_tpu peer"

    # purged ring ids remembered so late-arriving chunks of aborted
    # rounds are dropped instead of leaking in the mailbox forever.
    # Bounded LRU: re-purging a hot id refreshes its slot instead of
    # evicting a different recent id, and total memory is O(KEEP)
    # however long the job runs.
    _PURGED_KEEP = 256

    def __init__(self, key, epoch=0):
        # membership epoch this plane accepts; stale-epoch frames are
        # dropped in _handle so a straggler chunk from a torn-down ring
        # can never corrupt a post-reconfiguration collective
        self._epoch = epoch
        self.stale_epoch_drops = 0   # guarded by self._cv
        self._cv = threading.Condition()
        self._mailbox = {}   # (tag, src) -> payload; guarded by self._cv
        # ring-id index over the mailbox: purge and the late-chunk drop
        # check are O(chunks of that ring), not O(total mailbox)
        self._by_ring = {}   # ring_id -> mailbox keys; guarded by self._cv
        # ring_id -> None (LRU); guarded by self._cv
        self._purged = collections.OrderedDict()
        # (origin_rank, reason) once observed; guarded by self._cv
        self._aborted = None
        # set by the controller: called (origin, reason) when a PEER
        # pushes an abort here, so in-flight negotiation handles fail
        # too, not just blocked ring recvs
        self.abort_callback = None
        super().__init__(self.NAME, key)

    def session_epoch(self):
        """Session hellos must carry the plane's membership epoch: a
        client healing across a reconfiguration is fenced (refused
        welcome) and escalates instead of replaying a torn-down ring's
        frames into the new epoch."""
        return self._epoch

    def _handle(self, req, client_address):
        if isinstance(req, ChunkMsg):
            with self._cv:
                if getattr(req, "epoch", 0) != self._epoch:
                    # stale-epoch frame (or one from the future — a
                    # peer that reconfigured ahead of us): refuse it at
                    # the framing layer, before it can touch the mailbox
                    self.stale_epoch_drops += 1
                    return network.AckResponse()
                if self._aborted is not None \
                        or req.tag[0] in self._purged:
                    return network.AckResponse()  # aborted round, drop
                key = (req.tag, req.src)
                self._mailbox[key] = req.payload
                self._by_ring.setdefault(req.tag[0], set()).add(key)
                if race_hooks.active:
                    # deliver→recv happens-before edge: even a recv
                    # that never waits (chunk already buffered) is
                    # ordered after this insert (docs/race_detection.md)
                    race_hooks.publish(("mailbox", id(self)) + key)
                self._cv.notify_all()
            return network.AckResponse()
        if isinstance(req, network.AbortMsg):
            # direct peer-to-peer abort fan-out: delivery does not
            # depend on the coordinator (or its host process) surviving
            self.abort(req.origin_rank, req.reason)
            callback = self.abort_callback
            if callback is not None:
                callback(req.origin_rank, req.reason)
            return network.AckResponse()
        return super()._handle(req, client_address)

    def recv(self, tag, src, timeout=None, error_check=None):
        """``error_check`` (optional, called with the condition held on
        every wakeup) raises to fail this recv on a local error — the
        ring plane uses it so a blocked recv dies as soon as its own
        sender thread reports a broken stripe, instead of waiting out
        the timeout for segments the peer will never get to send."""
        import time as _time

        deadline = (_time.monotonic() + timeout) if timeout else None
        key = (tag, src)
        with self._cv:
            while key not in self._mailbox:
                if self._aborted is not None:
                    raise make_abort_error(*self._aborted)
                if error_check is not None:
                    error_check()
                remaining = None
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no chunk {tag!r} from rank {src} within "
                            f"{timeout}s")
                self._cv.wait(timeout=remaining)
            ring_keys = self._by_ring.get(tag[0])
            if ring_keys is not None:
                ring_keys.discard(key)
                if not ring_keys:
                    del self._by_ring[tag[0]]
            if race_hooks.active:
                race_hooks.observe(("mailbox", id(self)) + key)
            return self._mailbox.pop(key)

    def purge(self, ring_id):
        """Drop chunks of an aborted collective round (its tags lead with
        the coordinator-assigned ring id, so a retry — which gets a NEW
        id — can never consume stale data).  O(chunks of this ring) via
        the ring-id index, not a scan of every mailbox key."""
        with self._cv:
            self._purged[ring_id] = None
            self._purged.move_to_end(ring_id)
            while len(self._purged) > self._PURGED_KEEP:
                self._purged.popitem(last=False)
            for key in self._by_ring.pop(ring_id, ()):
                self._mailbox.pop(key, None)

    def purge_group(self, group):
        """Group-aware purge (docs/groups.md): drop every buffered round
        of process group ``group``.  Grouped ring ids live in a per-group
        namespace ("g<gid>:<seq>"), so the group's rounds — and only the
        group's rounds — are identifiable here without consulting any
        registry.  Used when a group's rounds must all die together
        (e.g. a reform at a new membership epoch) while other groups'
        in-flight rounds keep their mailbox state."""
        prefix = f"g{group}:"
        with self._cv:
            for ring_id in [rid for rid in self._by_ring
                            if isinstance(rid, str)
                            and rid.startswith(prefix)]:
                self._purged[ring_id] = None
                self._purged.move_to_end(ring_id)
                for key in self._by_ring.pop(ring_id, ()):
                    self._mailbox.pop(key, None)
            while len(self._purged) > self._PURGED_KEEP:
                self._purged.popitem(last=False)

    def abort(self, origin_rank, reason):
        """Coordinated abort observed: fail every blocked ``recv`` with
        the typed error, drop all buffered chunks and refuse new ones —
        no mailbox state survives the abort (sticky; the job is over).
        Recvs blocked on stripe-delivered segments wake too: every recv
        waits on this one condition regardless of which connection the
        bytes would have arrived on."""
        with self._cv:
            if self._aborted is not None:
                return
            self._aborted = (origin_rank, reason)
            self._mailbox.clear()
            self._by_ring.clear()
            self._cv.notify_all()


class RingPlane:
    """This process's endpoint of the worker ring."""

    def __init__(self, rank, service, resolve_peer, resolve_bulk=None, *,
                 segment_bytes=None, stripes=None, epoch=0):
        """``resolve_peer(rank) -> MuxClient`` (control; lazy, cached).
        ``resolve_bulk(rank) -> StripeClient`` builds one bulk-data
        stripe (called up to ``stripes`` times per peer; None routes
        bulk frames through the control client's bulk companion —
        still a dedicated socket, just a single one)."""
        self.rank = rank
        self.epoch = epoch        # stamped on every outgoing ChunkMsg
        self._service = service
        self._resolve = resolve_peer
        self._resolve_bulk = resolve_bulk
        self._clients = {}        # rank -> MuxClient; guarded by self._lock
        # rank -> [StripeClient | None]; guarded by self._lock
        self._stripe_pools = {}
        self._lock = threading.Lock()
        self.segment_bytes = (env_util.get_int(
            env_util.HVD_TPU_RING_SEGMENT_BYTES, DEFAULT_SEGMENT_BYTES)
            if segment_bytes is None else int(segment_bytes))
        self.stripes = (env_util.get_int(
            env_util.HVD_TPU_RING_STRIPES, DEFAULT_STRIPES)
            if stripes is None else int(stripes))
        self._sendq = queue.Queue()
        self._sender = None       # sender thread; guarded by self._lock
        # latest async send failure (sticky, written by the sender
        # thread, read by the compute thread); guarded by self._pending_cv
        self._send_error = None
        # peer the failed write was addressed to (None: not
        # peer-specific, e.g. close()); guarded by self._pending_cv
        self._send_error_peer = None
        # enqueued-but-unwritten segments; guarded by self._pending_cv
        self._pending_sends = 0
        self._pending_cv = threading.Condition()
        self._closed = False      # guarded by self._lock

    # ------------------------------------------------------------ transport
    def _peer(self, rank):
        with self._lock:
            if self._closed:
                # the sender thread may still be draining queued
                # segments when close() empties the pools — refusing
                # here stops it from repopulating them with fresh
                # connections nobody would ever close
                raise _PlaneClosedError("ring plane closed")
            client = self._clients.get(rank)
            if client is None:
                client = self._clients[rank] = self._resolve(rank)
            return client

    def cached_peer(self, rank):
        """The already-connected client for ``rank``, or None — the
        abort fan-out prefers live connections over re-resolving peers
        through the rendezvous mid-failure."""
        with self._lock:
            return self._clients.get(rank)

    def bytes_sent(self):
        """Wire bytes this plane has written (control posts + bulk
        stripes, framing included) — the byte-accounting surface the
        wire-efficiency tests measure."""
        with self._lock:
            total = sum(c.bytes_sent for c in self._clients.values())
            total += sum(s.bytes_sent for pool in
                         self._stripe_pools.values()
                         for s in pool if s is not None)
        return total

    def _stripe(self, dst, index):
        with self._lock:
            if self._closed:
                raise _PlaneClosedError("ring plane closed")
            n = max(1, int(self.stripes))
            pool = self._stripe_pools.setdefault(dst, [])
            i = index % n
            while len(pool) <= i:
                pool.append(self._resolve_bulk(dst)
                            if self._resolve_bulk is not None else None)
            return pool[i]

    def send(self, dst, tag, payload):
        # fire-and-forget: the mailbox is tag-keyed, so ordering doesn't
        # need acks, and ring steps stay bandwidth-bound (no ack RTT on
        # the critical path).  This is the seed-era unsegmented path —
        # kept for the reference (seed-parity) collectives and small
        # control-sized chunks.
        if faults.check("send"):
            return  # injected drop: the chunk vanishes on the wire
        self._peer(dst).post(
            ChunkMsg(tag, self.rank, payload, epoch=self.epoch))

    def recv(self, tag, src, timeout=None) -> bytes:
        if faults.check("recv"):
            raise TimeoutError(
                f"no chunk {tag!r} from rank {src} (injected recv fault)")
        return self._service.recv(tag, src, timeout=timeout)

    # --------------------------------------------------- segment pipeline
    @staticmethod
    def _segment_plan(nbytes, seg_bytes, align):
        """(segment size, segment count) — derived identically on the
        send and recv side from the chunk's wire size, the segment knob
        and the wire itemsize (every segment but the last is a multiple
        of ``align`` so per-segment decode never splits an element)."""
        nbytes = int(nbytes)
        if seg_bytes <= 0 or nbytes <= seg_bytes:
            return max(nbytes, 1), 1
        size = max(align, (int(seg_bytes) // align) * align)
        return size, -(-nbytes // size)

    def _sender_loop(self):
        while True:
            # wakeable: close() enqueues the None sentinel; the abort
            # path never needs to wake this thread (it only ever blocks
            # when there is nothing left to write)
            item = self._sendq.get()
            if item is None:
                return
            dst, stripe_i, msg, payload = item
            try:
                t0 = time.monotonic()
                stripe = self._stripe(dst, stripe_i)
                if stripe is not None:
                    stripe.post_bulk(msg, payload)
                else:
                    self._peer(dst).post_bulk(msg, payload)
                # per-peer write latency feeds the adaptive-deadline
                # EWMA: a bulk write blocking on socket backpressure (or
                # an injected delay/throttle) is exactly the slow-link
                # evidence the next heartbeat should carry upstream
                rtt_mod.tracker().sample(("peer", dst),
                                         time.monotonic() - t0)
            except Exception as exc:  # noqa: BLE001 — surface on the
                # compute thread: its next send/recv of any round fails
                # fast instead of waiting out the recv timeout
                with self._pending_cv:
                    self._send_error = exc
                    # peer evidence ONLY for genuine transport failures
                    # addressed at dst: a local error (framing bug,
                    # MemoryError, this plane's own close()) must not
                    # make the abort origin blame a healthy rank
                    if isinstance(exc, (OSError, TimeoutError)) \
                            and not isinstance(exc, _PlaneClosedError):
                        self._send_error_peer = dst
                # a recv already blocked on the mailbox must wake NOW:
                # its error_check re-raises this under the condition
                # (never nested with _pending_cv — no ordering edge)
                with self._service._cv:
                    self._service._cv.notify_all()
            finally:
                with self._pending_cv:
                    self._pending_sends -= 1
                    self._pending_cv.notify_all()

    def _raise_if_send_failed(self):
        with self._pending_cv:
            self._raise_if_send_failed_locked()

    def _raise_if_send_failed_locked(self):  # holds: self._pending_cv
        if self._send_error is not None:
            if self._send_error_peer is not None:
                raise RingSendError(self._send_error_peer,
                                    self._send_error)
            raise ConnectionError(
                f"ring bulk send failed: {self._send_error}")

    def _enqueue_segment(self, dst, stripe_i, tag, payload):
        # spawn-check and pending-count both under _lock: close() sets
        # _closed under the same lock, so a segment can never be
        # counted after close() decided nobody will ever drain it —
        # that would strand a timeout-less _flush_sends forever
        with self._lock:
            if self._closed:
                raise ConnectionError("ring plane closed")
            if self._sender is None:
                self._sender = threading.Thread(
                    target=self._sender_loop, daemon=True,
                    name="hvd-ring-sender")
                self._sender.start()
            with self._pending_cv:
                self._pending_sends += 1
        self._sendq.put(
            (dst, stripe_i,
             ChunkMsg(tag, self.rank, None, epoch=self.epoch), payload))

    def _flush_sends(self, timeout=None):
        """Block until every enqueued segment has been WRITTEN to its
        socket.  Every collective ends with this: fire-and-forget must
        not outlive the collective call — a rank whose process exits
        right after a broadcast/allreduce returns would otherwise race
        its own sender thread and strand peers waiting on segments that
        were never written."""
        import time as _time

        deadline = (_time.monotonic() + timeout) if timeout else None
        with self._pending_cv:
            while self._pending_sends > 0:
                self._raise_if_send_failed_locked()
                remaining = None
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"{self._pending_sends} ring segments still "
                            f"unsent after {timeout}s")
                # wakeable: every enqueued segment decrements
                # _pending_sends under this condition, a sender failure
                # notifies it, and close() fails any segments the
                # sender exited without writing — timeout-less callers
                # always wake
                self._pending_cv.wait(timeout=remaining)
            self._raise_if_send_failed_locked()

    def send_chunk(self, dst, base_tag, payload, seg_bytes=None,
                   align=1):
        """Split ``payload`` into pipeline segments, round-robined over
        the stripe pool via the dedicated sender thread — returns
        immediately so the caller's recv+accumulate of the incoming
        chunk overlaps the outgoing writes."""
        if faults.check("send"):
            return  # injected drop: the whole chunk vanishes
        self._raise_if_send_failed()
        seg = self.segment_bytes if seg_bytes is None else seg_bytes
        mv = memoryview(payload).cast("B")
        size, n_seg = self._segment_plan(mv.nbytes, seg, align)
        for k in range(n_seg):
            self._enqueue_segment(dst, k, base_tag + (k,),
                                  mv[k * size:(k + 1) * size])

    def recv_chunk(self, base_tag, src, nbytes, timeout=None,
                   consume=None, seg_bytes=None, align=1):
        """Receive the pipeline segments of one chunk.  ``nbytes`` is
        the chunk's deterministic wire size (both sides derive the
        segment count from it — no size header travels).  With
        ``consume(offset, segment)`` each segment is handed over as it
        arrives (overlapping the peer's remaining sends) and None is
        returned; otherwise the reassembled bytes are returned."""
        if faults.check("recv"):
            raise TimeoutError(
                f"no chunk {base_tag!r} from rank {src} "
                f"(injected recv fault)")
        seg = self.segment_bytes if seg_bytes is None else seg_bytes
        _, n_seg = self._segment_plan(nbytes, seg, align)
        parts = [] if consume is None else None
        offset = 0
        for k in range(n_seg):
            segment = self._service.recv(
                base_tag + (k,), src, timeout=timeout,
                error_check=self._raise_if_send_failed)
            if consume is None:
                parts.append(segment)
            else:
                consume(offset, segment)
            offset += len(segment)
        if consume is None:
            return parts[0] if len(parts) == 1 else b"".join(parts)
        return None

    def close(self):
        with self._lock:
            self._closed = True
            clients = list(self._clients.values())
            self._clients.clear()
            stripes = [s for pool in self._stripe_pools.values()
                       for s in pool if s is not None]
            self._stripe_pools.clear()
            sender = self._sender
            self._sender = None
        if sender is not None:
            self._sendq.put(None)
            sender.join(timeout=5)
        # a racing _enqueue_segment may have counted a segment the
        # (now-exiting) sender never wrote: fail it loudly so a blocked
        # _flush_sends raises instead of waiting forever
        with self._pending_cv:
            if self._pending_sends > 0:
                if self._send_error is None:
                    self._send_error = ConnectionError(
                        f"ring plane closed with {self._pending_sends} "
                        f"segment(s) unsent")
                self._pending_sends = 0
                self._pending_cv.notify_all()
        for client in clients:
            client.close()
        for stripe in stripes:
            stripe.close()

    # ------------------------------------------------------------- allreduce
    def allreduce(self, ring_id, arr, participants, *, op_average,
                  world_size, prescale=1.0, postscale=1.0, timeout=None,
                  compression="none", segment_bytes=None):
        """Pipelined ring allreduce over ``participants`` (sorted rank
        ids; must include ``self.rank``).  Joined ranks simply aren't in
        the ring — their zero stand-ins are additive identities.

        Bulk bytes travel in the tensor's native dtype (or the
        ``compression`` wire format: "int8" / "bf16" / "fp16", floats
        only); accumulation stays float64/int64 LOCAL to each rank, and
        every rank decodes the same reduced blobs, so the result is
        identical on all ranks."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)

        out_dtype = arr.dtype
        float_in = is_float_dtype(arr.dtype)
        wire_dt, acc_dtype = _wire_spec(
            arr.dtype, prescale, widen=op_average or postscale != 1.0)
        flat = arr.reshape(-1).astype(acc_dtype)
        if prescale != 1.0:
            flat = flat * prescale
        codec = (_codecs().get(compression)
                 if float_in and compression not in (None, "none") else None)
        seg = (self.segment_bytes if segment_bytes is None
               else int(segment_bytes))
        if p == 1:
            total = flat
        elif codec is not None:
            total = self._allreduce_compressed(ring_id, flat, participants,
                                               idx, codec, timeout, seg)
        else:
            total = self._allreduce_exact(ring_id, flat, participants, idx,
                                          wire_dt, acc_dtype, timeout, seg)
        if op_average:
            total = total / world_size
        if postscale != 1.0:
            total = total * postscale
        return total.astype(out_dtype).reshape(arr.shape)

    def _allreduce_exact(self, ring_id, flat, participants, idx, wire_dt,
                         acc_dtype, timeout, seg):
        """Native-wire-dtype pipelined ring.  Reduce-scatter leg: the
        running partial sum of each chunk hops the ring at wire
        precision (each hop decodes, adds its own contribution in the
        wide local accumulator, re-encodes).  Allgather leg: the chunk's
        owner encodes the reduced chunk ONCE and the rotation forwards
        the blob verbatim — every rank (owner included) decodes the
        same bytes, so the result is rank-consistent."""
        p = len(participants)
        right = participants[(idx + 1) % p]
        left = participants[(idx - 1) % p]
        chunks = np.array_split(flat, p)
        sizes = [c.size for c in chunks]
        item = wire_dt.itemsize

        # reduce-scatter: after p-1 steps this rank owns the fully
        # reduced chunk (idx+1) % p
        for s in range(p - 1):
            send_i = (idx - s) % p
            recv_i = (idx - 1 - s) % p
            out = chunks[send_i].astype(wire_dt)
            self.send_chunk(right, (ring_id, "rs", s), _as_bytes_view(out),
                            seg_bytes=seg, align=item)
            target = chunks[recv_i]

            def accumulate(offset, segment, target=target):
                lo = offset // item
                decoded = np.frombuffer(segment, dtype=wire_dt)
                target[lo:lo + decoded.size] += decoded.astype(
                    target.dtype, copy=False)

            self.recv_chunk((ring_id, "rs", s), left,
                            sizes[recv_i] * item, timeout=timeout,
                            consume=accumulate, seg_bytes=seg, align=item)

        # allgather: rotate the owner-encoded chunks p-1 times; blobs
        # forward verbatim
        owner = (idx + 1) % p
        own_wire = chunks[owner].astype(wire_dt)
        blobs = {owner: _as_bytes_view(own_wire)}
        carry = owner
        for s in range(p - 1):
            self.send_chunk(right, (ring_id, "ag", s), blobs[carry],
                            seg_bytes=seg, align=item)
            recv_owner = (idx - s) % p
            blobs[recv_owner] = self.recv_chunk(
                (ring_id, "ag", s), left, sizes[recv_owner] * item,
                timeout=timeout, seg_bytes=seg, align=item)
            carry = recv_owner
        self._flush_sends(timeout)
        return np.concatenate([
            np.frombuffer(blobs[i], dtype=wire_dt,
                          count=sizes[i]).astype(acc_dtype)
            for i in range(p)])

    def _allreduce_compressed(self, ring_id, flat, participants, idx,
                              codec, timeout, seg):
        """Compressed bulk exchange (EQuARX-style block scaling mapped
        onto the p2p transport).  Reduce-scatter leg: each rank encodes
        its contribution to every destination chunk ONCE at the source
        and ships it straight to the chunk's owner — same (p-1)/p bytes
        per rank as the classic ring's reduce-scatter, but one
        quantization per contribution instead of a requantize at every
        hop.  The owner accumulates all contributions in float64,
        encodes its reduced chunk once, and the allgather leg rotates
        the compressed blobs around the ring verbatim.  Every rank
        decodes the SAME blobs (the owner included), so the result stays
        rank-consistent like the exact ring."""
        enc, dec, enc_nbytes = codec
        p = len(participants)
        chunks = np.array_split(flat, p)
        sizes = [c.size for c in chunks]
        for d in range(p):
            if d != idx:
                self.send_chunk(participants[d], (ring_id, "qrs", d),
                                enc(np.ascontiguousarray(chunks[d])),
                                seg_bytes=seg)
        acc = chunks[idx].astype(np.float64, copy=True)
        for src_i, src in enumerate(participants):
            if src_i == idx:
                continue
            blob = self.recv_chunk((ring_id, "qrs", idx), src,
                                   enc_nbytes(sizes[idx]),
                                   timeout=timeout, seg_bytes=seg)
            acc += dec(blob, sizes[idx])
        # allgather: rotate the compressed reduced chunks p-1 times
        right = participants[(idx + 1) % p]
        left = participants[(idx - 1) % p]
        blobs = {idx: enc(np.ascontiguousarray(acc))}
        carry = idx
        for s in range(p - 1):
            self.send_chunk(right, (ring_id, "qag", s), blobs[carry],
                            seg_bytes=seg)
            recv_owner = (idx - 1 - s) % p
            blobs[recv_owner] = self.recv_chunk(
                (ring_id, "qag", s), left, enc_nbytes(sizes[recv_owner]),
                timeout=timeout, seg_bytes=seg)
            carry = recv_owner
        self._flush_sends(timeout)
        return np.concatenate([dec(blobs[i], sizes[i]) for i in range(p)])

    # -------------------------------------------- hierarchical allreduce
    def allreduce_hierarchical(self, ring_id, arr, participants, groups,
                               *, op_average, world_size, prescale=1.0,
                               postscale=1.0, timeout=None,
                               compression="none", segment_bytes=None):
        """Two-level (topology-aware) allreduce (the MLPerf TPU-pod
        schedule, arXiv:1909.09756, mapped onto the TCP plane).

        ``groups`` partitions ``participants`` into co-located sets (the
        coordinator stamps them from launcher host hashes or
        ``HVD_HIER_LOCAL_SIZE``).  Four phases:

        1. intra-group reduce-scatter — every member ships its
           contribution to each group slice straight to the slice's
           owner (one serialized round, same (g-1)/g bytes as a ring
           reduce-scatter);
        2. slice gather — members hand their reduced slice to the
           group's delegate (its min rank), which assembles the full
           group sum;
        3. delegates run the existing striped/pipelined ring
           (:meth:`_allreduce_exact` / :meth:`_allreduce_compressed`)
           across groups — rank-consistency and compression compose
           unchanged;
        4. each delegate encodes the global result ONCE and every group
           member (the delegate included) decodes the same blob, so the
           result is bitwise identical on all ranks.

        The flat ring serializes 2·(P−1) rounds; this schedule runs
        3 + 2·(G−1) rounds for G groups — the latency term the scaling
        curve collapses under (docs/tuning.md)."""
        participants = sorted(participants)
        out_dtype = arr.dtype
        float_in = is_float_dtype(arr.dtype)
        wire_dt, acc_dtype = _wire_spec(
            arr.dtype, prescale, widen=op_average or postscale != 1.0)
        flat = arr.reshape(-1).astype(acc_dtype)
        if prescale != 1.0:
            flat = flat * prescale
        codec = (_codecs().get(compression)
                 if float_in and compression not in (None, "none") else None)
        enc, dec, enc_nbytes = codec if codec else (None, None, None)
        seg = (self.segment_bytes if segment_bytes is None
               else int(segment_bytes))
        item = wire_dt.itemsize

        groups = [sorted(g) for g in groups]
        groups.sort(key=lambda g: g[0])
        group = next(g for g in groups if self.rank in g)
        g = len(group)
        gidx = group.index(self.rank)
        delegate = group[0]
        delegates = [gr[0] for gr in groups]

        # phase 1: intra-group owner-targeted reduce-scatter.  Slice d
        # of the flat vector belongs to group member d; contributions
        # are wire-encoded once at the source and accumulated wide at
        # the owner in group order (deterministic, like the star).
        chunks = np.array_split(flat, g)
        sizes = [c.size for c in chunks]
        if g > 1:
            own = chunks[gidx].astype(
                np.float64 if codec else acc_dtype, copy=True)
            for d in range(g):
                if d == gidx:
                    continue
                if codec is None:
                    out = chunks[d].astype(wire_dt)
                    self.send_chunk(group[d], (ring_id, "h1", d),
                                    _as_bytes_view(out), seg_bytes=seg,
                                    align=item)
                else:
                    self.send_chunk(group[d], (ring_id, "h1", d),
                                    enc(np.ascontiguousarray(chunks[d])),
                                    seg_bytes=seg)
            for src_i, src in enumerate(group):
                if src_i == gidx:
                    continue
                if codec is None:
                    blob = self.recv_chunk(
                        (ring_id, "h1", gidx), src, sizes[gidx] * item,
                        timeout=timeout, seg_bytes=seg, align=item)
                    own += np.frombuffer(blob, wire_dt).astype(
                        acc_dtype, copy=False)
                else:
                    blob = self.recv_chunk(
                        (ring_id, "h1", gidx), src,
                        enc_nbytes(sizes[gidx]), timeout=timeout,
                        seg_bytes=seg)
                    own += dec(blob, sizes[gidx])

            # phase 2: gather the reduced slices at the delegate
            if gidx != 0:
                if codec is None:
                    out = own.astype(wire_dt)
                    self.send_chunk(delegate, (ring_id, "h2", gidx),
                                    _as_bytes_view(out), seg_bytes=seg,
                                    align=item)
                else:
                    self.send_chunk(delegate, (ring_id, "h2", gidx),
                                    enc(np.ascontiguousarray(own)),
                                    seg_bytes=seg)
                total = None
            else:
                parts = [own]
                for i in range(1, g):
                    if codec is None:
                        blob = self.recv_chunk(
                            (ring_id, "h2", i), group[i],
                            sizes[i] * item, timeout=timeout,
                            seg_bytes=seg, align=item)
                        parts.append(np.frombuffer(blob, wire_dt).astype(
                            acc_dtype, copy=False))
                    else:
                        blob = self.recv_chunk(
                            (ring_id, "h2", i), group[i],
                            enc_nbytes(sizes[i]), timeout=timeout,
                            seg_bytes=seg)
                        parts.append(dec(blob, sizes[i]))
                total = np.concatenate(parts)
        else:
            total = flat if gidx == 0 else None

        if gidx == 0:
            # phase 3: the existing cross-group ring among delegates
            # ("rs"/"ag"/"qrs"/"qag" tags — disjoint from the "h*"
            # intra-group tags, all under this ring_id so purge() still
            # clears everything)
            if len(delegates) > 1:
                didx = delegates.index(self.rank)
                if codec is None:
                    total = self._allreduce_exact(
                        ring_id, total.astype(acc_dtype, copy=False),
                        delegates, didx, wire_dt, acc_dtype, timeout, seg)
                else:
                    total = self._allreduce_compressed(
                        ring_id, total, delegates, didx, codec, timeout,
                        seg)
            # phase 4: encode the global result ONCE; every rank in the
            # group (this delegate included) decodes the same blob, so
            # the result is bitwise identical everywhere
            if codec is None:
                wire = np.ascontiguousarray(total.astype(wire_dt))
                blob = _as_bytes_view(wire)
                for peer in group[1:]:
                    self.send_chunk(peer, (ring_id, "h3"), blob,
                                    seg_bytes=seg, align=item)
                total = wire.astype(acc_dtype)
            else:
                blob = enc(np.ascontiguousarray(total))
                for peer in group[1:]:
                    self.send_chunk(peer, (ring_id, "h3"), blob,
                                    seg_bytes=seg)
                total = dec(blob, flat.size).astype(np.float64)
        else:
            if codec is None:
                blob = self.recv_chunk(
                    (ring_id, "h3"), delegate, flat.size * item,
                    timeout=timeout, seg_bytes=seg, align=item)
                total = np.frombuffer(blob, wire_dt).astype(acc_dtype)
            else:
                blob = self.recv_chunk(
                    (ring_id, "h3"), delegate, enc_nbytes(flat.size),
                    timeout=timeout, seg_bytes=seg)
                total = dec(blob, flat.size).astype(np.float64)
        self._flush_sends(timeout)
        if op_average:
            total = total / world_size
        if postscale != 1.0:
            total = total * postscale
        return total.astype(out_dtype).reshape(arr.shape)

    # --------------------------------- recursive halving/doubling (rhd)
    def allreduce_rhd(self, ring_id, arr, participants, *, op_average,
                      world_size, prescale=1.0, postscale=1.0,
                      timeout=None, compression="none",
                      segment_bytes=None):
        """Latency-optimal small-tensor allreduce: recursive doubling
        with a fold-in step for non-power-of-two rings — O(log P)
        serialized rounds against the flat ring's 2·(P−1) and the
        coordinator star's O(P·bytes) hot spot.

        Extras (the P − 2^m highest positions) fold their vector into a
        power-of-two partner, the 2^m survivors run log2 rounds of
        pairwise full-vector exchange, and partners hand the finished
        vector back verbatim.  Every level re-encodes the local partial
        to the wire dtype and accumulates ``decode(mine) +
        decode(theirs)`` — both partners add the SAME two wire values
        (IEEE addition is commutative and deterministic), so by
        induction every rank finishes with bitwise-identical bytes.

        ``compression`` is accepted for signature parity but the wire
        stays in the native dtype: this schedule serves the
        latency-bound ≤``DEFAULT_RHD_MAX_BYTES`` regime where a
        quantization pass costs more than the bytes it saves."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        out_dtype = arr.dtype
        wire_dt, acc_dtype = _wire_spec(
            arr.dtype, prescale, widen=op_average or postscale != 1.0)
        flat = arr.reshape(-1).astype(acc_dtype)
        if prescale != 1.0:
            flat = flat * prescale
        seg = (self.segment_bytes if segment_bytes is None
               else int(segment_bytes))
        item = wire_dt.itemsize
        nbytes = flat.size * item

        if p > 1:
            m = p.bit_length() - 1        # floor(log2(p))
            pow2 = 1 << m
            if idx >= pow2:
                # extra: fold into the partner, receive the result back
                partner = participants[idx - pow2]
                out = np.ascontiguousarray(flat.astype(wire_dt))
                self.send_chunk(partner, (ring_id, "rdf"),
                                _as_bytes_view(out), seg_bytes=seg,
                                align=item)
                blob = self.recv_chunk((ring_id, "rdb"), partner, nbytes,
                                       timeout=timeout, seg_bytes=seg,
                                       align=item)
                flat = np.frombuffer(blob, wire_dt).astype(acc_dtype)
            else:
                if idx + pow2 < p:
                    blob = self.recv_chunk(
                        (ring_id, "rdf"), participants[idx + pow2],
                        nbytes, timeout=timeout, seg_bytes=seg,
                        align=item)
                    flat = flat + np.frombuffer(blob, wire_dt).astype(
                        acc_dtype, copy=False)
                for k in range(m):
                    partner = participants[idx ^ (1 << k)]
                    mine = np.ascontiguousarray(flat.astype(wire_dt))
                    self.send_chunk(partner, (ring_id, "rd", k),
                                    _as_bytes_view(mine), seg_bytes=seg,
                                    align=item)
                    blob = self.recv_chunk(
                        (ring_id, "rd", k), partner, nbytes,
                        timeout=timeout, seg_bytes=seg, align=item)
                    # decode(mine) + decode(theirs): both partners sum
                    # the same wire values -> bitwise-equal partials
                    flat = (mine.astype(acc_dtype) +
                            np.frombuffer(blob, wire_dt).astype(
                                acc_dtype, copy=False))
                # adopt the final wire encoding on EVERY survivor (not
                # just partners of extras) so extras' decoded copies and
                # survivors' accumulators agree bitwise before any
                # average/postscale math
                wfin = np.ascontiguousarray(flat.astype(wire_dt))
                if idx + pow2 < p:
                    self.send_chunk(participants[idx + pow2],
                                    (ring_id, "rdb"),
                                    _as_bytes_view(wfin), seg_bytes=seg,
                                    align=item)
                flat = wfin.astype(acc_dtype)
            self._flush_sends(timeout)
        if op_average:
            flat = flat / world_size
        if postscale != 1.0:
            flat = flat * postscale
        return flat.astype(out_dtype).reshape(arr.shape)

    # -------------------------------------------------------- reduce_scatter
    def reduce_scatter(self, ring_id, arr, participants, *, op_average,
                       world_size, prescale=1.0, postscale=1.0,
                       timeout=None, compression="none",
                       segment_bytes=None):
        """First-class reduce-scatter: the ring allreduce's reduce-scatter
        half, exposed on its own (the ZeRO decomposition's first stage).
        Chunk boundaries sit at FIRST-DIMENSION rows, partitioned
        np.array_split style (``reduce_scatter_split_sizes``), and the
        rank at position ``idx`` of the sorted participants receives
        chunk ``idx`` — unlike the fused allreduce's internal leg, whose
        element-granular chunks land one position rotated.  Returns this
        rank's reduced row block in the input dtype."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)

        out_dtype = arr.dtype
        rest = arr.shape[1:]
        counts = reduce_scatter_split_sizes(arr.shape[0], p)
        row = int(np.prod(rest or (1,)))
        sizes = [c * row for c in counts]
        bounds = np.cumsum([0] + sizes)

        float_in = is_float_dtype(arr.dtype)
        wire_dt, acc_dtype = _wire_spec(
            arr.dtype, prescale, widen=op_average or postscale != 1.0)
        flat = arr.reshape(-1).astype(acc_dtype)
        if prescale != 1.0:
            flat = flat * prescale
        codec = (_codecs().get(compression)
                 if float_in and compression not in (None, "none") else None)
        seg = (self.segment_bytes if segment_bytes is None
               else int(segment_bytes))
        chunks = [flat[bounds[i]:bounds[i + 1]] for i in range(p)]
        if p == 1:
            own = chunks[0]
        elif codec is not None:
            own = self._reduce_scatter_compressed(
                ring_id, chunks, sizes, participants, idx, codec, timeout,
                seg)
        else:
            own = self._reduce_scatter_exact(
                ring_id, chunks, sizes, participants, idx, wire_dt, timeout,
                seg)
        if op_average:
            own = own / world_size
        if postscale != 1.0:
            own = own * postscale
        return own.astype(out_dtype).reshape((counts[idx],) + rest)

    def _reduce_scatter_exact(self, ring_id, chunks, sizes, participants,
                              idx, wire_dt, timeout, seg):
        """The pipelined ring's reduce-scatter leg, shifted one chunk so
        rank ``idx`` ends up owning chunk ``idx`` (the fused allreduce
        leaves rank ``idx`` holding chunk ``(idx+1) % p``): at step ``s``
        send the running partial of chunk ``(idx-1-s) % p`` rightward and
        accumulate chunk ``(idx-2-s) % p`` from the left."""
        p = len(participants)
        right = participants[(idx + 1) % p]
        left = participants[(idx - 1) % p]
        item = wire_dt.itemsize
        for s in range(p - 1):
            send_i = (idx - 1 - s) % p
            recv_i = (idx - 2 - s) % p
            out = chunks[send_i].astype(wire_dt)
            self.send_chunk(right, (ring_id, "rs", s), _as_bytes_view(out),
                            seg_bytes=seg, align=item)
            target = chunks[recv_i]

            def accumulate(offset, segment, target=target):
                lo = offset // item
                decoded = np.frombuffer(segment, dtype=wire_dt)
                target[lo:lo + decoded.size] += decoded.astype(
                    target.dtype, copy=False)

            self.recv_chunk((ring_id, "rs", s), left,
                            sizes[recv_i] * item, timeout=timeout,
                            consume=accumulate, seg_bytes=seg, align=item)
        self._flush_sends(timeout)
        return chunks[idx]

    def _reduce_scatter_compressed(self, ring_id, chunks, sizes,
                                   participants, idx, codec, timeout, seg):
        """The compressed allreduce's owner-targeted reduce-scatter half
        without the allgather rotation: each rank encodes its
        contribution to every destination chunk ONCE and ships it
        straight to the chunk's owner, who accumulates in float64 — one
        quantization per contribution, same wire format as the fused
        path."""
        enc, dec, enc_nbytes = codec
        p = len(participants)
        for d in range(p):
            if d != idx:
                self.send_chunk(participants[d], (ring_id, "qrs", d),
                                enc(np.ascontiguousarray(chunks[d])),
                                seg_bytes=seg)
        acc = chunks[idx].astype(np.float64, copy=True)
        for src_i, src in enumerate(participants):
            if src_i == idx:
                continue
            blob = self.recv_chunk((ring_id, "qrs", idx), src,
                                   enc_nbytes(sizes[idx]),
                                   timeout=timeout, seg_bytes=seg)
            acc += dec(blob, sizes[idx])
        self._flush_sends(timeout)
        return acc

    # ----------------------------------------------------- seed reference
    def allreduce_seed(self, ring_id, arr, participants, *, op_average,
                       world_size, prescale=1.0, postscale=1.0,
                       timeout=None):
        """The seed-era exact ring, verbatim: float64/int64 accumulator
        bytes on the wire, strictly serial whole-chunk blocking steps on
        the control connection.  Kept as the measured baseline for the
        pipelined plane (bench leg ``allreduce_gbs_ring_pipelined``) and
        as the oracle for the parity matrix — NOT used in production."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)

        out_dtype = arr.dtype
        float_in = is_float_dtype(arr.dtype)
        acc_dtype = np.float64 if float_in else np.int64
        flat = arr.reshape(-1).astype(acc_dtype)
        if prescale != 1.0:
            flat = flat * prescale
        if p == 1:
            total = flat
        else:
            right = participants[(idx + 1) % p]
            left = participants[(idx - 1) % p]
            chunks = np.array_split(flat, p)
            for s in range(p - 1):
                send_i = (idx - s) % p
                recv_i = (idx - 1 - s) % p
                self.send(right, ((ring_id, "rs", s)),
                          np.ascontiguousarray(chunks[send_i]).tobytes())
                data = self.recv(((ring_id, "rs", s)), left, timeout=timeout)
                chunks[recv_i] = chunks[recv_i] + np.frombuffer(
                    data, dtype=acc_dtype)
            for s in range(p - 1):
                send_i = (idx + 1 - s) % p
                recv_i = (idx - s) % p
                self.send(right, ((ring_id, "ag", s)),
                          np.ascontiguousarray(chunks[send_i]).tobytes())
                data = self.recv(((ring_id, "ag", s)), left, timeout=timeout)
                chunks[recv_i] = np.frombuffer(data, dtype=acc_dtype)
            total = np.concatenate(chunks)
        if op_average:
            total = total / world_size
        if postscale != 1.0:
            total = total * postscale
        return total.astype(out_dtype).reshape(arr.shape)

    # --------------------------------------------------------------- adasum
    def adasum(self, ring_id, arr, participants, *, timeout=None,
               segment_bytes=None):
        """Distributed Adasum vector-halving distance-doubling
        (reference: ``Adasum<Communicator_type>::FusedAllreduce``,
        ``adasum/adasum.h:194-330``) over the p2p plane — no rank-0
        payload hotspot: per-rank traffic is ~2|x| halves plus 24-byte
        scalar rounds.

        At level ``k`` this rank exchanges half of its current piece
        with ``participants[idx ^ 2^k]``; the dot/norm scalars of the
        two logical vectors (distributed over the ``2^(k+1)``-rank
        group) are star-reduced through the group's lowest rank (the
        reference's per-level ``reduction_comms``); coefficients
        combine the halves.  After ``log2(p)`` levels each rank holds
        ``1/p`` of the result at bit-reversed chunk order; a block
        gather + static permutation rebuilds the full vector — same
        algebra as :func:`horovod_tpu.ops.adasum.adasum_vhdd`, which the
        numpy oracle validates.

        Accumulation and the scalar reductions stay float64 locally;
        the exchanged halves and gathered blocks wire the array's
        NATIVE dtype (floats) — the gather rotates each rank's
        once-encoded piece verbatim, so the rebuilt vector is
        rank-consistent.

        ``participants`` must be ALL world ranks (the coordinator
        falls back to the payload path when ranks have joined) and a
        power of two.
        """
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        if p & (p - 1):
            raise ValueError(
                f"ring Adasum requires power-of-two ranks, got {p}")
        out_dtype = arr.dtype
        shape = arr.shape
        size = arr.size
        if p == 1:
            return arr
        seg = (self.segment_bytes if segment_bytes is None
               else int(segment_bytes))
        wire_dt = (np.dtype(arr.dtype) if is_float_dtype(arr.dtype)
                   else np.dtype(np.float64))
        item = wire_dt.itemsize
        padded = -(-size // p) * p
        piece = np.zeros(padded, np.float64)
        piece[:size] = arr.reshape(-1).astype(np.float64)

        dist = 1
        level = 0
        while dist < p:
            half = piece.size // 2
            low, high = piece[:half], piece[half:]
            bit = (idx // dist) % 2
            send_half, mine = (high, low) if bit == 0 else (low, high)
            peer = participants[idx ^ dist]
            self.send_chunk(peer, (ring_id, "ad", level),
                            _as_bytes_view(send_half.astype(wire_dt)),
                            seg_bytes=seg)
            recv = np.frombuffer(
                self.recv_chunk((ring_id, "ad", level), peer,
                                half * item, timeout=timeout,
                                seg_bytes=seg),
                dtype=wire_dt).astype(np.float64)
            # a = the lower sub-group's vector piece, b = the upper's —
            # fixed roles so every group member reduces the same scalars
            a, b = (mine, recv) if bit == 0 else (recv, mine)
            partial = np.array([a @ b, a @ a, b @ b])

            group = [r for r in range(p)
                     if r // (2 * dist) == idx // (2 * dist)]
            leader = group[0]
            if idx == leader:
                total = partial.copy()
                for member in group[1:]:
                    total += np.frombuffer(self.recv(
                        ((ring_id, "adp", level)),
                        participants[member], timeout=timeout), np.float64)
                blob = np.ascontiguousarray(total).tobytes()
                for member in group[1:]:
                    self.send(participants[member],
                              ((ring_id, "ads", level)), blob)
            else:
                self.send(participants[leader], ((ring_id, "adp", level)),
                          np.ascontiguousarray(partial).tobytes())
                total = np.frombuffer(self.recv(
                    ((ring_id, "ads", level)), participants[leader],
                    timeout=timeout), np.float64)
            dot, na, nb = total
            a_coeff = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
            b_coeff = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
            piece = a_coeff * a + b_coeff * b
            dist *= 2
            level += 1

        # block gather (ring rotation) of the NATIVE-dtype pieces, then
        # undo the bit-reversed chunk order the halving walk leaves
        # behind (adasum.py:150-153).  Every rank decodes each piece
        # from the same once-encoded blob — its own included.
        own_wire = piece.astype(wire_dt)
        blocks = {idx: _as_bytes_view(own_wire)}
        block_nbytes = piece.size * item
        right = participants[(idx + 1) % p]
        left = participants[(idx - 1) % p]
        carry = idx
        for s in range(p - 1):
            self.send_chunk(right, (ring_id, "adg", s), blocks[carry],
                            seg_bytes=seg)
            recv_owner = (idx - 1 - s) % p
            blocks[recv_owner] = self.recv_chunk(
                (ring_id, "adg", s), left, block_nbytes, timeout=timeout,
                seg_bytes=seg)
            carry = recv_owner
        self._flush_sends(timeout)
        levels = p.bit_length() - 1
        order = [int(format(i, f"0{levels}b")[::-1], 2) for i in range(p)]
        full = np.concatenate([
            np.frombuffer(blocks[order[i]], dtype=wire_dt).astype(
                np.float64)
            for i in range(p)])
        return full[:size].reshape(shape).astype(out_dtype)

    # ------------------------------------------------------------- broadcast
    def broadcast(self, ring_id, arr_or_none, participants, root, *,
                  shape, dtype, timeout=None, segment_bytes=None):
        """Segmented pipeline around the ring rooted at ``root``: every
        rank receives each segment once from its left neighbor and
        forwards it once to its right AS IT ARRIVES — the root uploads
        the tensor exactly once, in its native dtype, and hop latency
        overlaps across segments."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        root_idx = participants.index(root)
        right = participants[(idx + 1) % p]
        nbytes = int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
        seg = (self.segment_bytes if segment_bytes is None
               else int(segment_bytes)) or BCAST_CHUNK

        if self.rank == root:
            data = np.ascontiguousarray(arr_or_none)
            if p > 1:
                self.send_chunk(right, (ring_id, "bc"),
                                _as_bytes_view(data), seg_bytes=seg)
            data = data.tobytes()
        else:
            left = participants[(idx - 1) % p]
            last = (idx + 1) % p == root_idx  # my right neighbor is root
            forward = not last and not faults.check("send")
            pieces = []

            def relay(offset, segment, seg_i=[0]):
                if forward:
                    self._enqueue_segment(right, seg_i[0],
                                          (ring_id, "bc", seg_i[0]),
                                          segment)
                seg_i[0] += 1
                pieces.append(segment)

            self.recv_chunk((ring_id, "bc"), left, nbytes,
                            timeout=timeout, consume=relay, seg_bytes=seg)
            data = (bytes(pieces[0]) if len(pieces) == 1
                    else b"".join(pieces))
        if p > 1:
            self._flush_sends(timeout)
        return np.frombuffer(data, dtype=np.dtype(dtype)).reshape(shape)

    # ------------------------------------------------------------- allgather
    def allgather(self, ring_id, arr, participants, *, block_nbytes=None,
                  timeout=None, segment_bytes=None):
        """Ring block rotation: each step forwards the block received the
        previous step; after p-1 steps every rank holds every block.
        Returns the blocks (bytes) in participant rank order — blocks
        travel as raw native-dtype bytes, segmented across the stripes;
        ``block_nbytes`` gives each participant's block size (negotiated
        out-of-band by the coordinator; None falls back to unsegmented
        single-frame rotation for callers that don't know the sizes)."""
        participants = sorted(participants)
        p = len(participants)
        idx = participants.index(self.rank)
        blocks = {self.rank: np.ascontiguousarray(arr).tobytes()}
        if p > 1:
            right = participants[(idx + 1) % p]
            left = participants[(idx - 1) % p]
            seg = ((self.segment_bytes if segment_bytes is None
                    else int(segment_bytes))
                   if block_nbytes is not None else 0)
            sizes = (dict(zip(participants, block_nbytes))
                     if block_nbytes is not None else None)
            carry_owner = self.rank
            for s in range(p - 1):
                self.send_chunk(right, (ring_id, "ag", s),
                                blocks[carry_owner], seg_bytes=seg)
                recv_owner = participants[(idx - 1 - s) % p]
                nbytes = (sizes[recv_owner] if sizes is not None
                          else len(blocks[carry_owner]))
                blocks[recv_owner] = self.recv_chunk(
                    (ring_id, "ag", s), left, nbytes, timeout=timeout,
                    seg_bytes=seg)
                carry_owner = recv_owner
            self._flush_sends(timeout)
        return [blocks[r] for r in participants]
